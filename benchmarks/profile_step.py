"""Measured per-op profile of the flagship GPT train step.

The round's MFU question — *which op eats the step time?* — answered by
``apex_tpu.monitor.report.step_report``: run the bench.py train step under
``jax.profiler``, join per-instruction measured time with HLO flops/bytes
AND bytes-on-wire, print the per-op table (stderr, human) plus ONE
machine-parseable JSON line (stdout — the ``bench_comm.py`` convention,
schema-stamped by ``monitor.sink.json_record``).

Run: ``python benchmarks/profile_step.py [--steps N] [--top N]``.
Uses the real TPU when the tunnel answers (full bench shape); otherwise
falls back to the CPU protocol at a small shape, flagged in the header.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--remat", action="store_true",
                    help="profile the remat=dots config instead of no-remat")
    args = ap.parse_args()

    from apex_tpu.utils.platform import (
        pin_cpu_if_requested,
        pin_cpu_if_tunnel_dead,
    )

    pin_cpu_if_requested()
    pin_cpu_if_tunnel_dead()
    backend = jax.default_backend()
    on_tpu = backend == "tpu"

    import bench
    from apex_tpu.monitor import (
        gpt_analytic_flops_per_token,
        json_record,
        step_report,
    )
    from apex_tpu.pyprof import format_measured_table

    batch, seq = (bench.BATCH, bench.SEQ) if on_tpu else (2, 128)
    # profile the lightest remat that fits: no-remat (the MFU operating
    # point) unless it OOMs, then selective-dots, then full — a failed
    # stage-4 fire must not waste a tunnel window. The probe runs through
    # the same non-donating wrapper the profiler jits (wrapping the jitted
    # step inlines it WITHOUT donate_argnums, so repeated profiled calls
    # reuse the param buffers; same function object -> same jit cache
    # entry, so the probe's compile is the profiler's compile).
    tries = ([(True, "dots"), (True, "full")] if args.remat
             else [(False, "full"), (True, "dots"), (True, "full")])
    last = None
    for remat, policy in tries:
        cfg = bench.flagship_config(seq, remat=remat, remat_policy=policy)
        train_step, params, opt_state, tok, tgt = bench.build_train_step(
            cfg, batch, seq)

        # everything the step produces is returned — returning only the
        # loss would let XLA dead-code-eliminate the optimizer update
        def step(params, opt_state, tok, tgt, _ts=train_step):
            return _ts(params, opt_state, tok, tgt)

        try:
            out = jax.jit(step)(params, opt_state, tok, tgt)
            jax.block_until_ready(jax.tree.leaves(out)[0])
            args.remat = remat
            break
        except Exception as e:  # OOM at this config — drop a tier
            last = e
            print(f"# remat={remat}/{policy} failed "
                  f"({type(e).__name__}), trying next", flush=True)
    else:
        raise RuntimeError(f"no profiling config fit: {last}")

    peak = bench.PEAK_FLOPS.get(backend, 1e12)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    flops_step = gpt_analytic_flops_per_token(
        n_params, cfg.num_layers, cfg.hidden, seq) * batch * seq
    header = (f"flagship GPT step profile | backend={backend}"
              f"{'' if on_tpu else ' (CPU_FALLBACK)'} | batch={batch} "
              f"seq={seq} remat={args.remat}")
    print(header, file=sys.stderr)
    rep = step_report(step, params, opt_state, tok, tgt,
                      steps=args.steps, depth=args.depth, peak_flops=peak,
                      analytic_flops_per_step=flops_step)
    # human table on stderr; the one-line contract owns stdout
    print(format_measured_table(
        {"rows": rep.pop("rows"), "unattributed": rep.pop("unattributed"),
         "total_ms_per_step": rep["step_time_ms"],
         "coverage_pct": rep["coverage_pct"]}, top=args.top),
        file=sys.stderr, flush=True)
    name = "gpt2_124m_step_profile"
    if not on_tpu:
        name += "_CPU_FALLBACK"
    print(json_record(metric=name, batch=batch, seq=seq,
                      remat=bool(args.remat), **rep), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

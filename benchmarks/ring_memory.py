"""Quantify ring sequence parallelism's long-context memory advantage.

The reference's only sequence-length tools are activation-checkpoint
sharding and the sk<=2048 fused softmax (SURVEY §5 long-context row); the
TPU build's north star adds ring attention (``transformer/
sequence_parallel.py``) so context scales by adding chips. This script
pins that claim with XLA's buffer assignment (``memory_analysis()``) —
the same methodology as ``pipeline_memory.py`` — instead of asserting it:

* dense single-device attention at seq S: the (b·h, S, S) score temps
  dominate and grow O(S²);
* the ring at sp=8: each device holds S/8 of the sequence and the
  per-step (S/8, S/8) chunk scores, so temps grow O(S²/sp²) per device
  (the p2p K/V chunks add O(S/sp)).

Numbers are WHOLE-MESH totals over the 8 virtual CPU devices (one buffer
assignment; per-device = total/8 for evenly-sharded programs). The dense
leg is compile-only — a 16k dense backward would need tens of GB — which
is exactly the point. Run: ``python benchmarks/ring_memory.py``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_platform

pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import build_mesh
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    replicate_loss,
)
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_param_specs,
    init_gpt_params,
)

# flagship-width attention block at long context; depth trimmed so the
# dense leg's compile stays tractable on a small box
HID, HEADS, LAYERS, VOCAB, BATCH = 768, 12, 2, 1024, 1


def build_case(seq: int, sp: int, tp: int = 1, megatron_sp: bool = False,
               remat: bool = True):
    """-> compiled fwd+bwd loss for the GPT stack at (seq, sp, tp)."""
    mesh = build_mesh(tp=tp, pp=1, sp=sp, dp=8 // (sp * tp))
    cfg = GPTConfig(vocab_size=VOCAB, max_seq=seq, hidden=HID,
                    num_layers=LAYERS, num_heads=HEADS, dtype=jnp.bfloat16,
                    tie_embeddings=True, remat=remat,
                    megatron_sp=megatron_sp)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((BATCH, seq), jnp.int32)
    targets = jnp.zeros((BATCH, seq), jnp.int32)

    def loss_fn(p, tok, tgt):
        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(gpt_param_specs(cfg), P(None, "sp"), P(None, "sp")),
            out_specs=P())(p, tok, tgt)

    def step(p, tok, tgt):
        return jax.grad(lambda p: loss_fn(p, tok, tgt))(p)

    return jax.jit(step).lower(params, tokens, targets).compile()


def measure(seq: int, sp: int, tp: int = 1, megatron_sp: bool = False,
            remat: bool = True):
    c = build_case(seq, sp, tp=tp, megatron_sp=megatron_sp, remat=remat)
    ma = c.memory_analysis()
    return {
        "seq": seq, "sp": sp, "tp": tp, "megatron_sp": megatron_sp,
        "remat": remat,
        "temp_mb": round(ma.temp_size_in_bytes / 1e6, 1),
        "peak_mb": round(ma.peak_memory_in_bytes / 1e6, 1),
        "temp_mb_per_dev": round(ma.temp_size_in_bytes / 8 / 1e6, 1),
    }


def main() -> int:
    rows = []
    for seq, sp, kw in ((4096, 1, {}), (4096, 8, {}), (8192, 1, {}),
                        (8192, 8, {}), (16384, 8, {}), (32768, 8, {}),
                        # Megatron-SP A/B at ring sp=4 x tp=2, remat OFF
                        # so saved activations (what Megatron-SP shards:
                        # LN/dropout/residual regions run on
                        # (b, s/(sp*tp), h) shards instead of
                        # tp-replicated (b, s/sp, h)) dominate the temps;
                        # under full remat the delta is noise
                        (8192, 4, {"tp": 2, "remat": False}),
                        (8192, 4, {"tp": 2, "remat": False,
                                   "megatron_sp": True})):
        try:
            r = measure(seq, sp, **kw)
        except Exception as e:  # dense legs can exhaust the compiler
            r = {"seq": seq, "sp": sp,
                 "error": f"{type(e).__name__}: {str(e)[:120]}"}
        rows.append(r)
        print(json.dumps(r), flush=True)

    ok = {(r["seq"], r["sp"]): r for r in rows if "temp_mb" in r}
    d8, r8 = ok.get((8192, 1)), ok.get((8192, 8))
    if d8 and r8:
        print(f"# seq 8192: dense temps {d8['temp_mb']:.0f} MB vs ring@sp=8 "
              f"{r8['temp_mb']:.0f} MB total "
              f"({r8['temp_mb_per_dev']:.0f} MB/device, "
              f"{d8['temp_mb'] / max(r8['temp_mb'], 1e-9):.1f}x less)")
    r16, r32 = ok.get((16384, 8)), ok.get((32768, 8))
    if r16 and r32:
        print(f"# ring scaling 16k->32k: temps {r16['temp_mb']:.0f} -> "
              f"{r32['temp_mb']:.0f} MB "
              f"({r32['temp_mb'] / max(r16['temp_mb'], 1e-9):.2f}x for 2x "
              f"seq; O(S^2/sp) chunk scores dominate at this width)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Multi-host disaggregated serving benchmark — goodput, shed, transfer.

The ROADMAP item-2 deliverable: drive ``apex_tpu.serve.cluster`` —
SLO-aware router → prefill hosts → KV-block transfer → decode hosts —
with the PR-6 closed-loop load generator (Poisson arrivals + bursts +
long-tail lengths + multi-tenant tags) at ≥ 2 simulated hosts and emit
ONE ``json_record`` line with:

* **goodput-under-SLO** (req/s meeting every latency budget), TTFT/TPOT
  p50/p99 from the merged streaming histograms, violation counts;
* **shed accounting** — ``shed_rate`` and per-tenant counters from the
  router's explicit load-shedding path, plus an ``overload`` sub-record
  from a second pass at ``--overload-factor``× the offered rate (arrival
  times compressed) showing graceful degradation: sheds recorded, kept
  traffic still inside budget, never a deadlock;
* **transfer wire accounting** — measured bytes shipped over the
  simulated transport, asserted byte-for-byte against the
  ``transfer_wire_bytes`` model (the ``comm.accounting`` convention);
  disagreement makes the record ``ok: false`` and ``tpu_watch.sh``
  stage 15 refuses to bank it;
* a **disaggregated-vs-colocated A/B**: the same workload through one
  colocated engine with the same total decode slots, so the record
  carries what the split bought (or cost) on this hardware.

``--chaos`` adds the ISSUE-13 **goodput-under-chaos** pass: the same
workload at ``--overload-factor``× (min 2×) with 1 of N decode workers
KILLED at ``--chaos-kill-step`` — its live requests migrate to the
survivors over the KV wire and the record carries
``goodput_under_chaos_rps`` / ``survivor_good_fraction`` (higher-better)
next to the recovery-noise counters (``migrations_total`` /
``replayed_tokens`` / ``worker_deaths`` / ``heartbeat_misses`` /
``transfer_retries``, lower-better). A chaos pass that fails to drain or
whose kill did not land makes the record ``ok: false``.

``--lora`` adds the PR-16 **per-tenant adapter A/B**: the same tenant
mix with every tenant bound to a LoRA adapter (loadgen's fixed
``t{i} -> ad{i % M}`` mapping) through an adapter-enabled fleet — the
record carries tokens/s + TTFT p99 next to the adapter-free pass, the
registry ``adapter_hit_rate`` and the router ``adapter_warm_dispatch_
rate`` (higher-better), ``adapter_load_ms`` / ``adapter_evictions``
(lower-better), and ``streams_equal``: the aid=0 cohort replayed
through both fleets must match BITWISE or the record is ``ok: false``.

``--plan {tp,pp,fsdp,all}`` swaps the cluster for the ISSUE-20
**plan-sharded serving pass**: one ``ParallelismPlan``-driven engine
(``apex_tpu.serve.sharded``) on a device slice, emitting the
>1-chip-HBM headline — a model whose ``hbm_model_bytes`` EXCEEDS one
simulated chip's budget (default: the midpoint of the plan-resident
and single-chip totals; the record carries all three numbers) still
serving the workload under the same SLO — next to the strategy's own
accounting (``weight_gather_ms`` + modeled wire bytes for fsdp,
``pp_bubble_fraction`` measured-vs-modeled for pp, the per-chip
residency cut for tp) and a monolithic-oracle stream pin
(``streams_equal`` — an undrained run or a stream mismatch makes the
record ``ok: false``). ``--plan all`` drives every strategy and the
flat gate fields take the worst case.

Run: ``python benchmarks/bench_serve_mh.py [--hosts 2] [--wire-mode
int8] [--out FILE]``. ``tpu_watch.sh`` stage 15 banks
``SERVE_MH_TPU.json`` from ``--hosts 2``, regression-gated via
``python -m apex_tpu.monitor.regress --tol 0.15``; CPU rehearsals carry
``_CPU_FALLBACK`` and never promote. Stage 18 banks
``SERVE_CHAOS_TPU.json`` from ``--hosts 3 --chaos``, stage 20 banks
``SERVE_LORA_TPU.json`` from ``--lora``, stage 24 banks
``SERVE_PLAN_TPU.json`` from ``--plan all``, all under the same promote
rules.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import argparse

    from apex_tpu.utils.platform import (
        pin_cpu_if_requested,
        pin_cpu_if_tunnel_dead,
        pin_cpu_platform,
    )

    pin_cpu_if_requested()
    pin_cpu_if_tunnel_dead()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        pin_cpu_platform()

    # the --plan pass shards a model over a device slice; a CPU rehearsal
    # only has the virtual devices it asks for, and the flag must land
    # before jax initializes the backend
    argv_probe = sys.argv[1:] if argv is None else list(argv)
    if (any(a == "--plan" or a.startswith("--plan=") for a in argv_probe)
            and os.environ.get("JAX_PLATFORMS") == "cpu"
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")

    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())  # after the pin: backend is final

    from apex_tpu.monitor import SloSpec, json_record
    from apex_tpu.serve import (
        ClusterConfig,
        InferenceEngine,
        RouterConfig,
        ServeCluster,
        ServeConfig,
        transfer_wire_bytes,
    )
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from loadgen import WorkloadConfig, build_workload, run_workload

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--hosts", type=int, default=2,
                    help="total simulated hosts; split prefill/decode "
                         "(2 -> 1+1, 4 -> 2+2)")
    ap.add_argument("--prefill-hosts", type=int, default=None,
                    help="override the prefill side of the split")
    ap.add_argument("--decode-hosts", type=int, default=None,
                    help="override the decode side of the split")
    ap.add_argument("--n-requests", type=int, default=64)
    ap.add_argument("--rate-rps", type=float, default=8.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kv-quant", default="none",
                    choices=["none", "int8", "int4"])
    ap.add_argument("--wire-mode", default="raw", choices=["raw", "int8"])
    ap.add_argument("--spec-k", type=int, default=0)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--megakernel", default="auto",
                    choices=["auto", "on", "off"])
    ap.add_argument("--n-tenants", type=int, default=2)
    ap.add_argument("--tenant-weights", default="3,1",
                    help="comma-separated WFQ weights, one per tenant")
    ap.add_argument("--ttft-budget", type=float, default=2000.0)
    ap.add_argument("--tpot-budget", type=float, default=200.0)
    ap.add_argument("--queue-budget", type=float, default=1000.0)
    ap.add_argument("--overload-factor", type=float, default=2.0,
                    help="second pass at this multiple of the offered "
                         "rate (0: skip) — the graceful-degradation "
                         "evidence")
    ap.add_argument("--chaos", action="store_true",
                    help="third pass: kill 1 of N decode workers at "
                         "--chaos-kill-step while running at "
                         "--overload-factor x — emits the goodput-under-"
                         "chaos fields (needs >= 2 decode hosts)")
    ap.add_argument("--chaos-kill-step", type=int, default=12,
                    help="cluster tick the chaos kill fires at (early "
                         "enough that even a hard-shedding overload run "
                         "is still mid-flight)")
    ap.add_argument("--link-fixed-ms", type=float, default=0.0)
    ap.add_argument("--link-gib-per-s", type=float, default=0.0,
                    help="simulated link bandwidth (0: instant)")
    ap.add_argument("--lora", action="store_true",
                    help="per-tenant LoRA A/B (PR-16): the same workload "
                         "with every tenant bound to an adapter, through "
                         "an adapter-enabled fleet — emits adapter hit/"
                         "warm-dispatch rates and asserts the aid=0 "
                         "cohort streams BITWISE the adapter-free fleet")
    ap.add_argument("--lora-rank", type=int, default=8)
    ap.add_argument("--n-adapters", type=int, default=None,
                    help="distinct adapters ad0..ad{M-1} (default: one "
                         "per tenant)")
    ap.add_argument("--plan", default=None,
                    choices=["tp", "pp", "fsdp", "all"],
                    help="plan-sharded serving pass (serve.sharded, "
                         "ISSUE-20): ONE model-parallel engine on a "
                         "device slice instead of the disaggregated "
                         "cluster — emits the >1-chip-HBM headline "
                         "(hbm_model_bytes vs a simulated per-chip "
                         "budget), goodput under the same SLO, gather/"
                         "bubble accounting and a monolithic-oracle "
                         "stream pin")
    ap.add_argument("--plan-world", type=int, default=None,
                    help="chips the plan spans (default tp=4, pp=2, "
                         "fsdp=8)")
    ap.add_argument("--chip-hbm-mb", type=float, default=0.0,
                    help="simulated per-chip HBM budget in MiB; 0 = the "
                         "midpoint of the plan-resident and single-chip "
                         "totals (the record carries all three numbers, "
                         "so the arithmetic is inspectable)")
    args = ap.parse_args(argv)

    if args.hosts < 2:
        ap.error("--hosts must be >= 2 (that is the point)")
    n_prefill = args.prefill_hosts or max(1, args.hosts // 2)
    n_decode = args.decode_hosts or max(1, args.hosts - n_prefill)
    if args.chaos and n_decode < 2:
        ap.error("--chaos kills a decode worker mid-run: it needs >= 2 "
                 "decode hosts to have a survivor (use --hosts 3)")

    on_tpu = jax.default_backend() == "tpu"
    if args.chaos:
        name = "gpt_serve_mh_chaos_goodput"
    elif args.lora:
        name = "gpt_serve_mh_lora_goodput"
    else:
        name = "gpt_serve_mh_goodput"
    if not on_tpu:
        name += "_CPU_FALLBACK"

    # the pinned bench model (bench_serve.py / loadgen canary constants)
    HIDDEN, LAYERS, HEADS, VOCAB, MAX_SEQ = 128, 2, 8, 512, 256
    SLOTS, BLOCK_SIZE = 4, 16
    cfg = GPTConfig(vocab_size=VOCAB, max_seq=MAX_SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS,
                    dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)

    weights = tuple(float(w) for w in args.tenant_weights.split(","))
    if len(weights) != args.n_tenants:
        ap.error("--tenant-weights must list one weight per tenant")
    wcfg = WorkloadConfig(n_requests=args.n_requests, rate_rps=args.rate_rps,
                          seed=args.seed, prompt_len_max=MAX_SEQ // 2,
                          n_tenants=args.n_tenants, tenant_weights=weights)
    workload = build_workload(wcfg, VOCAB, MAX_SEQ)
    slo = SloSpec(ttft_ms=args.ttft_budget, tpot_ms=args.tpot_budget,
                  queue_ms=args.queue_budget)
    scfg = ServeConfig(num_slots=SLOTS, block_size=BLOCK_SIZE,
                       kv_quant=args.kv_quant,
                       prefill_chunk=args.prefill_chunk,
                       spec_k=args.spec_k, megakernel=args.megakernel,
                       prefix_cache=False)
    # -- plan-sharded serving pass (ISSUE-20, stage 24) -------------------
    # ONE ParallelismPlan-driven engine on a device slice instead of the
    # disaggregated cluster: the record's headline is residency — a model
    # whose hbm_model_bytes EXCEEDS one simulated chip's budget still
    # serving the workload under the same SLO — next to the strategy's
    # own accounting (weight_gather_ms / pp_bubble_fraction) and a
    # monolithic-oracle stream pin (transparency, not tolerance).
    if args.plan:
        import dataclasses as _dc

        from apex_tpu.fsdp.accounting import hbm_serve_bytes
        from apex_tpu.parallel import ParallelismPlan
        from apex_tpu.serve import Request as _Req, build_engine
        from apex_tpu.serve.kv_cache import kv_cache_bytes

        name = "gpt_serve_plan_goodput"
        if not on_tpu:
            name += "_CPU_FALLBACK"
        worlds = {"tp": 4, "pp": 2, "fsdp": 8}

        oracle = InferenceEngine(params, cfg, scfg, retain_streams=False)
        cohort = [_Req(f"eq{i}", list(r.tokens),
                       max_new_tokens=min(r.max_new_tokens, 8),
                       tenant=r.tenant)
                  for i, (_, r) in enumerate(workload[:6])]
        oracle_streams = oracle.run(cohort)
        single_total = hbm_serve_bytes(
            params, strategy="single", world=1,
            kv_bytes=kv_cache_bytes(oracle.kv_cfg))["total"]

        def plan_pass(strategy):
            world = args.plan_world or worlds[strategy]
            if world > jax.device_count():
                return {"strategy": strategy, "ok": False,
                        "reason": f"plan spans {world} chips, have "
                                  f"{jax.device_count()}"}
            plan = {"tp": lambda: ParallelismPlan(tp=world,
                                                  overlap_comm=True),
                    "pp": lambda: ParallelismPlan(pp=world),
                    "fsdp": lambda: ParallelismPlan("fsdp", dp=world),
                    }[strategy]()
            eng = build_engine(params, cfg, _dc.replace(scfg, plan=plan),
                               slo=slo, retain_streams=False)
            pstats = run_workload(eng, workload)
            pslo = pstats.get("slo_report", {})
            # oracle stream pin AFTER the workload pass: the engine's
            # completed counter is cumulative, and drained below reads
            # the workload's own count
            streams_equal = eng.run(
                [_Req(r.uid, list(r.tokens),
                      max_new_tokens=r.max_new_tokens, tenant=r.tenant)
                 for r in cohort]) == oracle_streams
            st = eng.stats()
            chip_bytes = st["hbm_chip_bytes"]
            budget = (args.chip_hbm_mb * 2 ** 20
                      or (chip_bytes + single_total) / 2)
            exceeds_single = single_total > budget
            fits_plan = chip_bytes <= budget
            drained = pstats.get("completed", 0) == len(workload)
            sub = {
                "strategy": strategy,
                "plan_world": st["plan_world"],
                "ok": bool(drained and streams_equal and exceeds_single
                           and fits_plan),
                "drained": drained,
                "streams_equal": streams_equal,
                "hbm_model_bytes": st["hbm_model_bytes"],
                "hbm_chip_bytes": chip_bytes,
                "chip_budget_bytes": round(budget),
                "single_chip_total_bytes": single_total,
                "exceeds_single_chip": exceeds_single,
                "fits_plan_chip": fits_plan,
                "hbm_cut_vs_single": round(single_total / chip_bytes, 4),
                "goodput_rps": pslo.get("goodput_rps"),
                "good_fraction": pslo.get("good_fraction"),
                "violations": pslo.get("violations"),
                "completed": pstats.get("completed"),
                "tokens_per_s": pstats.get("tokens_per_s"),
                **{k: pstats.get(k) for k in (
                    "ttft_ms_p50", "ttft_ms_p99",
                    "tpot_ms_p50", "tpot_ms_p99")},
                "compilations": eng.compile_counts(),
            }
            for k in ("weight_gather_ms", "weight_gather_wire_bytes",
                      "pp_bubble_fraction", "pp_bubble_fraction_modeled",
                      "pp_microbatches", "pp_credit_waits"):
                if k in st:
                    sub[k] = st[k]
            return sub

        strategies = (["tp", "pp", "fsdp"] if args.plan == "all"
                      else [args.plan])
        passes = {s: plan_pass(s) for s in strategies}
        rec = {
            "metric": name,
            "ok": all(p["ok"] for p in passes.values()),
            "plan": args.plan,
            "hbm_model_bytes": max(
                (p["hbm_model_bytes"] for p in passes.values()
                 if "hbm_model_bytes" in p), default=None),
            "single_chip_total_bytes": single_total,
            # worst driven strategy carries the flat gate fields: the
            # budget headline must hold for EVERY plan, goodput for the
            # slowest
            "hbm_chip_bytes": max(
                (p["hbm_chip_bytes"] for p in passes.values()
                 if "hbm_chip_bytes" in p), default=None),
            "goodput_rps": min(
                (p["goodput_rps"] for p in passes.values()
                 if p.get("goodput_rps") is not None), default=None),
            "plans": passes,
            "slo": slo.to_dict(),
            "workload": {"mode": wcfg.mode, "n": wcfg.n_requests,
                         "rate_rps": wcfg.rate_rps, "seed": wcfg.seed,
                         "n_tenants": wcfg.n_tenants,
                         "kv_quant": args.kv_quant,
                         "spec_k": args.spec_k},
            "backend": jax.default_backend(),
        }
        for s, key in (("fsdp", "weight_gather_ms"),
                       ("pp", "pp_bubble_fraction")):
            if s in passes and key in passes[s]:
                rec[key] = passes[s][key]
        line = json_record(**rec)
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    tenant_w = {f"t{i}": w for i, w in enumerate(weights)}
    ccfg = ClusterConfig(
        n_prefill=n_prefill, n_decode=n_decode, serve=scfg,
        wire_mode=args.wire_mode,
        router=RouterConfig(slo=slo, tenant_weights=tenant_w),
        link_fixed_ms=args.link_fixed_ms,
        link_gib_per_s=args.link_gib_per_s)

    def run_cluster(time_scale: float, chaos=None):
        cl = ServeCluster(params, cfg, ccfg, retain_streams=False,
                          chaos=chaos)
        stats = run_workload(cl, workload, time_scale=time_scale)
        return cl, stats

    # -- disaggregated pass at the offered rate ---------------------------
    cluster, stats = run_cluster(1.0)

    # wire-model agreement: every handoff's payload nbytes was asserted
    # against the model at pack time; re-derive the total independently
    # from the workload's prompt lengths
    kv = cluster.prefill_workers[0].kv_cfg
    shed_uids = set(cluster.shed)
    modeled = sum(
        transfer_wire_bytes(kv, kv.blocks_for_tokens(len(r.tokens)),
                            args.wire_mode)
        for _, r in workload if r.uid not in shed_uids)
    measured = cluster.transport.wire_bytes_total
    # agreement is meaningful only on a drained run (every non-shed
    # request made exactly one handoff)
    wire_model_agrees = (measured == modeled)

    # -- colocated A/B: one engine, same total decode slots ---------------
    colo_cfg = ServeConfig(
        num_slots=SLOTS * n_decode, block_size=BLOCK_SIZE,
        kv_quant=args.kv_quant, prefill_chunk=args.prefill_chunk,
        spec_k=args.spec_k, megakernel=args.megakernel, prefix_cache=False)
    colo = InferenceEngine(params, cfg, colo_cfg, slo=slo,
                           retain_streams=False)
    colo_stats = run_workload(colo, workload)
    colo_slo = colo_stats.get("slo_report", {})

    # -- overload pass: arrivals compressed overload-factor x -------------
    overload = None
    if args.overload_factor and args.overload_factor > 1.0:
        ov_cluster, ov = run_cluster(1.0 / args.overload_factor)
        ov_slo = ov.get("slo_report", {})
        overload = {
            "factor": args.overload_factor,
            "offered": ov.get("offered"),
            "completed": ov.get("completed"),
            "shed": ov_cluster.router.shed,
            "shed_rate": ov.get("shed_rate"),
            "goodput_rps": ov_slo.get("goodput_rps"),
            "good_fraction": ov_slo.get("good_fraction"),
            "deadlocked": False,  # run_workload returned — by contract
        }

    # -- chaos pass: kill 1 of N decode workers at overload ---------------
    # the ISSUE-13 deliverable: goodput-under-chaos — the same 2x-offered
    # workload, but a decode worker fail-stops mid-run and its live
    # requests migrate to the survivors over the KV wire. The record
    # carries what the failure cost (goodput_under_chaos_rps /
    # survivor_good_fraction, regress-gated higher-is-better) and how
    # noisy the recovery was (migrations/replays/retries, lower-better).
    chaos_rec = None
    chaos_ok = True
    if args.chaos:
        from apex_tpu.serve import ClusterChaos
        from apex_tpu.serve.cluster.chaos import KillWorker

        factor = max(args.overload_factor or 0.0, 2.0)
        plan = ClusterChaos([KillWorker(at_step=args.chaos_kill_step,
                                        worker="decode0")])
        ch_cluster, ch = run_cluster(1.0 / factor, chaos=plan)
        ch_slo = ch.get("slo_report", {})
        ch_drained = (ch.get("completed", 0) + len(ch_cluster.shed)
                      == len(workload))
        chaos_ok = bool(ch_drained and ch.get("worker_deaths") == 1)
        chaos_rec = {
            "factor": factor,
            "kill_step": args.chaos_kill_step,
            "killed": "decode0",
            "offered": ch.get("offered"),
            "completed": ch.get("completed"),
            "shed_rate": ch.get("shed_rate"),
            "goodput_under_chaos_rps": ch_slo.get("goodput_rps"),
            "survivor_good_fraction": ch_slo.get("good_fraction"),
            "worker_deaths": ch.get("worker_deaths"),
            "migrations_total": ch.get("migrations_total"),
            "replayed_tokens": ch.get("replayed_tokens"),
            "heartbeat_misses": ch.get("heartbeat_misses"),
            "transfer_retries": ch.get("transfer_retries"),
            "drained": ch_drained,
            "deadlocked": False,  # run_workload returned — by contract
            "faults": plan.summary(),
        }

    # -- per-tenant LoRA A/B: adapters off vs N tenants x M adapters ------
    # the PR-16 stage-20 record: the same tenant mix with every tenant
    # bound to an adapter (loadgen's fixed t{i} -> ad{i % M} mapping)
    # through an adapter-enabled fleet. Carries tokens/s + TTFT p99 next
    # to the baseline pass above, the registry hit rate and the router's
    # warm-dispatch rate (both regress-gated higher-is-better), and
    # asserts the aid=0 cohort streams BITWISE what an adapter-free
    # fleet streams — transparency, not tolerance.
    lora_rec = None
    lora_ok = True
    if args.lora:
        import dataclasses

        from apex_tpu.serve import make_adapter_weights

        n_adapters = args.n_adapters or args.n_tenants
        lora_scfg = dataclasses.replace(scfg, lora_rank=args.lora_rank,
                                        max_adapters=n_adapters)
        lora_ccfg = dataclasses.replace(ccfg, serve=lora_scfg)
        lora_workload = build_workload(
            dataclasses.replace(wcfg, n_adapters=n_adapters),
            VOCAB, MAX_SEQ)
        adapters = {
            f"ad{i}": make_adapter_weights(cfg, args.lora_rank,
                                           jax.random.PRNGKey(100 + i))
            for i in range(n_adapters)}
        lora_cluster = ServeCluster(params, cfg, lora_ccfg,
                                    retain_streams=False)
        for aname, w in adapters.items():
            lora_cluster.load_adapter(aname, w)
        lora_stats = run_workload(lora_cluster, lora_workload)
        lora_slo = lora_stats.get("slo_report", {})
        lora_drained = (lora_stats.get("completed", 0)
                        + len(lora_cluster.shed) == len(lora_workload))
        lst = lora_cluster.stats()

        # aid=0 transparency cohort: the first requests of the BASE
        # workload (no adapter bound), replayed through a fresh
        # adapter-free fleet and a fresh adapter-ENABLED fleet — the
        # streams must be bitwise equal or the record refuses to bank
        from apex_tpu.serve import Request as _Req

        cohort = [_Req(f"eq{i}", list(r.tokens),
                       max_new_tokens=min(r.max_new_tokens, 8),
                       tenant=r.tenant)
                  for i, (_, r) in enumerate(workload[:6])]
        base_streams = ServeCluster(params, cfg, ccfg).run(
            cohort, max_steps=200000)
        lora_fleet = ServeCluster(params, cfg, lora_ccfg)
        for aname, w in adapters.items():
            lora_fleet.load_adapter(aname, w)
        lora_streams = lora_fleet.run(cohort, max_steps=200000)
        streams_equal = base_streams == lora_streams

        lora_ok = bool(lora_drained and streams_equal)
        tps = (round(lora_stats.get("generated_tokens", 0)
                     / lora_stats["wall_s"], 3)
               if lora_stats.get("wall_s") else None)
        lora_rec = {
            "rank": args.lora_rank,
            "n_adapters": n_adapters,
            "n_tenants": args.n_tenants,
            "completed": lora_stats.get("completed"),
            "shed_rate": lora_stats.get("shed_rate"),
            "tokens_per_s": tps,
            "goodput_rps": lora_slo.get("goodput_rps"),
            "ttft_ms_p99": lora_stats.get("ttft_ms_p99"),
            "tpot_ms_p99": lora_stats.get("tpot_ms_p99"),
            "adapter_hit_rate": lst.get("adapter_hit_rate"),
            "adapter_warm_dispatch_rate":
                lst.get("adapter_warm_dispatch_rate"),
            "adapter_evictions": lst.get("adapter_evictions"),
            "adapter_load_ms": lst.get("adapter_load_ms"),
            "catalog_loads": lst["adapters"]["catalog_loads"],
            "streams_equal": streams_equal,
            "drained": lora_drained,
        }

    # -- int8-vs-int4 KV concurrency A/B (modeled, config-exact) ----------
    # at the int8 pool's byte budget, how many pool blocks — and so
    # concurrent max-length contexts — does each tier hold? (halving
    # bytes/token must double both; the stage-17 regress gate covers
    # contexts_max higher-better / kv_bits lower-better)
    import dataclasses as _dc

    from apex_tpu.serve.kv_cache import kv_cache_bytes

    kv_run = cluster.decode_workers[0].engine.kv_cfg
    max_ctx = scfg.max_context or cfg.max_seq
    kv_ab = {}
    budget = None
    for bits in (8, 4):
        kvq = _dc.replace(kv_run, quantized=True, bits=bits,
                          group_size=None)
        per_pool = kv_cache_bytes(kvq)
        if budget is None:
            budget = per_pool  # the int8 tier's budget anchors the A/B
        blocks_at_budget = budget * kvq.num_blocks // per_pool
        kv_ab[f"int{bits}"] = {
            "kv_cache_bytes": per_pool,
            "blocks_at_int8_budget": blocks_at_budget,
            "contexts_max": blocks_at_budget * kvq.block_size // max_ctx,
            "transfer_wire_bytes": sum(
                transfer_wire_bytes(kvq,
                                    kvq.blocks_for_tokens(len(r.tokens)))
                for _, r in workload),
        }
    kv_ab["hbm_cut_int8_over_int4"] = round(
        kv_ab["int8"]["kv_cache_bytes"] / kv_ab["int4"]["kv_cache_bytes"],
        4)

    slo_rep = stats.get("slo_report", {})
    drained = stats.get("completed", 0) + len(cluster.shed) == len(workload)
    rec = {
        "metric": name,
        "ok": bool(drained and wire_model_agrees and chaos_ok
                   and lora_ok),
        "hosts": {"prefill": n_prefill, "decode": n_decode,
                  "total": n_prefill + n_decode},
        "goodput_rps": slo_rep.get("goodput_rps"),
        "throughput_rps": slo_rep.get("throughput_rps"),
        "good_fraction": slo_rep.get("good_fraction"),
        "violations": slo_rep.get("violations"),
        "shed_rate": stats.get("shed_rate"),
        "admitted_rps": stats.get("admitted_rps"),
        **{k: stats.get(k) for k in (
            "offered", "submitted", "completed", "offered_rps",
            "generated_tokens", "wall_s",
            "ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50", "tpot_ms_p99",
            "queue_ms_p50", "queue_ms_p99", "e2e_ms_p50", "e2e_ms_p99",
            "decode_step_ms_p50", "decode_step_ms_p99",
            "transfer_ms_p50", "transfer_ms_p99")},
        "transfer": stats.get("transfer"),
        "wire_model_agrees": wire_model_agrees,
        "transfer_wire_bytes_modeled": modeled,
        # sub-8-bit KV headline fields (regress-gated; wire_bytes_int4 is
        # the modeled int4 handoff total for THIS workload)
        "kv_bits": (kv_run.bits if kv_run.quantized
                    else 8 * jnp.dtype(kv_run.dtype).itemsize),
        "contexts_max": kv_run.tokens_capacity // max_ctx,
        "wire_bytes_int4": kv_ab["int4"]["transfer_wire_bytes"],
        "kv_ab": kv_ab,
        "router": stats.get("router"),
        "colocated": {
            "goodput_rps": colo_slo.get("goodput_rps"),
            "good_fraction": colo_slo.get("good_fraction"),
            "tokens_per_s": colo_stats.get("tokens_per_s"),
            "ttft_ms_p50": colo_stats.get("ttft_ms_p50"),
            "ttft_ms_p99": colo_stats.get("ttft_ms_p99"),
            "tpot_ms_p50": colo_stats.get("tpot_ms_p50"),
            "tpot_ms_p99": colo_stats.get("tpot_ms_p99"),
            "completed": colo_stats.get("completed"),
        },
        "disagg_vs_colocated_goodput": (
            round(slo_rep["goodput_rps"] / colo_slo["goodput_rps"], 4)
            if slo_rep.get("goodput_rps") and colo_slo.get("goodput_rps")
            else None),
        "overload": overload,
        "chaos": chaos_rec,
        "lora": lora_rec,
        # elastic counters of the CLEAN pass (all zero unless the run
        # hit real faults — regress gates them lower-is-better)
        "elastic": stats.get("elastic"),
        "compilations": cluster.compile_counts(),
        "slo": slo.to_dict(),
        "workload": {"mode": wcfg.mode, "n": wcfg.n_requests,
                     "rate_rps": wcfg.rate_rps,
                     "burst_every_s": wcfg.burst_every_s,
                     "burst_size": wcfg.burst_size, "seed": wcfg.seed,
                     "n_tenants": wcfg.n_tenants,
                     "tenant_weights": list(weights),
                     "wire_mode": args.wire_mode,
                     "kv_quant": args.kv_quant,
                     "spec_k": args.spec_k},
        "backend": jax.default_backend(),
    }
    if chaos_rec is not None:
        # flat goodput-under-chaos headline fields (the stage-18 gate:
        # goodput/survivor fraction higher-is-better, recovery noise
        # lower-is-better)
        for k in ("goodput_under_chaos_rps", "survivor_good_fraction",
                  "migrations_total", "replayed_tokens", "worker_deaths",
                  "heartbeat_misses", "transfer_retries"):
            rec[k] = chaos_rec[k]
    if lora_rec is not None:
        # flat per-tenant LoRA headline fields (the stage-20 gate: hit
        # and warm-dispatch rates higher-is-better, load time and LRU
        # churn lower-is-better)
        for k in ("adapter_hit_rate", "adapter_warm_dispatch_rate",
                  "adapter_evictions", "adapter_load_ms",
                  "streams_equal"):
            rec[k] = lora_rec[k]
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

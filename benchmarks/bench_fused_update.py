"""Fused optimizer update-tail benchmark: one Pallas kernel vs the XLA
op chain.

The ZeRO half of the megakernel PR (ROADMAP item 4): after the gradient
reduce-scatter the Adam/LAMB tail is ~10 tiny elementwise ops per leaf —
dispatch-bound on a dp-sharded state exactly like the q_len=1 decode
step. This bench times BOTH tails over a GPT-2-124M-shaped ZeRO shard
(1/8 of each leaf, the dp=8 slice) through jitted steps and emits ONE
JSON line (the ``bench.py`` / ``monitor.json_record`` protocol):

* ``ref_ms`` / ``fused_ms`` — p50 per-step wall time of the unfused op
  chain vs ``ops.fused_update.fused_adam_tail`` over the same leaves
* ``speedup`` — ref / fused
* ``lamb_ref_ms`` / ``lamb_fused_ms`` — the LAMB variant (tail + local
  trust-ratio sq-sums)

Honesty: off-TPU the kernel runs the Pallas INTERPRETER (it re-expands to
the same XLA ops — no dispatch is saved) so the metric name carries the
``_CPU_FALLBACK`` suffix and the CPU numbers are a correctness rehearsal,
not a speedup claim; ``tpu_watch.sh`` stage 13 banks the TPU truth as
``FUSED_UPDATE_TPU.json``.

Run: ``python benchmarks/bench_fused_update.py [--out FILE]``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import (
    pin_cpu_if_requested,
    pin_cpu_if_tunnel_dead,
    pin_cpu_platform,
)

pin_cpu_if_requested()
pin_cpu_if_tunnel_dead()
if os.environ.get("JAX_PLATFORMS") == "cpu":
    pin_cpu_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

ON_TPU = jax.default_backend() == "tpu"

# GPT-2-124M leaves sliced to the dp=8 ZeRO shard (ceil(size/8), the
# _sharding.py split) — the shapes the fused tail actually runs on. The
# CPU rehearsal scales them 1:16 (the interpret-mode kernel re-expands to
# XLA anyway — off-chip only correctness is being rehearsed, not speed).
DP = 8
SCALE = 1 if ON_TPU else 16
LEAVES = {
    "wte": 50257 * 768, "wpe": 1024 * 768,
    "qkv": 12 * 768 * 2304, "attn_out": 12 * 768 * 768,
    "fc1": 12 * 768 * 3072, "fc2": 12 * 3072 * 768,
    "lns": 12 * 4 * 768 + 2 * 768,
}
REPS = 30


def main() -> int:
    import argparse
    import statistics
    import time

    from apex_tpu.monitor import json_record
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    from apex_tpu.ops.fused_update import (
        adam_tail_reference,
        fused_adam_tail,
        fused_lamb_tail,
        lamb_tail_reference,
    )

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--reps", type=int, default=REPS)
    args = ap.parse_args()

    name = "zero_fused_update_tail"
    if not ON_TPU:
        name += "_CPU_FALLBACK"

    key = jax.random.PRNGKey(0)
    shards = {}
    for i, (k, n) in enumerate(LEAVES.items()):
        sz = -(-n // (DP * SCALE))
        kk = jax.random.fold_in(key, i)
        shards[k] = tuple(
            jax.random.normal(jax.random.fold_in(kk, j), (sz,),
                              jnp.float32) for j in range(4))
    # moments must be valid (v >= 0)
    shards = {k: (g, m, jnp.abs(v), p) for k, (g, m, v, p) in shards.items()}
    n_elems = sum(v[0].size for v in shards.values())
    kw = dict(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01,
              adam_w_mode=True)
    c1 = jnp.float32(1 - 0.9 ** 10)
    c2 = jnp.float32(1 - 0.999 ** 10)

    def step(tail, extra=()):
        def f(sh, c1, c2):
            return {k: tail(g, m, v, p, c1, c2, **kw, **dict(extra))
                    for k, (g, m, v, p) in sh.items()}
        return jax.jit(f)

    def time_it(f):
        out = f(shards, c1, c2)          # compile
        jax.block_until_ready(out)
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f(shards, c1, c2))
            times.append((time.perf_counter() - t0) * 1e3)
        return statistics.median(times)

    lamb_kw = {k: v for k, v in kw.items() if k != "adam_w_mode"}

    def lamb_step(tail):
        def f(sh, c1, c2):
            return {k: tail(g, m, v, p, c1, c2, **lamb_kw)
                    for k, (g, m, v, p) in sh.items()}
        return jax.jit(f)

    ref_ms = time_it(step(adam_tail_reference))
    fused_ms = time_it(step(fused_adam_tail, extra=(("use_pallas", True),)))
    lamb_ref_ms = time_it(lamb_step(lamb_tail_reference))
    lamb_fused_ms = time_it(lamb_step(
        lambda *a, **k2: fused_lamb_tail(*a, use_pallas=True, **k2)))

    rec = {
        "metric": name,
        "ok": True,
        "n_elems": int(n_elems),
        "n_leaves": len(shards),
        "dp": DP,
        "scale": SCALE,
        "ref_ms": round(ref_ms, 4),
        "fused_ms": round(fused_ms, 4),
        "speedup": round(ref_ms / fused_ms, 3) if fused_ms else None,
        "lamb_ref_ms": round(lamb_ref_ms, 4),
        "lamb_fused_ms": round(lamb_fused_ms, 4),
        "lamb_speedup": (round(lamb_ref_ms / lamb_fused_ms, 3)
                         if lamb_fused_ms else None),
        "reps": args.reps,
        "backend": jax.default_backend(),
    }
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Pre-flight: AOT-lower the flagship bench path for TPU from a CPU box.

A healthy tunnel window is scarce (rounds 2-3 had none; round 4's two
windows totalled ~30 min). Every Pallas/Mosaic lowering failure found
here instead of on the chip saves window minutes for measurement. This
traces bench.py's OWN ``build_train_step`` (same model, same code path
the headline times) for every auto-tune sweep configuration plus the
ring-attention long-context step, and lowers each for the TPU target —
the full Mosaic tiling/layout verification, no chip needed
(``tests/test_tpu_lowering.py`` guards single kernels; this guards the
composed programs).

Run: ``JAX_PLATFORMS=cpu python benchmarks/preflight_lowering.py``
Exit 1 if any configuration fails to lower.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from apex_tpu.ops._pallas_util import force_compiled


def _lower(tag, f, *args, min_kernels=1):
    """Lower for TPU and require >= min_kernels Mosaic custom calls in the
    module — a preflight that silently lowers the reference fallback
    (because some dispatch site checks the live backend instead of
    ``compiled_backend()``) would de-risk nothing."""
    t0 = time.perf_counter()
    try:
        with force_compiled():
            lo = jax.jit(f).trace(*args).lower(lowering_platforms=("tpu",))
        n = lo.as_text().count("tpu_custom_call")
        if n < min_kernels:
            print(f"FAIL {tag}: only {n} tpu_custom_call(s) in the lowered "
                  f"module (expected >= {min_kernels}) — a kernel dispatch "
                  f"site fell back to the reference", flush=True)
            return False
        print(f"OK   {tag}  ({n} kernels, {time.perf_counter() - t0:.1f}s)",
              flush=True)
        return True
    except Exception as e:  # noqa: BLE001 — report, keep going
        print(f"FAIL {tag}: {type(e).__name__}: {str(e)[:300]}", flush=True)
        return False


def main() -> int:
    ok = True

    # --- the flagship train step, every sweep configuration -------------
    # bench.py sweeps (remat, policy, scan_unroll); batch does not change
    # lowering legality, so lower each distinct program shape once at a
    # small batch to keep tracing fast.
    import bench

    seq = 1024
    for remat, policy, unroll, fused in [
            (False, "full", 1, True), (True, "full", 1, True),
            (True, "dots", 1, True), (True, "dots_attn", 1, True),
            (False, "full", 12, True),
            (True, "dots", 12, True), (False, "full", 1, False),
            (True, "full", 1, False)]:
        cfg = bench.flagship_config(
            seq, remat=remat, remat_policy=policy, scan_unroll=unroll,
            fused_loss=fused)
        step, params, opt_state, tok, tgt = bench.build_train_step(
            cfg, batch=2, seq=seq)
        ok &= _lower(
            f"train_step remat={remat}/{policy} unroll={unroll} "
            f"fused={fused}",
            step, params, opt_state, tok, tgt, min_kernels=4)

    # --- ring attention (long-context SP path), fwd + bwd ---------------
    from apex_tpu.parallel.mesh import build_mesh
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.sequence_parallel import ring_attention

    n = min(4, len(jax.devices()))
    mesh = build_mesh(tp=1, pp=1, sp=n, devices=jax.devices()[:n])
    b, h, s, d = 1, 4, 512 * n, 64
    q = jnp.zeros((b, h, s, d), jnp.bfloat16)

    def ring_loss(q, k, v):
        def body(q, k, v):
            o = ring_attention(q, k, v, axis_name="sp", causal=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        f = jax.shard_map(body, mesh=mesh,
                          in_specs=(P(None, None, "sp"),) * 3,
                          out_specs=P(), check_vma=False)
        return jnp.sum(f(q, k, v))

    ok &= _lower("ring_attention sp fwd+bwd",
                 jax.grad(ring_loss, argnums=(0, 1, 2)), q, q, q,
                 min_kernels=2)

    print("PREFLIGHT", "PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Forensics overhead A/B — what does the tier-4 plane cost?

The ISSUE-17 gate: monitor tier 4 (per-request latency attribution +
per-tenant cost metering) must cost ≤ ~5% tokens/s on the loadgen
serving workload, or it is not an always-on plane. Same discipline as
``bench_observe.py`` (stage 19): run the SAME seeded multi-tenant
workload through a 2-host disaggregated cluster twice:

* **on** — ``ClusterConfig(metering=True, attribution=True)``: every
  retirement attributed into queue/prefill/transfer/decode/stall and
  charged to its tenant under the cost model;
* **off** — ``metering=False, attribution=False``: the floor.

ONE ``json_record`` line carries ``tokens_per_s_on/off``, the
``forensics_overhead_pct`` delta (the ok gate, ``--overhead-tol``),
``attrib_coverage`` (must be 1.0 — an unattributed retirement is a
broken plane, not overhead), the component p50/p99s, per-tenant cost
rollup vs fleet totals (``rollup_matches_totals`` must hold to the
unit) and ``cost_per_token``. ``tpu_watch.sh`` stage 21 banks
``ATTRIB_COST_TPU.json``, regression-gated via ``python -m
apex_tpu.monitor.regress --tol 0.15``; CPU rehearsals carry
``_CPU_FALLBACK`` and never promote — the ≤ 5% claim is a TPU truth.

Run: ``python benchmarks/bench_attrib_cost.py [--out FILE]``.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import argparse

    from apex_tpu.utils.platform import (
        pin_cpu_if_requested,
        pin_cpu_if_tunnel_dead,
        pin_cpu_platform,
    )

    pin_cpu_if_requested()
    pin_cpu_if_tunnel_dead()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        pin_cpu_platform()

    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())  # after the pin: backend is final

    from apex_tpu.monitor import SloSpec, json_record
    from apex_tpu.monitor.attrib import COMPONENTS
    from apex_tpu.serve import (
        ClusterConfig,
        RouterConfig,
        ServeCluster,
        ServeConfig,
    )
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from loadgen import WorkloadConfig, build_workload, run_workload

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--n-requests", type=int, default=64)
    ap.add_argument("--rate-rps", type=float, default=8.0)
    ap.add_argument("--n-tenants", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overhead-tol", type=float, default=0.05,
                    help="max tokens/s fraction the forensics plane may "
                         "cost (the ok gate; ISSUE-17 pins 5%%)")
    args = ap.parse_args(argv)

    on_tpu = jax.default_backend() == "tpu"
    name = "gpt_serve_attrib_cost_ab"
    if not on_tpu:
        name += "_CPU_FALLBACK"

    # the pinned bench model (bench_serve.py / bench_observe constants)
    HIDDEN, LAYERS, HEADS, VOCAB, MAX_SEQ = 128, 2, 8, 512, 256
    SLOTS, BLOCK_SIZE = 4, 16
    cfg = GPTConfig(vocab_size=VOCAB, max_seq=MAX_SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS,
                    dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    wcfg = WorkloadConfig(n_requests=args.n_requests,
                          rate_rps=args.rate_rps, seed=args.seed,
                          prompt_len_max=MAX_SEQ // 2,
                          n_tenants=args.n_tenants)
    workload = build_workload(wcfg, VOCAB, MAX_SEQ)
    slo = SloSpec(ttft_ms=2000.0, tpot_ms=200.0)
    scfg = ServeConfig(num_slots=SLOTS, block_size=BLOCK_SIZE,
                       prefix_cache=False)

    def run(forensics: bool):
        # everything except the tier-4 plane is identical (no scraping,
        # no flight rings): the delta isolates attribution + metering
        ccfg = ClusterConfig(
            n_prefill=1, n_decode=1, serve=scfg,
            router=RouterConfig(slo=slo),
            scrape_every=0, flight_capacity=0,
            metering=forensics, attribution=forensics)
        cl = ServeCluster(params, cfg, ccfg, retain_streams=False)
        t0 = time.perf_counter()
        stats = run_workload(cl, workload)
        wall = time.perf_counter() - t0
        return cl, stats, wall

    # warm pass compiles the programs so neither timed pass pays XLA
    run(False)

    cl_on, st_on, wall_on = run(True)
    cl_off, st_off, wall_off = run(False)

    tps_on = st_on.get("generated_tokens", 0) / wall_on
    tps_off = st_off.get("generated_tokens", 0) / wall_off
    overhead = (tps_off - tps_on) / tps_off if tps_off else None
    streams_equal = (st_on.get("completed") == st_off.get("completed")
                     and st_on.get("generated_tokens")
                     == st_off.get("generated_tokens"))

    full = cl_on.stats()
    meter = full.get("meter", {})
    tenants = meter.get("tenants", {})
    totals = meter.get("totals", {})
    # per-tenant rollup must equal fleet totals to the unit (the ledgers
    # are exact; displayed values are rounded to 1e-6 per tenant)
    rollup = sum(t.get("cost_units", 0.0) for t in tenants.values())
    rollup_ok = (abs(rollup - totals.get("cost_units", 0.0))
                 <= max(len(tenants), 1) * 1e-6)
    coverage = full.get("attrib_coverage")

    ok = bool(streams_equal
              and coverage == 1.0
              and full.get("meter_coverage") == 1.0
              and rollup_ok
              and overhead is not None
              and overhead <= args.overhead_tol)
    rec = {
        "metric": name,
        "ok": ok,
        "tokens_per_s_on": round(tps_on, 3),
        "tokens_per_s_off": round(tps_off, 3),
        "forensics_overhead_pct": (round(100 * overhead, 2)
                                   if overhead is not None else None),
        "overhead_tol_pct": round(100 * args.overhead_tol, 2),
        # forensics must never perturb the WORK: same tokens out
        "streams_equal": streams_equal,
        "attrib_coverage": coverage,
        "meter_coverage": full.get("meter_coverage"),
        **{f"{c}_component_ms_{q}": full.get(f"{c}_component_ms_{q}")
           for c in COMPONENTS for q in ("p50", "p99")},
        "cost_per_token": full.get("cost_per_token"),
        "cost_per_request": full.get("cost_per_request"),
        "rollup_matches_totals": rollup_ok,
        "n_tenants": len(tenants),
        "tenant_cost_units": {t: v.get("cost_units")
                              for t, v in sorted(tenants.items())},
        "worker_cost_rates": meter.get("worker_cost_rates"),
        "overflow_charges_total": meter.get("overflow_charges_total"),
        "completed": st_on.get("completed"),
        "goodput_rps_on": st_on.get("goodput_rps"),
        "goodput_rps_off": st_off.get("goodput_rps"),
        "workload": {"n": wcfg.n_requests, "rate_rps": wcfg.rate_rps,
                     "seed": wcfg.seed, "mode": wcfg.mode,
                     "n_tenants": wcfg.n_tenants},
        "backend": jax.default_backend(),
    }
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    # ok:false is a bench FAILURE (broken attribution/rollup or a plane
    # too expensive to leave on), not a slow record
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Comm/compute-overlap benchmark: GPTConfig.overlap_comm on vs off.

One ``json_record`` line (the bench.py protocol): tp-parallel GPT train
step time with the monolithic collectives vs the decomposed ppermute rings
(``apex_tpu.comm.overlap``), plus the HLO-measured evidence — total
modeled wire bytes for both programs (per-ring byte-neutral; the full
grad program pays ~10% extra for the dW re-gather under remat, see the
``comm.overlap`` docstring) and the
decomposed program's hidden-vs-exposed collective-permute split from
``comm.accounting.overlap_report`` (hidden = the hop has a ``dot``
scheduled in its async start/done window on TPU, or a data-independent
``dot`` a latency-hiding scheduler may overlap on the CPU sim).

On the CPU sim the time column is NOT the story (collectives are memcpys;
the ring's extra dispatch overhead usually LOSES there) — the byte
neutrality + hidden-fraction columns are; the time column becomes the
headline on a real multi-chip slice, which is why ``tpu_watch.sh`` stages
this for the next healthy tunnel window (needs a slice: a single-chip
tunnel has no ring to overlap and the record says so honestly).

Run: ``python benchmarks/bench_overlap.py [--out FILE]``.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import (
    pin_cpu_if_requested,
    pin_cpu_if_tunnel_dead,
    pin_cpu_platform,
)

pin_cpu_if_requested()
pin_cpu_if_tunnel_dead()  # don't hang the watcher on a dead tunnel
if os.environ.get("JAX_PLATFORMS") == "cpu":
    # CPU path (explicit or dead-tunnel): the 8-virtual-device sim, set
    # BEFORE the first backend init or the flag is ignored
    pin_cpu_platform(virtual_devices=8)

import jax

ON_TPU = jax.default_backend() == "tpu"

import dataclasses  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

# the pinned protocol (canary discipline, see bench_comm.py): one fixed
# model/config so the line is comparable round-over-round
BATCH, SEQ, HIDDEN, LAYERS, HEADS, VOCAB = 2, 256, 128, 2, 8, 512
STEPS = 5


def _build(overlap: bool, tp: int):
    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    cfg = GPTConfig(vocab_size=VOCAB, max_seq=SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS, dtype=jnp.bfloat16,
                    megatron_sp=True, overlap_comm=overlap)
    mesh = build_mesh(tp=tp, pp=1, sp=1)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    specs = gpt_param_specs(cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (BATCH, SEQ), 0, VOCAB)

    def loss(p, t, y):
        def body(p, a, b):
            return replicate_loss(gpt_loss(p, a, b, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(body, mesh=mesh,
                             in_specs=(specs, P(), P()), out_specs=P())(
                                 p, t, y)

    compiled = jax.jit(jax.value_and_grad(loss)).lower(
        params, tok, tok).compile()
    return compiled, (params, tok, tok)


def _time(compiled, args) -> float:
    out = compiled(*args)  # warmup is the caller's compile; run once more
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = compiled(*args)
    float(out[0])  # value-transfer fence (bench.py protocol)
    return (time.perf_counter() - t0) / STEPS * 1e3


def main() -> int:
    import argparse

    from apex_tpu.comm import collective_report, overlap_report
    from apex_tpu.monitor import json_record
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    tp = len(jax.devices())
    name = "gpt_tp_overlap_comm_step"
    if not ON_TPU:
        name += "_CPU_FALLBACK"
    if tp < 2:
        line = json_record(
            metric=name, ok=False, tp=tp,
            reason="single device: no TP ring to decompose; needs a slice")
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    off, off_args = _build(False, tp)
    on, on_args = _build(True, tp)
    off_ms = _time(off, off_args)
    on_ms = _time(on, on_args)
    bytes_off = collective_report(off).wire_bytes
    bytes_on = collective_report(on).wire_bytes
    rep = overlap_report(on)
    rec = {
        "metric": name,
        "tp": tp,
        "megatron_sp": True,
        "overlap_off_ms": round(off_ms, 3),
        "overlap_on_ms": round(on_ms, 3),
        "speedup": round(off_ms / on_ms, 3) if on_ms else None,
        "wire_bytes_off": round(bytes_off),
        "wire_bytes_on": round(bytes_on),
        "permutes": rep.permutes,
        "async_pairs": rep.async_pairs,
        "hidden_bytes": round(rep.hidden_wire_bytes),
        "exposed_bytes": round(rep.exposed_wire_bytes),
        "hidden_fraction": round(rep.hidden_fraction, 4),
        "config": {"batch": BATCH, "seq": SEQ, "hidden": HIDDEN,
                   "layers": LAYERS, "steps": STEPS},
        "backend": jax.default_backend(),
    }
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    if not hasattr(jax, "shard_map"):
        # stock-jax box: the mesh program cannot build — fail loudly, do
        # not bank a fake artifact (the watcher retries next window)
        print('{"metric": "gpt_tp_overlap_comm_step", "ok": false, '
              '"reason": "jax.shard_map unavailable (stock jax)"}')
        sys.exit(2)
    sys.exit(main())

"""The full BASELINE.json config matrix — one JSON line per config.

``BASELINE.json`` names five configs (the reference publishes no numbers, so
every figure here is measured by this harness — see BASELINE.md):

1. ResNet-50 ImageNet amp O1, single chip                   -> img/s
2. DCGAN amp (2 models / 3 scalers)                         -> img/s
3. FusedAdam + FusedLayerNorm microbench (BERT-base shapes) -> ms/step
4. ResNet-50 DDP + SyncBatchNorm (8-device scaling shape on the virtual CPU
   mesh; chip img/s on the real chip)                       -> img/s + ratio
5. Megatron GPT-2 TP loss parity vs single-chip (virtual mesh; single-chip
   tokens/s is ../bench.py's headline)                      -> bool

Run: ``python benchmarks/bench_matrix.py [config ...]`` with configs from
{resnet50_o1, dcgan, microbench, ddp_syncbn, gpt_tp_pp}; default all.
Configs that need a multi-device mesh re-exec themselves in a subprocess on
an 8-device virtual CPU platform (the 1-chip tunnel cannot host them).

Timing fence: example trainers host-read the loss every iteration; direct
loops here end with a scalar host-read (axon's ``block_until_ready`` returns
early; a value transfer cannot). Steady-state numbers come from a second
``train()`` call that hits the in-process jit cache.
"""

from __future__ import annotations

import functools
import importlib.util
import json
import os
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

if (os.environ.get("APEX_TPU_BENCH_VIRTUAL")
        or os.environ.get("JAX_PLATFORMS") == "cpu"):
    # the env var alone does NOT stop the image's axon backend hook — only
    # the config-flag pin does (utils/platform.py); without it the virtual
    # child (or an explicit JAX_PLATFORMS=cpu run) dials the TPU tunnel
    from apex_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform()
import jax
import jax.numpy as jnp


def _emit(metric, value, unit, **extra):
    line = {"metric": metric, "value": round(float(value), 3), "unit": unit}
    line.update(extra)
    print(json.dumps(line), flush=True)


def _on_tpu():
    return jax.default_backend() == "tpu"


def _suffix(name):
    return name if _on_tpu() else name + "_CPU_FALLBACK"


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _imagenet():
    return _load(os.path.join(_ROOT, "examples", "imagenet", "main_amp.py"),
                 "imagenet_main_amp")


# ---------------------------------------------------------------------------
# 1. ResNet-50 amp O1 single chip — drives the example trainer itself

def _timed_train(m, argv, iters):
    """(img_or_tok per sec denominator dt). First train() compiles (the
    example trainer caches its jitted step per config), second is pure
    steady state."""
    m.train(m.parse_args(argv))  # compile
    t0 = time.perf_counter()
    m.train(m.parse_args(argv))  # steady state (jit cache hit)
    return (time.perf_counter() - t0) / iters


def bench_resnet50_o1():
    m = _imagenet()
    # reference operating point: image 224, per-device batch 224 at O1
    # (examples/imagenet/README.md:30-60); walk the batch down on OOM
    batches, size, iters = ([224, 128, 64], 224, 8) if _on_tpu() \
        else ([8], 32, 2)
    for batch in batches:
        argv = ["--arch", "resnet50", "--opt-level", "O1",
                "--batch-size", str(batch), "--image-size", str(size),
                "--iters", str(iters), "--print-freq", "1000"]
        try:
            dt = _timed_train(m, argv, iters)
        except Exception as e:  # OOM at this batch — try the next
            if batch == batches[-1]:
                raise
            print(f"# resnet50_o1 batch {batch} failed "
                  f"({type(e).__name__}); retrying smaller", flush=True)
            continue
        _emit(_suffix("resnet50_imagenet_ampO1_img_per_sec_chip"),
              batch / dt, "img/s", batch=batch, image_size=size)
        return


# ---------------------------------------------------------------------------
# 2. DCGAN amp

def bench_dcgan():
    dcgan = _load(os.path.join(_ROOT, "examples", "dcgan", "main_amp.py"),
                  "dcgan_main_amp")
    batch, iters = (64, 8) if _on_tpu() else (16, 2)
    argv = ["--iters", str(iters), "--batch-size", str(batch)]
    dcgan.train(dcgan.parse_args(argv))  # compile
    t0 = time.perf_counter()
    dcgan.train(dcgan.parse_args(argv))
    dt = (time.perf_counter() - t0) / iters
    _emit(_suffix("dcgan_ampO1_img_per_sec_chip"), batch / dt, "img/s",
          batch=batch)


# ---------------------------------------------------------------------------
# 3. FusedAdam + FusedLayerNorm microbench (BERT-base shapes)

def bench_microbench():
    from apex_tpu.normalization import FusedLayerNorm
    from apex_tpu.optimizers import FusedAdam

    hidden, tokens = 768, 32 * 512  # BERT-base rows
    iters = 20 if _on_tpu() else 3

    ln = FusedLayerNorm(hidden)
    vs = ln.init(jax.random.PRNGKey(0), jnp.zeros((2, hidden), jnp.bfloat16))
    x = jax.random.normal(jax.random.PRNGKey(1), (tokens, hidden)).astype(
        jnp.bfloat16)

    @jax.jit
    def ln_step(x):
        def f(x):
            return jnp.sum(ln.apply(vs, x).astype(jnp.float32) ** 2)
        g = jax.grad(f)(x)
        return x + 0.0 * g.astype(x.dtype)

    x = ln_step(x); float(x[0, 0])
    t0 = time.perf_counter()
    for _ in range(iters):
        x = ln_step(x)
    float(x[0, 0])
    _emit(_suffix("fused_layer_norm_bert_base_fwdbwd_ms"),
          (time.perf_counter() - t0) / iters * 1e3, "ms",
          shape=[tokens, hidden])

    key = jax.random.PRNGKey(2)
    params = {f"l{i}": jax.random.normal(jax.random.fold_in(key, i),
                                         (hidden, 12 * hidden)).astype(
        jnp.bfloat16) for i in range(12)}  # ~85M params
    opt = FusedAdam(lr=1e-4)
    state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def adam_step(p, s):
        g = jax.tree.map(lambda a: a * jnp.bfloat16(1e-4), p)
        u, s = opt.update(g, s, p)
        p = jax.tree.map(lambda a, b: a + b, p, u)
        return p, s

    params, state = adam_step(params, state)
    float(params["l0"][0, 0])
    t0 = time.perf_counter()
    for _ in range(iters):
        params, state = adam_step(params, state)
    float(params["l0"][0, 0])
    n = sum(x.size for x in jax.tree.leaves(params))
    _emit(_suffix("fused_adam_step_ms_per_100M_params"),
          (time.perf_counter() - t0) / iters * 1e3 * (1e8 / n), "ms",
          params_m=round(n / 1e6, 1))


# ---------------------------------------------------------------------------
# 4. ResNet-50 DDP + SyncBatchNorm

def bench_ddp_syncbn():
    """Chip rate on whatever devices exist here, plus 8-way DP scaling shape
    measured on the virtual CPU mesh in a subprocess (dp=8 vs dp=1 on the
    same platform — the scaling ratio the ICI allreduce must beat)."""
    m = _imagenet()
    n_dev = len(jax.devices())
    batches, size, iters = ([128 * n_dev, 64 * n_dev], 224, 6) \
        if _on_tpu() else ([8], 32, 2)
    for batch in batches:
        argv = ["--arch", "resnet50", "--opt-level", "O2", "--sync_bn",
                "--batch-size", str(batch), "--image-size", str(size),
                "--iters", str(iters), "--print-freq", "1000"]
        try:
            dt = _timed_train(m, argv, iters)
        except Exception as e:
            if batch == batches[-1]:
                raise
            print(f"# ddp_syncbn batch {batch} failed "
                  f"({type(e).__name__}); retrying smaller", flush=True)
            continue
        _emit(_suffix("resnet50_ddp_syncbn_img_per_sec"), batch / dt,
              "img/s", devices=n_dev, batch=batch)
        return


def bench_ddp_scaling_virtual():
    """ResNet-50+SyncBN throughput on an 8-device virtual CPU mesh (the dp
    mesh follows the platform's device count). The dp=1 comparison runs in a
    separate 1-device subprocess; the parent computes the scaling ratio."""
    m = _imagenet()
    per, size, iters = 4, 32, 3
    n_dev = len(jax.devices())

    batch = per * n_dev
    argv = ["--arch", "resnet50", "--opt-level", "O2", "--sync_bn",
            "--batch-size", str(batch), "--image-size", str(size),
            "--iters", str(iters), "--print-freq", "1000"]
    m.train(m.parse_args(argv))
    t0 = time.perf_counter()
    m.train(m.parse_args(argv))
    ips = batch * iters / (time.perf_counter() - t0)
    _emit(f"resnet50_ddp_syncbn_{n_dev}dev_virtual", ips, "img/s",
          devices=n_dev)


# ---------------------------------------------------------------------------
# 5. GPT-2 TP loss parity vs single chip (virtual mesh)

def bench_gpt_tp_pp():
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    n_dev = len(jax.devices())
    if n_dev < 2:
        _emit("gpt2_tp2_loss_parity", float("nan"), "bool",
              note=f"needs >=2 devices, have {n_dev}")
        return
    cfg = GPTConfig(vocab_size=1024, max_seq=128, hidden=128, num_layers=4,
                    num_heads=4, dtype=jnp.float32, remat=False,
                    fused_loss=False)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, 1024)
    tgt = jnp.roll(tok, -1, 1)

    def run(tp):
        mesh = build_mesh(tp=tp, pp=1, sp=1, devices=jax.devices()[:tp])
        specs = gpt_param_specs(cfg)

        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                                  masked_axis=None)

        return float(jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=P()))(params, tok, tgt))

    single, tp2 = run(1), run(2)
    parity = bool(np.isclose(single, tp2, rtol=1e-4))
    _emit("gpt2_tp2_loss_parity_vs_single_chip", parity, "bool",
          single=round(single, 6), tp2=round(tp2, 6))


CONFIGS = {
    "resnet50_o1": (bench_resnet50_o1, False),
    "dcgan": (bench_dcgan, False),
    "microbench": (bench_microbench, False),
    "ddp_syncbn": (bench_ddp_syncbn, False),
    "ddp_scaling_virtual": (bench_ddp_scaling_virtual, True),
    "gpt_tp_pp": (bench_gpt_tp_pp, True),
}


def _run_virtual(names, n_devices):
    """Re-exec the named configs on an n-device virtual CPU platform and
    forward their JSON lines; returns them parsed."""
    env = dict(os.environ,
               APEX_TPU_BENCH_VIRTUAL="1",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                          f" --xla_force_host_platform_device_count"
                          f"={n_devices}"))
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)] + names,
                          env=env, check=False, capture_output=True, text=True)
    rows = []
    for line in proc.stdout.splitlines():
        try:
            rows.append(json.loads(line))
            print(line, flush=True)
        except json.JSONDecodeError:
            pass
    if proc.returncode != 0:
        tail = proc.stderr.strip().splitlines()[-3:]
        _emit("virtual_subprocess_FAILED", float("nan"), "error",
              configs=names, rc=proc.returncode, stderr_tail=" | ".join(tail))
    return rows


def main(argv=None):
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    names = list((argv if argv is not None else sys.argv[1:]) or CONFIGS)
    unknown = [n for n in names if n not in CONFIGS]
    for n in unknown:
        _emit(f"{n}_FAILED", float("nan"), "error",
              error=f"unknown config (choose from {sorted(CONFIGS)})")
    names = [n for n in names if n in CONFIGS]
    virtual = [n for n in names if CONFIGS[n][1]]
    local = [n for n in names if not CONFIGS[n][1]]
    if os.environ.get("APEX_TPU_BENCH_VIRTUAL"):
        local, virtual = names, []  # we ARE the subprocess
    else:
        from apex_tpu.utils.platform import pin_cpu_if_tunnel_dead

        # dead tunnel: run the local configs on the CPU protocol instead
        # of hanging on first backend touch (see bench.py)
        pin_cpu_if_tunnel_dead()

    for n in local:
        try:
            CONFIGS[n][0]()
        except Exception as e:
            _emit(f"{n}_FAILED", float("nan"), "error",
                  error=f"{type(e).__name__}: {str(e)[:200]}")

    if virtual:
        rows = _run_virtual(virtual, 8)
        if "ddp_scaling_virtual" in virtual:
            # same program on 1 virtual device -> the DP scaling ratio
            rows1 = _run_virtual(["ddp_scaling_virtual"], 1)
            v8 = next((r["value"] for r in rows
                       if r["metric"].startswith("resnet50_ddp_syncbn_8dev")),
                      None)
            v1 = next((r["value"] for r in rows1
                       if r["metric"].startswith("resnet50_ddp_syncbn_1dev")),
                      None)
            if v8 and v1:
                _emit("resnet50_ddp_syncbn_scaling_ratio_8dev_vs_1dev",
                      v8 / v1, "x", ideal=8.0)


if __name__ == "__main__":
    main()

"""Compressed-collective benchmark: bytes/step and step time, none vs int8
vs int8+ef, on the 8-chip (CPU-sim) dp mesh.

Emits ONE JSON line per policy plus a headline summary line — the bench.py
protocol. Bytes come from the compiled HLO via ``apex_tpu.comm.accounting``
(the same pricer the tier-1 wire-byte test asserts with); times are
measured, but on the CPU simulator collectives are memcpys, so the honest
headline here is the byte ratio — the time column becomes meaningful on a
real multi-chip slice where ICI is the bottleneck this subsystem attacks.

CPU-fallback canary pin (the bench.py round-5 lesson, PERF.md): this bench
always runs the 8-virtual-device CPU sim, so its time column is only
useful round-over-round if the protocol CANNOT drift — r04's canary
silently dropped 17% when a new flag default changed the timed program.
Every codec knob is therefore pinned explicitly below (``use_pallas=False``
above all: a future auto-Pallas-on-CPU flip would run interpret-mode
kernels and shift the time column without touching the bytes), and the
pinned protocol rides the summary line as ``canary_config`` so any future
change is visible in the artifact diff.

Run: ``python benchmarks/bench_comm.py`` (tier-1 box, no TPU needed).
"""

from __future__ import annotations

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_platform

pin_cpu_platform(virtual_devices=8)

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.comm import CompressionConfig, collective_report
from apex_tpu.monitor import json_record
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.parallel.mesh import build_mesh

# a GPT-2-124M-sized gradient set, as a few flat leaves (the bucketed DDP
# path concatenates them anyway); ~124M fp32 elements would swamp the CPU
# sim, so scale 1:16 and report bytes exactly, time as measured
LEAVES = {
    "embed": (768 * 3264,),
    "blocks": (12, 768 * 590),
    "head": (768,),
}
STEPS = 10

# the pinned canary protocol: every knob explicit (see module docstring)
CANARY = dict(block_size=256, min_elements=2048, stochastic_rounding=False,
              use_pallas=False)

POLICIES = {
    "none": None,
    "int8": CompressionConfig(policy="int8", **CANARY),
    "int8_ef": CompressionConfig(policy="int8_ef", **CANARY),
    # the sub-8-bit tier: group size rides the same canary block_size
    # (256 — even, so nibble packing holds), every other knob pinned
    "int4": CompressionConfig(policy="int4", **CANARY),
    "int4_ef": CompressionConfig(policy="int4_ef", **CANARY),
}


def build(policy_name):
    mesh = build_mesh(tp=1, pp=1, sp=1)  # dp=8
    cfg = POLICIES[policy_name]
    ddp = DistributedDataParallel(compression=cfg,
                                  allreduce_always_fp32=True)
    grads = {
        k: jax.random.normal(jax.random.fold_in(jax.random.PRNGKey(0),
                                                i), shape)
        for i, (k, shape) in enumerate(LEAVES.items())
    }
    ef = ddp.init_comm_state(grads)

    if ef is None:
        def body(g):
            return ddp.average_gradients(g)

        step = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False))
        compiled = step.lower(grads).compile()
        return compiled, grads, None

    def body(g, r):
        r = jax.tree_util.tree_map(lambda x: x[0], r)
        out, r = ddp.average_gradients(g, comm_state=r)
        return out, jax.tree_util.tree_map(lambda x: x[None], r)

    step = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), P("dp")),
        out_specs=(P(), P("dp")), check_vma=False))
    residual = jax.tree_util.tree_map(
        lambda g: jnp.zeros((8,) + g.shape, jnp.float32), grads)
    compiled = step.lower(grads, residual).compile()
    return compiled, grads, residual


def run(policy_name):
    compiled, grads, residual = build(policy_name)
    rep = collective_report(compiled)
    args = (grads,) if residual is None else (grads, residual)
    out = compiled(*args)  # warmup
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = compiled(*args)
    # async-dispatch fence: host-read one scalar of the last step
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf[..., :1]))
    dt = (time.perf_counter() - t0) / STEPS
    n_elems = sum(math.prod(s) for s in LEAVES.values())
    return {
        "policy": policy_name,
        "grad_elements": n_elems,
        "wire_bytes_per_step": round(rep.wire_bytes),
        "collective_counts": {k: v for k, v in rep.counts.items() if v},
        "step_time_ms": round(dt * 1e3, 3),
    }


def main():
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    rows = {}
    for name in POLICIES:
        r = run(name)
        rows[name] = r
        print(json_record(**r), flush=True)
    def ratio(name):
        return round(rows["none"]["wire_bytes_per_step"]
                     / max(rows[name]["wire_bytes_per_step"], 1), 2)

    print(json_record(
        name="comm_compression_wire_reduction",
        metric="fp32_bytes / quantized_bytes",
        int8=ratio("int8"),
        int8_ef=ratio("int8_ef"),
        int4=ratio("int4"),
        int4_ef=ratio("int4_ef"),
        # the stage-17 gated column: absolute int4 wire bytes per step
        # (lower-better under monitor.regress's wire_bytes_int4 rule)
        wire_bytes_int4=rows["int4_ef"]["wire_bytes_per_step"],
        backend=jax.default_backend(),
        canary_config=dict(CANARY, steps=STEPS,
                           grad_elements=rows["none"]["grad_elements"]),
    ), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

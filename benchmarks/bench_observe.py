"""Observability overhead A/B — what does the fleet plane cost?

The ISSUE-14 gate: monitor tier 3 (distributed tracing + per-worker
flight rings + FleetScraper + alert rules) must cost ≤ ~5% tokens/s on
the loadgen serving workload, or it is not an always-on plane. This
bench runs the SAME seeded Poisson+burst workload through a 2-host
disaggregated cluster twice:

* **on** — full fleet observability: every event JSONL-sunk with trace
  ids bound, flight rings armed, FleetScraper + an alert rule evaluated
  every tick;
* **off** — the floor: no sink, no rings, no scraping, no rules.

ONE ``json_record`` line carries ``tokens_per_s_on/off``, the
``observe_overhead_pct`` delta (the ok gate, ``--overhead-tol``),
``scrape_ms_p50/p99`` (the scraper measuring itself), ``events_per_s``
written to the sink, ``alerts_fired_total`` and
``trace_stitch_failures`` (must be 0 — broken stitching is broken
observability, not overhead). ``tpu_watch.sh`` stage 19 banks
``OBSERVE_TPU.json``, regression-gated via ``python -m
apex_tpu.monitor.regress --tol 0.15``; CPU rehearsals carry
``_CPU_FALLBACK`` and never promote — the ≤ 5% claim is a TPU truth
(CPU decode steps are ~10× slower, flattering the overhead).

Run: ``python benchmarks/bench_observe.py [--out FILE]``.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import argparse

    from apex_tpu.utils.platform import (
        pin_cpu_if_requested,
        pin_cpu_if_tunnel_dead,
        pin_cpu_platform,
    )

    pin_cpu_if_requested()
    pin_cpu_if_tunnel_dead()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        pin_cpu_platform()

    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())  # after the pin: backend is final

    from apex_tpu.monitor import (
        AlertRule,
        Condition,
        EventLog,
        JsonlSink,
        SloSpec,
        json_record,
        stitch_traces,
    )
    from apex_tpu.serve import (
        ClusterConfig,
        RouterConfig,
        ServeCluster,
        ServeConfig,
    )
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from loadgen import WorkloadConfig, build_workload, run_workload

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--n-requests", type=int, default=64)
    ap.add_argument("--rate-rps", type=float, default=8.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overhead-tol", type=float, default=0.05,
                    help="max tokens/s fraction the full plane may cost "
                         "(the ok gate; ISSUE-14 pins 5%%)")
    ap.add_argument("--trace-dir", default=None,
                    help="keep the ON pass's events.jsonl + trace.json "
                         "here (default: a temp dir, discarded)")
    args = ap.parse_args(argv)

    on_tpu = jax.default_backend() == "tpu"
    name = "gpt_serve_observe_ab"
    if not on_tpu:
        name += "_CPU_FALLBACK"

    # the pinned bench model (bench_serve.py / bench_serve_mh constants)
    HIDDEN, LAYERS, HEADS, VOCAB, MAX_SEQ = 128, 2, 8, 512, 256
    SLOTS, BLOCK_SIZE = 4, 16
    cfg = GPTConfig(vocab_size=VOCAB, max_seq=MAX_SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS,
                    dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    wcfg = WorkloadConfig(n_requests=args.n_requests,
                          rate_rps=args.rate_rps, seed=args.seed,
                          prompt_len_max=MAX_SEQ // 2)
    workload = build_workload(wcfg, VOCAB, MAX_SEQ)
    slo = SloSpec(ttft_ms=2000.0, tpot_ms=200.0)
    scfg = ServeConfig(num_slots=SLOTS, block_size=BLOCK_SIZE,
                       prefix_cache=False)

    class _CountingSink:
        """JsonlSink shim counting records so events/s is measured at
        the sink boundary (what durable observability actually wrote)."""

        def __init__(self, inner):
            self.inner = inner
            self.n = 0

        def write(self, **fields):
            self.n += 1
            self.inner.write(**fields)

        def flush(self):
            self.inner.flush()

    def run(observe: bool, trace_dir=None):
        if observe:
            sink = _CountingSink(JsonlSink(
                os.path.join(trace_dir, "events.jsonl"),
                buffer_steps=64, rotate_bytes=32 << 20))
            events = EventLog(sink=sink, keep=True)
            ccfg = ClusterConfig(
                n_prefill=1, n_decode=1, serve=scfg,
                router=RouterConfig(slo=slo),
                scrape_every=1, flight_capacity=2048,
                alert_rules=(AlertRule("backlog_high", conditions=(
                    Condition("queued_tokens", ">", 4.0 * MAX_SEQ),)),))
        else:
            sink = None
            events = None
            ccfg = ClusterConfig(
                n_prefill=1, n_decode=1, serve=scfg,
                router=RouterConfig(slo=slo),
                scrape_every=0, flight_capacity=0)
        cl = ServeCluster(params, cfg, ccfg, retain_streams=False,
                          events=events)
        t0 = time.perf_counter()
        stats = run_workload(cl, workload)
        wall = time.perf_counter() - t0
        if observe:
            sink.inner.close()
        return cl, stats, wall, sink

    # warm pass compiles the programs so neither timed pass pays XLA
    run(False)

    with tempfile.TemporaryDirectory() as tmp:
        trace_dir = args.trace_dir or tmp
        os.makedirs(trace_dir, exist_ok=True)
        cl_on, st_on, wall_on, sink = run(True, trace_dir)
        stitch = stitch_traces(cl_on._events.records)
        if args.trace_dir:
            from apex_tpu.monitor import write_chrome_trace

            write_chrome_trace(os.path.join(trace_dir, "trace.json"),
                               cl_on._events.records)
    cl_off, st_off, wall_off, _ = run(False)

    tps_on = st_on.get("generated_tokens", 0) / wall_on
    tps_off = st_off.get("generated_tokens", 0) / wall_off
    overhead = (tps_off - tps_on) / tps_off if tps_off else None
    fleet = cl_on.stats()["fleet"]
    streams_equal = (st_on.get("completed") == st_off.get("completed")
                     and st_on.get("generated_tokens")
                     == st_off.get("generated_tokens"))
    ok = bool(streams_equal
              and stitch["stitch_failures"] == 0
              and overhead is not None
              and overhead <= args.overhead_tol)
    rec = {
        "metric": name,
        "ok": ok,
        "tokens_per_s_on": round(tps_on, 3),
        "tokens_per_s_off": round(tps_off, 3),
        "observe_overhead_pct": (round(100 * overhead, 2)
                                 if overhead is not None else None),
        "overhead_tol_pct": round(100 * args.overhead_tol, 2),
        # observation must never perturb the WORK: same tokens out
        "streams_equal": streams_equal,
        "events_per_s": round(sink.n / wall_on, 1) if wall_on else None,
        "events_total": sink.n,
        "scrape_ms_p50": fleet.get("scrape_ms_p50"),
        "scrape_ms_p99": fleet.get("scrape_ms_p99"),
        "scrapes_total": fleet.get("scrapes_total"),
        "scrape_coverage": fleet.get("scrape_coverage"),
        "alerts_fired_total": fleet["alerts"]["alerts_fired_total"],
        "trace_stitch_failures": stitch["stitch_failures"],
        "traces_minted": fleet.get("traces_minted"),
        "goodput_rps_on": st_on.get("goodput_rps"),
        "goodput_rps_off": st_off.get("goodput_rps"),
        "fleet_goodput_rps": st_on.get("fleet_goodput_rps"),
        "completed": st_on.get("completed"),
        "workload": {"n": wcfg.n_requests, "rate_rps": wcfg.rate_rps,
                     "seed": wcfg.seed, "mode": wcfg.mode},
        "backend": jax.default_backend(),
    }
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Serving benchmark: continuous-batching engine throughput + latency.

One ``json_record`` line (the bench.py protocol): tokens/s, TTFT p50/p99,
mean slot occupancy, decode-step p50 ms and the KV byte model for a fixed
mixed-length request workload through ``apex_tpu.serve.InferenceEngine``.
The KV/collective byte columns join the ``comm.accounting`` convention
(modeled bytes, stated as such).

Honesty notes baked into the record: the metric name gains a
``_CPU_FALLBACK`` suffix off-chip (CPU rehearsal numbers must never be
read as TPU serving throughput), and on a single chip the
``tp_sharded_serving`` column says "needs a slice" — the TP-sharded
decode path (vocab-gathered logits, sharded heads) has no ring to measure
until a multi-chip window, exactly like ``bench_overlap.py``.

Run: ``python benchmarks/bench_serve.py [--out FILE]``. Staged as
``tpu_watch.sh`` stage 9 (hourly retry until banked).

``--loadgen`` switches to the monitor-tier-2 goodput-under-SLO bench:
``benchmarks/loadgen.py`` drives the engine with a seeded Poisson+burst
workload and the line becomes goodput req/s + TTFT/TPOT p50/p99 from the
streaming histograms + SLO violation counts (watcher stage 10, regression
-gated against the banked record via ``apex_tpu.monitor.regress``).
Extra args after ``--loadgen`` pass through (``--n-requests``,
``--rate-rps``, ``--prefix-pool``, ``--trace-dir``, budgets — see
``loadgen.py``). Watcher stage 11 runs ``--loadgen --prefix-pool 2
--spec-k 4`` — the shared-prefix + speculative workload whose record
(``SERVE_PREFIX_TPU.json``, prefix-hit and acceptance rates included)
must materially beat the plain stage-10 goodput on the same hardware.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import (
    pin_cpu_if_requested,
    pin_cpu_if_tunnel_dead,
    pin_cpu_platform,
)

pin_cpu_if_requested()
pin_cpu_if_tunnel_dead()  # don't hang the watcher on a dead tunnel
if os.environ.get("JAX_PLATFORMS") == "cpu":
    pin_cpu_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

ON_TPU = jax.default_backend() == "tpu"

# the pinned protocol (canary discipline, see bench_comm.py): one fixed
# model + workload so the line is comparable round-over-round
HIDDEN, LAYERS, HEADS, VOCAB, MAX_SEQ = 128, 2, 8, 512, 256
SLOTS, BLOCK_SIZE, MAX_NEW = 4, 16, 32
PREFILL_CHUNK = 32
PROMPT_LENS = (5, 17, 40, 9, 33, 12, 60, 25)


def main() -> int:
    import argparse
    import statistics
    import tempfile

    from apex_tpu.monitor import JsonlSink, json_record, read_jsonl
    from apex_tpu.serve import InferenceEngine, Request, ServeConfig
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"])
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft length (0: off)")
    ap.add_argument("--loadgen", action="store_true",
                    help="run the goodput-under-SLO loadgen bench instead")
    args, extra = ap.parse_known_args()

    if args.loadgen:
        # the tier-2 record: loadgen drives the engine, SLO accounting
        # emits the line (same --out contract, extra args pass through)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from loadgen import main as loadgen_main

        fwd = list(extra) + ["--kv-quant", args.kv_quant,
                             "--spec-k", str(args.spec_k)]
        if args.out:
            fwd += ["--out", args.out]
        return loadgen_main(fwd)
    if extra:
        ap.error(f"unrecognized arguments: {' '.join(extra)}")

    name = "gpt_serve_engine"
    if not ON_TPU:
        name += "_CPU_FALLBACK"

    cfg = GPTConfig(vocab_size=VOCAB, max_seq=MAX_SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS,
                    dtype=jnp.bfloat16 if ON_TPU else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    requests = [
        Request(f"r{i}", rng.integers(0, VOCAB, size=p).tolist(),
                max_new_tokens=MAX_NEW)
        for i, p in enumerate(PROMPT_LENS)
    ]

    step_log = os.path.join(tempfile.mkdtemp(), "serve_steps.jsonl")
    with JsonlSink(step_log, buffer_steps=1) as sink:
        eng = InferenceEngine(
            params, cfg,
            ServeConfig(num_slots=SLOTS, block_size=BLOCK_SIZE,
                        kv_quant=args.kv_quant,
                        prefill_chunk=PREFILL_CHUNK, spec_k=args.spec_k),
            sink=sink)
        out = eng.run(requests)
        tokens_per_s = eng.throughput()
        stats = eng.stats()  # TTFT/step quantiles from the streaming hists
        kv_budget = eng.kv_budget_bytes()
        compiles = eng.compile_counts()
    steps = [r for r in read_jsonl(step_log)
             if r.get("phase") == "decode"]
    gen_tokens = sum(len(v) for v in out.values())

    rec = {
        "metric": name,
        "ok": len(out) == len(requests),
        "tokens_per_s": round(tokens_per_s, 3) if tokens_per_s else None,
        "generated_tokens": gen_tokens,
        "ttft_ms_p50": stats.get("ttft_ms_p50"),
        "ttft_ms_p99": stats.get("ttft_ms_p99"),
        "tpot_ms_p50": stats.get("tpot_ms_p50"),
        "decode_step_ms_p50": stats.get("decode_step_ms_p50"),
        "mean_occupancy": round(
            statistics.fmean(r["occupancy"] for r in steps), 4)
        if steps else None,
        "kv_cache_budget_bytes": kv_budget,
        "kv_read_bytes_peak": max((r["kv_read_bytes"] for r in steps),
                                  default=None),
        "kv_quant": args.kv_quant,
        # the tightened compile gate: 1 chunked prefill + 1 decode
        # (+ <= 1 verify when speculation is on) — no bucket ladder
        "compilations": compiles,
        "prefill_chunk": PREFILL_CHUNK,
        "prefix_hit_rate": stats.get("prefix_hit_rate"),
        "spec_acceptance_rate": stats.get("spec_acceptance_rate"),
        "spec_k": args.spec_k,
        # the TP-sharded serving path (sharded heads, gathered logits)
        # needs a multi-chip slice; a single chip has nothing to shard
        "tp_sharded_serving": ("needs a slice"
                               if len(jax.devices()) < 2 else "untested"),
        "config": {"hidden": HIDDEN, "layers": LAYERS, "heads": HEADS,
                   "vocab": VOCAB, "slots": SLOTS,
                   "block_size": BLOCK_SIZE, "max_new": MAX_NEW,
                   "prompts": list(PROMPT_LENS)},
        "backend": jax.default_backend(),
    }
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

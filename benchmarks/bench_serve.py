"""Serving benchmark: continuous-batching engine throughput + latency.

One ``json_record`` line (the bench.py protocol): tokens/s, TTFT p50/p99,
mean slot occupancy, decode-step p50 ms and the KV byte model for a fixed
mixed-length request workload through ``apex_tpu.serve.InferenceEngine``.
The KV/collective byte columns join the ``comm.accounting`` convention
(modeled bytes, stated as such).

Honesty notes baked into the record: the metric name gains a
``_CPU_FALLBACK`` suffix off-chip (CPU rehearsal numbers must never be
read as TPU serving throughput), and on a single chip the
``tp_sharded_serving`` column says "needs a slice" — the TP-sharded
decode path (vocab-gathered logits, sharded heads) has no ring to measure
until a multi-chip window, exactly like ``bench_overlap.py``.

Run: ``python benchmarks/bench_serve.py [--out FILE]``. Staged as
``tpu_watch.sh`` stage 9 (hourly retry until banked).

``--megakernel {auto,on,off}`` selects the fused per-layer decode block
(``serve.megakernel``; the record's ``decode_kernel`` field says which
path actually served). ``--megakernel-ab`` runs the SAME workload twice —
megakernel on, then off — and emits one A/B record whose headline fields
come from the fused side (watcher stage 12, ``DECODE_FUSED_TPU.json``,
regression-gated like stages 10/11). The A/B is a TPU measurement: on
CPU the fused block only exists in interpret mode (a simulator, not a
perf number), so the record honestly says ``megakernel_ab: needs a
chip`` and carries the per-op-path numbers under the ``_CPU_FALLBACK``
metric suffix.

``--model {pinned,flagship}`` picks the served model: ``pinned`` is the
small canary above; ``flagship`` is the GPT-2-124M serve shape (768
hidden, 12 layers, 50304 vocab — per-layer bf16 weights OVER the 10 MB
VMEM budget, so only the tier-2 weight-streaming tiles can serve it
fused). Watcher stage 23 runs ``--megakernel-ab --spec-k 4 --model
flagship`` (``DECODE_FUSED_T2_TPU.json``): the record must show
``decode_kernel`` AND ``verify_kernel`` ``== "fused"`` on the fused
side — the lifted-gate acceptance measurement.

``--loadgen`` switches to the monitor-tier-2 goodput-under-SLO bench:
``benchmarks/loadgen.py`` drives the engine with a seeded Poisson+burst
workload and the line becomes goodput req/s + TTFT/TPOT p50/p99 from the
streaming histograms + SLO violation counts (watcher stage 10, regression
-gated against the banked record via ``apex_tpu.monitor.regress``).
Extra args after ``--loadgen`` pass through (``--n-requests``,
``--rate-rps``, ``--prefix-pool``, ``--trace-dir``, budgets — see
``loadgen.py``). Watcher stage 11 runs ``--loadgen --prefix-pool 2
--spec-k 4`` — the shared-prefix + speculative workload whose record
(``SERVE_PREFIX_TPU.json``, prefix-hit and acceptance rates included)
must materially beat the plain stage-10 goodput on the same hardware.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import (
    pin_cpu_if_requested,
    pin_cpu_if_tunnel_dead,
    pin_cpu_platform,
)

pin_cpu_if_requested()
pin_cpu_if_tunnel_dead()  # don't hang the watcher on a dead tunnel
if os.environ.get("JAX_PLATFORMS") == "cpu":
    pin_cpu_platform()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

ON_TPU = jax.default_backend() == "tpu"

# the pinned protocol (canary discipline, see bench_comm.py): one fixed
# model + workload so the line is comparable round-over-round. The
# flagship row is the GPT-2-124M serve shape the tier-2 megakernel
# gate-lift targets (per-layer bf16 weights > 10 MB — full residency
# refuses, weight-tile streaming serves it fused).
MODELS = {
    "pinned": dict(hidden=128, layers=2, heads=8, vocab=512, max_seq=256),
    "flagship": dict(hidden=768, layers=12, heads=12, vocab=50304,
                     max_seq=1024),
}
SLOTS, BLOCK_SIZE, MAX_NEW = 4, 16, 32
PREFILL_CHUNK = 32
PROMPT_LENS = (5, 17, 40, 9, 33, 12, 60, 25)


def main() -> int:
    import argparse
    import statistics
    import tempfile

    from apex_tpu.monitor import JsonlSink, json_record, read_jsonl
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    from apex_tpu.serve import InferenceEngine, Request, ServeConfig
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"])
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft length (0: off)")
    ap.add_argument("--megakernel", default="auto",
                    choices=["auto", "on", "off"],
                    help="fused per-layer decode block (serve.megakernel)")
    ap.add_argument("--megakernel-ab", action="store_true",
                    help="run the workload megakernel-on AND -off, emit "
                         "one A/B record (watcher stage 12)")
    ap.add_argument("--model", default="pinned", choices=sorted(MODELS),
                    help="served model: the pinned canary or the GPT-2-"
                         "124M flagship serve shape (watcher stage 23)")
    ap.add_argument("--loadgen", action="store_true",
                    help="run the goodput-under-SLO loadgen bench instead")
    args, extra = ap.parse_known_args()
    if args.megakernel_ab and args.loadgen:
        ap.error("--megakernel-ab runs the fixed A/B workload; it cannot "
                 "be combined with --loadgen")
    if args.megakernel_ab and args.megakernel == "off":
        ap.error("--megakernel-ab measures the fused side; "
                 "--megakernel off contradicts it")

    if args.loadgen:
        # the tier-2 record: loadgen drives the engine, SLO accounting
        # emits the line (same --out contract, extra args pass through)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from loadgen import main as loadgen_main

        fwd = list(extra) + ["--kv-quant", args.kv_quant,
                             "--spec-k", str(args.spec_k),
                             "--megakernel", args.megakernel]
        if args.out:
            fwd += ["--out", args.out]
        return loadgen_main(fwd)
    if extra:
        ap.error(f"unrecognized arguments: {' '.join(extra)}")

    name = ("gpt_serve_decode_fused_ab" if args.megakernel_ab
            else "gpt_serve_engine")
    if args.model == "flagship":
        name += "_124m"
    if not ON_TPU:
        name += "_CPU_FALLBACK"

    model = MODELS[args.model]
    cfg = GPTConfig(vocab_size=model["vocab"], max_seq=model["max_seq"],
                    hidden=model["hidden"], num_layers=model["layers"],
                    num_heads=model["heads"],
                    dtype=jnp.bfloat16 if ON_TPU else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model["vocab"], size=p).tolist()
               for p in PROMPT_LENS]

    def run_engine(megakernel):
        """One full workload pass -> (measurement sub-record, streams);
        fresh Request objects each pass (the engine consumes them)."""
        requests = [Request(f"r{i}", list(p), max_new_tokens=MAX_NEW)
                    for i, p in enumerate(prompts)]
        step_log = os.path.join(tempfile.mkdtemp(), "serve_steps.jsonl")
        with JsonlSink(step_log, buffer_steps=1) as sink:
            eng = InferenceEngine(
                params, cfg,
                ServeConfig(num_slots=SLOTS, block_size=BLOCK_SIZE,
                            kv_quant=args.kv_quant,
                            prefill_chunk=PREFILL_CHUNK,
                            spec_k=args.spec_k, megakernel=megakernel),
                sink=sink)
            out = eng.run(requests)
            tokens_per_s = eng.throughput()
            stats = eng.stats()  # quantiles from the streaming hists
            kv_budget = eng.kv_budget_bytes()
            compiles = eng.compile_counts()
        steps = [r for r in read_jsonl(step_log)
                 if r.get("phase") == "decode"]
        return {
            "ok": len(out) == len(requests),
            # which decode path actually served (fused|pallas|reference):
            # lets the stage-12 gate tell a kernel fallback from a real
            # regression
            "decode_kernel": stats.get("decode_kernel"),
            "tokens_per_s": round(tokens_per_s, 3) if tokens_per_s
            else None,
            "generated_tokens": sum(len(v) for v in out.values()),
            "ttft_ms_p50": stats.get("ttft_ms_p50"),
            "ttft_ms_p99": stats.get("ttft_ms_p99"),
            "tpot_ms_p50": stats.get("tpot_ms_p50"),
            "decode_step_ms_p50": stats.get("decode_step_ms_p50"),
            "decode_step_ms_p99": stats.get("decode_step_ms_p99"),
            # the verify jit site's path + latency (None when spec_k=0
            # or no slot ever proposed): the stage-23 verify A/B columns
            "verify_kernel": stats.get("verify_kernel"),
            "verify_step_ms_p50": stats.get("verify_step_ms_p50"),
            "verify_step_ms_p99": stats.get("verify_step_ms_p99"),
            "mean_occupancy": round(
                statistics.fmean(r["occupancy"] for r in steps), 4)
            if steps else None,
            "kv_cache_budget_bytes": kv_budget,
            "kv_read_bytes_peak": max((r["kv_read_bytes"] for r in steps),
                                      default=None),
            # the tightened compile gate: 1 chunked prefill + 1 decode
            # (+ <= 1 verify when speculation is on) — no bucket ladder
            "compilations": compiles,
            "prefix_hit_rate": stats.get("prefix_hit_rate"),
            "spec_acceptance_rate": stats.get("spec_acceptance_rate"),
        }, out

    # the headline run; in A/B mode the fused side is the headline (what
    # stage 12 regression-tracks), forced on only where it is a real
    # measurement (compiled Mosaic, not the interpreter)
    mega = args.megakernel
    if args.megakernel_ab:
        mega = "on" if ON_TPU else "auto"
    head, out = run_engine(mega)

    rec = {"metric": name, **head}
    if args.megakernel_ab:
        if ON_TPU:
            # same workload, per-op layer body: the denominator. Streams
            # must be EQUAL (the parity oracle) — a divergence means the
            # A/B measured different work, so it FAILS the bench (ok:
            # false + exit 1; the stage-12 gate additionally refuses to
            # promote a record whose streams diverged).
            base, out_off = run_engine("off")
            rec["megakernel_ab"] = {"fused_on": head, "fused_off": base}
            rec["streams_equal"] = out == out_off
            rec["ok"] = bool(rec["ok"] and base["ok"]
                             and rec["streams_equal"])
            p_on, p_off = (head.get("decode_step_ms_p50"),
                           base.get("decode_step_ms_p50"))
            rec["decode_step_speedup_p50"] = (
                round(p_off / p_on, 4) if p_on and p_off else None)
        else:
            # off-chip the fused block is interpret mode — a simulator,
            # not a measurement (the stage-12 gate never promotes this)
            rec["megakernel_ab"] = "needs a chip"
    rec.update({
        "kv_quant": args.kv_quant,
        "prefill_chunk": PREFILL_CHUNK,
        "spec_k": args.spec_k,
        # the TP-sharded serving path (sharded heads, gathered logits)
        # needs a multi-chip slice; a single chip has nothing to shard
        "tp_sharded_serving": ("needs a slice"
                               if len(jax.devices()) < 2 else "untested"),
        "config": {"model": args.model, "hidden": model["hidden"],
                   "layers": model["layers"], "heads": model["heads"],
                   "vocab": model["vocab"], "slots": SLOTS,
                   "block_size": BLOCK_SIZE, "max_new": MAX_NEW,
                   "prompts": list(PROMPT_LENS),
                   "megakernel": mega},  # the mode actually run
        "backend": jax.default_backend(),
    })
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    # ok:false (e.g. A/B stream divergence) is a bench FAILURE, not a
    # slow record — the exit code is the first gate stage 12 sees
    return 0 if rec.get("ok", True) else 1


if __name__ == "__main__":
    sys.exit(main())

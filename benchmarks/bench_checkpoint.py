"""Checkpoint-path benchmark: sync vs async save latency, bytes, restore.

The resilience layer's claim is that durability stays off the step's
critical path: an async ``CheckpointManager.save`` should cost the caller
only the device→host transfer + checksum pass, with serialization and the
atomic publish hidden on the worker thread. This bench measures exactly
that split on a GPT-2-124M-shaped state (1:4 scale so the CPU box stays
fast) and emits ONE JSON line — the ``bench.py`` / ``monitor.json_record``
protocol — so checkpoint overhead joins the BENCH_* trajectory:

* ``sync_save_ms`` — full blocking save (transfer + serialize + publish)
* ``async_submit_ms`` — what the train loop actually pays per async save
* ``async_drain_ms`` — worker time to finish the same save
* ``restore_ms`` — verified restore (manifest + crc + unflatten)
* ``verify_ms`` — ``latest_valid()`` discovery cost
* ``bytes`` — manifest-accounted checkpoint payload

Run: ``python benchmarks/bench_checkpoint.py`` (tier-1 box, no TPU).
"""

from __future__ import annotations

import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

from apex_tpu.monitor import json_record
from apex_tpu.resilience import CheckpointManager

# a GPT-2-124M-shaped train state at 1:4 scale: params + 2 Adam moments
# (fp32) + a handful of small leaves, ~93 MB on disk
LEAVES = {
    "embed": (768, 3264),
    "blocks": (12, 768, 590),
    "head": (768,),
}
REPS = 5


def build_state():
    key = jax.random.PRNGKey(0)
    params = {
        k: jax.random.normal(jax.random.fold_in(key, i), shape,
                             dtype=jnp.float32)
        for i, (k, shape) in enumerate(LEAVES.items())
    }
    return {
        "params": params,
        "mu": jax.tree.map(jnp.zeros_like, params),
        "nu": jax.tree.map(jnp.ones_like, params),
        "count": jnp.asarray(123, jnp.int32),
    }


def timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return (time.perf_counter() - t0) * 1000.0


def main() -> None:
    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())
    state = build_state()
    jax.block_until_ready(state)
    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        sync_mgr = CheckpointManager(os.path.join(root, "sync"),
                                     keep_last_n=2, fsync=False)
        sync_ms = [timed(lambda s=s: sync_mgr.save(state, s))
                   for s in range(REPS)]

        amgr = CheckpointManager(os.path.join(root, "async"),
                                 async_save=True, keep_last_n=2, fsync=False)
        submit_ms, drain_ms = [], []
        for s in range(REPS):
            submit_ms.append(timed(lambda s=s: amgr.save(state, s)))
            drain_ms.append(timed(amgr.wait))
        amgr.close()

        bytes_ = sync_mgr.last_save_bytes
        latest = sync_mgr.latest_valid()
        verify_ms = timed(lambda: sync_mgr.latest_valid())
        template = jax.tree.map(jnp.zeros_like, state)
        restore_ms = timed(
            lambda: sync_mgr.restore(target=template, path=latest))

        med = statistics.median
        print(json_record(
            bench="checkpoint",
            bytes=bytes_,
            sync_save_ms=round(med(sync_ms), 3),
            async_submit_ms=round(med(submit_ms), 3),
            async_drain_ms=round(med(drain_ms), 3),
            restore_ms=round(restore_ms, 3),
            verify_ms=round(verify_ms, 3),
            hidden_fraction=round(
                1.0 - med(submit_ms) / max(med(sync_ms), 1e-9), 4),
            reps=REPS,
        ))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()

"""Hardware smoke of every compiled (non-interpret) Pallas kernel path.

The CPU test suite validates these kernels in Pallas interpret mode; this
script executes the COMPILED kernels on the real chip — the paths that
have never run anywhere else (VERDICT r2 weak #6): flash attention
fwd/bwd, in-kernel counter-dropout determinism, varlen block-skip
fwd/bwd, Pallas LayerNorm fwd/bwd at small and large hidden, fused
LM-head+CE, scaled softmax, and label-smoothing CE. Target < 2 min.

Run: ``python benchmarks/smoke_tpu.py [--out smoke.json]``. Each kernel
records pass/fail + max-error vs the XLA reference; exit code 1 if any
fail. On a non-TPU backend the same drives run with ``use_pallas`` left
to its default (reference fallback), flagged in the JSON: every
Pallas-kernel row there is marked NOT ok — a dry rehearsal exercises the
harness, it is not kernel evidence, and the exit code says so.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_if_requested

pin_cpu_if_requested()

import jax


def _results():
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    force = True if on_tpu else None  # force the compiled Pallas path on TPU
    k = jax.random.PRNGKey(0)
    out = []

    def record(name, fn, tol=5e-2, zero_is_fallback=False,
               pallas_row=False):
        # ok requires err WITHIN the per-kernel tolerance (advisor r3): a
        # finite-but-large error vs the XLA reference must fail the gate,
        # not pass it. tol=0.0 demands bitwise equality (dropout determinism).
        # zero_is_fallback: a kernel compared against a separately-computed
        # matmul-precision-highest reference cannot be bitwise equal —
        # err == 0.0 means the Pallas path silently fell back and the row
        # compared the reference against itself (round-4 find: the first
        # committed smoke's attention rows were exactly this, and the
        # CPU-rehearsal artifact later overwrote the real one looking all
        # green). Such a row is not kernel evidence on ANY backend, so it
        # must FAIL, not pass — which also makes the CPU rehearsal's exit
        # code honest (the harness ran; the kernels were not exercised).
        t0 = time.perf_counter()
        try:
            err = float(fn())
            ok = bool(np.isfinite(err) and err <= tol)
            row = {"kernel": name, "ok": ok, "max_err": err, "tol": tol,
                   "seconds": round(time.perf_counter() - t0, 2)}
            if zero_is_fallback and err == 0.0:
                row["ok"] = False
                row["error"] = ("err == 0.0: kernel-vs-reference cannot be "
                                "bitwise equal; the Pallas path fell back "
                                "(not kernel evidence)")
            if pallas_row and not on_tpu:
                # off-TPU the drive runs reference fallbacks whose rows can
                # still look green (reviewer find: the dropout fallback is
                # also seed-deterministic, the dense LM-head is ~1e-7 from
                # loss_ref) — a rehearsal row is never kernel evidence
                row["ok"] = False
                row.setdefault("error", "CPU rehearsal: reference fallback, "
                                        "not kernel evidence")
            out.append(row)
        except Exception as e:  # noqa: BLE001 — record, keep smoking
            out.append({"kernel": name, "ok": False,
                        "error": f"{type(e).__name__}: {str(e)[:300]}",
                        "seconds": round(time.perf_counter() - t0, 2)})
        print(json.dumps(out[-1]), file=sys.stderr, flush=True)

    from apex_tpu.ops.attention import attention_reference, flash_attention

    # Attention runs in bf16 — the model dtype the kernels exist for. The
    # reference is traced under matmul precision "highest" so its fp32
    # einsums are true fp32 even on TPU (the default lowers fp32 dots to
    # one bf16 MXU pass, making the *reference* bf16-accurate — round-4
    # find: per-element relative error between two bf16-class results on
    # near-zero outputs read as O(1) "failures" on a correct kernel).
    # Error metric: max |a-b| normalized by the reference's max |b| —
    # scale-relative, stable at near-zero entries.
    b, h, s, d = 2, 4, 1024, 64
    q = jax.random.normal(k, (b, h, s, d), jnp.bfloat16)
    kk = jax.random.normal(jax.random.fold_in(k, 1), (b, h, s, d),
                           jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(k, 2), (b, h, s, d),
                          jnp.bfloat16)

    def nerr(got, want):
        """max-abs error normalized by the reference tensor's scale."""
        return max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b_.astype(jnp.float32)))
                  / (jnp.max(jnp.abs(b_.astype(jnp.float32))) + 1e-12))
            for a, b_ in zip(got, want))

    def ref_grad(loss_ref, argnums, *args):
        with jax.default_matmul_precision("highest"):
            return jax.jit(jax.grad(loss_ref, argnums=argnums))(*args)

    def flash_fwd_bwd():
        def loss(q, kk, v):
            return jnp.sum(flash_attention(q, kk, v, causal=True,
                                           use_pallas=force)
                           .astype(jnp.float32) ** 2)

        def loss_ref(q, kk, v):
            return jnp.sum(attention_reference(q, kk, v, causal=True)
                           .astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, kk, v)
        gr = ref_grad(loss_ref, (0, 1, 2), q, kk, v)
        jax.block_until_ready(g)
        return nerr(g, gr)

    record("flash_attention_fwd_bwd_causal", flash_fwd_bwd, tol=2e-2,
           zero_is_fallback=True, pallas_row=True)

    def dropout_determinism():
        f = jax.jit(lambda q, kk, v: flash_attention(
            q, kk, v, causal=True, use_pallas=force, dropout_rate=0.1,
            dropout_seed=jnp.int32(7)))
        a, b_ = f(q, kk, v), f(q, kk, v)
        jax.block_until_ready((a, b_))
        same = float(jnp.max(jnp.abs(a - b_)))
        c = jax.jit(lambda q, kk, v: flash_attention(
            q, kk, v, causal=True, use_pallas=force, dropout_rate=0.1,
            dropout_seed=jnp.int32(8)))(q, kk, v)
        differs = float(jnp.max(jnp.abs(a - c)))
        # same seed -> bitwise equal; different seed -> visibly different
        return same if differs > 1e-3 else float("nan")

    record("flash_attention_inkernel_dropout", dropout_determinism, tol=0.0,
           pallas_row=True)

    def dropout_global_offsets():
        # the ring-SP dropout contract, single-chip: a dense kernel call
        # must equal the same computation CHUNKED with global position
        # offsets (the [seed, q_off, k_off] SMEM operand) — non-causal so
        # every chunk is the plain kernel, merged by the ring's lse rule
        from apex_tpu.ops.attention import _fa_fwd

        seed = jnp.int32(4242)
        rate = 0.2
        # dense side pinned to the KERNEL (interpret off-chip): the
        # reference fallback draws a different stream, and the row's
        # claim is kernel-vs-chunked-kernel mask identity
        dense = jax.jit(lambda q, kk, v: flash_attention(
            q, kk, v, causal=False, use_pallas=True,
            interpret=None if on_tpu else True, dropout_rate=rate,
            dropout_seed=seed))(q, kk, v)

        def chunked(q, kk, v):
            half = s // 2
            q3 = q.reshape(b * h, s, d)
            outs = []
            for k_off in (0, half):
                k3 = kk[:, :, k_off:k_off + half].reshape(b * h, half, d)
                v3 = v[:, :, k_off:k_off + half].reshape(b * h, half, d)
                sv = jnp.stack([seed, jnp.int32(0), jnp.int32(k_off)])
                o3, lse3 = _fa_fwd(q3, k3, v3, 1.0 / d ** 0.5, False,
                                   128, 128, interpret=not on_tpu,
                                   dropout_rate=rate, seed=sv)
                outs.append((o3, lse3[..., 0]))
            (o1, l1), (o2, l2) = outs
            lse = jnp.logaddexp(l1, l2)
            o = (o1.astype(jnp.float32) * jnp.exp(l1 - lse)[..., None]
                 + o2.astype(jnp.float32) * jnp.exp(l2 - lse)[..., None])
            return o.reshape(b, h, s, d)

        got = jax.jit(chunked)(q, kk, v)
        jax.block_until_ready(got)
        err = float(jnp.max(jnp.abs(got - dense.astype(jnp.float32)))
                    / (jnp.max(jnp.abs(dense.astype(jnp.float32)))
                       + 1e-12))
        # identical masks by construction; only bf16 merge rounding
        return err

    record("flash_attention_dropout_global_offsets", dropout_global_offsets,
           tol=2e-2, pallas_row=True)

    def bias_fwd_bwd():
        # T5 relative-position-bias contract: batch-shared (h, sq, sk)
        # additive logit bias, grads for q/k/v AND the bias (the
        # batch-reducing dbias kernel) vs the XLA reference
        bias = jax.random.normal(jax.random.fold_in(k, 9), (h, s, s))

        def loss(q, kk, v, bias):
            return jnp.sum(flash_attention(q, kk, v, causal=True,
                                           use_pallas=force, bias=bias)
                           .astype(jnp.float32) ** 2)

        def loss_ref(q, kk, v, bias):
            return jnp.sum(attention_reference(q, kk, v, causal=True,
                                               bias=bias)
                           .astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(q, kk, v, bias)
        gr = ref_grad(loss_ref, (0, 1, 2, 3), q, kk, v, bias)
        jax.block_until_ready(g)
        return nerr(g, gr)

    record("flash_attention_additive_bias", bias_fwd_bwd, tol=2e-2,
           zero_is_fallback=True, pallas_row=True)

    from apex_tpu.ops.attention_varlen import (
        attention_varlen_reference,
        flash_attention_varlen,
    )

    seg = jnp.where(jnp.arange(s)[None, :] < s // 2, 0, 1) * jnp.ones(
        (b, 1), jnp.int32)
    seg = seg.at[:, -64:].set(-1)  # pad tail exercises the skip path

    def varlen_fwd_bwd():
        def loss(q, kk, v):
            return jnp.sum(flash_attention_varlen(
                q, kk, v, seg, causal=True, use_pallas=force)
                .astype(jnp.float32) ** 2)

        def loss_ref(q, kk, v):
            return jnp.sum(attention_varlen_reference(
                q, kk, v, seg, causal=True).astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, kk, v)
        gr = ref_grad(loss_ref, (0, 1, 2), q, kk, v)
        jax.block_until_ready(g)
        return nerr(g, gr)

    record("flash_attention_varlen_block_skip", varlen_fwd_bwd,
           tol=2e-2, zero_is_fallback=True, pallas_row=True)

    from apex_tpu.ops.layer_norm import layer_norm, layer_norm_reference

    for hidden, tag in ((1024, "1k"), (16384, "16k")):
        x = jax.random.normal(k, (256, hidden), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(k, 3), (hidden,)) * 0.1 + 1.0
        bb = jax.random.normal(jax.random.fold_in(k, 4), (hidden,)) * 0.1

        def ln_fwd_bwd(x=x, w=w, bb=bb):
            def loss(x, w, bb):
                return jnp.sum(layer_norm(x, w, bb, use_pallas=force) ** 2)

            def loss_ref(x, w, bb):
                return jnp.sum(layer_norm_reference(x, w, bb) ** 2)

            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, w, bb)
            gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(x, w, bb)
            jax.block_until_ready(g)
            return max(float(jnp.max(jnp.abs(a - b_) / (jnp.abs(b_) + 1e-2)))
                       for a, b_ in zip(g, gr))

        record(f"pallas_layer_norm_h{tag}", ln_fwd_bwd,
               zero_is_fallback=True, pallas_row=True)

    from apex_tpu.ops.lm_head_loss import lm_head_loss

    def fused_head():
        bt, hid, vv = 512, 256, 8192
        xx = jax.random.normal(k, (bt, hid), jnp.float32) * 0.1
        ww = jax.random.normal(jax.random.fold_in(k, 5), (vv, hid)) * 0.02
        tt = jax.random.randint(jax.random.fold_in(k, 6), (bt,), 0, vv)

        def loss(xx, ww):
            return jnp.mean(lm_head_loss(xx, ww, tt, use_pallas=force))

        def loss_ref(xx, ww):
            lg = (xx @ ww.T).astype(jnp.float32)
            return -jnp.mean(jax.nn.log_softmax(lg)[jnp.arange(bt), tt])

        g = jax.jit(jax.grad(loss, argnums=(0, 1)))(xx, ww)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(xx, ww)
        jax.block_until_ready(g)
        return max(float(jnp.max(jnp.abs(a - b_) / (jnp.abs(b_) + 1e-4)))
                   for a, b_ in zip(g, gr))

    record("fused_lm_head_cross_entropy", fused_head, pallas_row=True)

    from apex_tpu.ops.softmax import scaled_upper_triang_masked_softmax
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    def softmax_xent():
        xx = jax.random.normal(k, (4, 8, 256, 256), jnp.float32)
        y = jax.jit(lambda a: scaled_upper_triang_masked_softmax(a, 1.0))(xx)
        ref = jax.nn.softmax(
            jnp.where(jnp.tril(jnp.ones((256, 256), bool)), xx, -1e9), -1)
        e1 = float(jnp.max(jnp.abs(y - ref)))
        lg = jax.random.normal(k, (512, 1000), jnp.float32)
        tt = jax.random.randint(jax.random.fold_in(k, 7), (512,), 0, 1000)
        l1 = jax.jit(lambda lg: jnp.mean(softmax_cross_entropy_loss(
            lg, tt, smoothing=0.1)))(lg)
        onehot = jax.nn.one_hot(tt, 1000) * 0.9 + 0.1 / 1000
        l2 = -jnp.mean(jnp.sum(jax.nn.log_softmax(lg) * onehot, -1))
        jax.block_until_ready((y, l1))
        return max(e1, float(jnp.abs(l1 - l2)))

    record("scaled_softmax_and_xentropy", softmax_xent, tol=1e-4)

    return {"backend": jax.default_backend(), "on_tpu": on_tpu,
            "kernels": out}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from apex_tpu.utils.platform import pin_cpu_if_tunnel_dead

    pin_cpu_if_tunnel_dead()

    t0 = time.perf_counter()
    res = _results()
    res["total_seconds"] = round(time.perf_counter() - t0, 1)
    text = json.dumps(res, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if all(r["ok"] for r in res["kernels"]):
        return 0
    # distinguish an off-chip rehearsal (whose kernel rows are forced red
    # by design — see pallas_row) from a real on-chip kernel failure, so
    # CI-style callers checking the exit code don't read a working harness
    # as a broken kernel
    return 1 if res["on_tpu"] else 2


if __name__ == "__main__":
    sys.exit(main())

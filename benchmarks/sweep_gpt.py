"""Config sweep for the GPT train-step bench — measures tokens/s for
combinations of fused_loss / remat / remat_policy to guide tuning.

Run: python benchmarks/sweep_gpt.py
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.utils.platform import pin_cpu_if_requested

pin_cpu_if_requested()
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

BATCH, SEQ, STEPS = 32, 1024, 10


def measure(remat, remat_policy, fused_loss):
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel.mesh import build_mesh
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        replicate_loss,
    )
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        gpt_param_specs,
        init_gpt_params,
    )

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    batch, seq, steps = (BATCH, SEQ, STEPS) if on_tpu else (2, 128, 2)

    cfg = GPTConfig(vocab_size=50304, max_seq=seq, hidden=768, num_layers=12,
                    num_heads=12, dtype=jnp.bfloat16, remat=remat,
                    remat_policy=remat_policy, fused_loss=fused_loss)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(tp=1, pp=1, sp=1, devices=jax.devices()[:1])
    specs = gpt_param_specs(cfg)
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)

    def loss_fn(p, tok, tgt):
        def body(p, tok, tgt):
            return replicate_loss(gpt_loss(p, tok, tgt, cfg), mesh,
                                  masked_axis=None)

        return jax.shard_map(body, mesh=mesh, in_specs=(specs, P(), P()),
                             out_specs=P())(p, tok, tgt)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tok, tgt):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    key = jax.random.PRNGKey(1)
    tok = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)

    try:
        params, opt_state, loss = train_step(params, opt_state, tok, tgt)
        float(loss)  # host-read fence: axon's block_until_ready returns early
    except Exception as e:  # OOM etc.
        return None, f"{type(e).__name__}: {str(e)[:120]}"

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, tok, tgt)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    return batch * seq / dt, None


def main():
    combos = [
        (False, "full", False),
        (False, "full", True),
        (True, "dots", False),
        (True, "dots", True),
        (True, "full", False),
        (True, "full", True),
    ]
    for remat, pol, fused in combos:
        tps, err = measure(remat, pol, fused)
        tag = f"remat={remat} policy={pol} fused_loss={fused}"
        if tps is None:
            print(f"{tag}: FAILED {err}", flush=True)
        else:
            print(f"{tag}: {tps:,.0f} tokens/s", flush=True)


if __name__ == "__main__":
    main()

"""Elastic training benchmark — reshard throughput, kill→resume wall
time, loss-rejoin fidelity, and sentinel overhead.

The ISSUE-18 gates, measured end-to-end on one box:

* **reshard_ms / reshard_ms_per_gb** — a dp=4 block-aligned checkpoint
  (fp32 master + both Adam moments, ``bench_checkpoint``-class size)
  restored onto a dp=2 layout with ``allow_reshard=True``; the manager's
  ``last_reshard_ms`` isolates the retarget arithmetic from I/O.
* **kill_resume_wall_ms** — the full elastic story on the sim loop:
  supervisor runs at dp=4 under a ``KillRankAtStep`` chaos plan, a second
  supervisor resumes the restart manifest at dp=2 and finishes the run.
* **loss_rejoin_delta** — max |stitched − fault-free| over the loss
  curve; the sim optimizer is elementwise so the padded-flat math is
  dp-invariant and the gate is ``--rejoin-tol`` (default 1e-5; bitwise 0
  in practice).
* **sentinel_overhead_pct** — the same supervised loop with the
  straggler sentinel + per-step SDC agreement check on vs off; gated
  ``--overhead-tol`` (≤5%, the always-on claim) with zero false
  positives required on the clean run.

ONE ``json_record`` line; ``tpu_watch.sh`` stage 22 banks
``ELASTIC_TPU.json``, regression-gated via ``python -m
apex_tpu.monitor.regress --tol 0.15``; CPU rehearsals carry
``_CPU_FALLBACK`` and never promote.

Run: ``python benchmarks/bench_elastic.py [--out FILE]``.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import argparse

    from apex_tpu.utils.platform import (
        pin_cpu_if_requested,
        pin_cpu_if_tunnel_dead,
    )

    pin_cpu_if_requested()
    pin_cpu_if_tunnel_dead()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())  # after the pin: backend is final

    from apex_tpu.contrib.optimizers._sharding import shard_size
    from apex_tpu.monitor import json_record
    from apex_tpu.resilience import (
        CheckpointManager,
        KillRankAtStep,
        SDCSentinel,
        StragglerSentinel,
        TrainChaosPlan,
        TrainSupervisor,
        dp_flat_spec,
        replicated_spec,
    )

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--steps", type=int, default=8,
                    help="sim-loop length for the kill→resume story")
    ap.add_argument("--kill-at", type=int, default=5)
    ap.add_argument("--dp-save", type=int, default=4)
    ap.add_argument("--dp-resume", type=int, default=2)
    ap.add_argument("--param-elems", type=int, default=(1 << 23) + 4099,
                    help="logical element count of the reshard-throughput "
                         "state (x3 fp32 leaves: master + mu + nu); odd "
                         "on purpose so the padded layouts actually "
                         "differ across dp degrees")
    ap.add_argument("--sentinel-steps", type=int, default=16)
    ap.add_argument("--rejoin-tol", type=float, default=1e-5)
    ap.add_argument("--overhead-tol", type=float, default=0.05,
                    help="max step-loop fraction the sentinels may cost "
                         "(the ok gate; ISSUE-18 pins 5%%)")
    args = ap.parse_args(argv)

    on_tpu = jax.default_backend() == "tpu"
    name = "elastic_train_resume"
    if not on_tpu:
        name += "_CPU_FALLBACK"

    # -- the elementwise-Adam sim (dp-invariant padded-flat math; the
    # test suite pins the bitwise property, the bench times it) ---------
    MULT = 256

    def sim_init(n, dp, hot=0):
        size = shard_size(n, dp, MULT) * dp
        master = np.zeros(size, np.float32)
        master[:n] = np.linspace(-1.0, 1.0, n, dtype=np.float32)
        state = {"count": jnp.zeros((), jnp.int32),
                 "master": jnp.asarray(master),
                 "mu": jnp.zeros(size, jnp.float32),
                 "nu": jnp.zeros(size, jnp.float32)}
        spec = {"count": replicated_spec(),
                "master": dp_flat_spec(n, dp, MULT),
                "mu": dp_flat_spec(n, dp, MULT),
                "nu": dp_flat_spec(n, dp, MULT)}
        for _ in range(hot):  # warm moments so the reshard moves entropy
            state = sim_step(n, state)
        return state, spec

    def sim_step(n, state, losses=None):
        master = np.asarray(state["master"])
        mu, nu = np.asarray(state["mu"]), np.asarray(state["nu"])
        target = np.float32(0.5)
        g = np.zeros_like(master)
        g[:n] = master[:n] - target
        if losses is not None:
            losses.append(0.5 * float(np.dot(g[:n], g[:n])))
        t = int(state["count"]) + 1
        mu = np.float32(0.9) * mu + np.float32(0.1) * g
        nu = np.float32(0.999) * nu + np.float32(0.001) * (g * g)
        master = (master - np.float32(0.1) * (mu / np.float32(1 - 0.9 ** t))
                  / (np.sqrt(nu / np.float32(1 - 0.999 ** t))
                     + np.float32(1e-8)))
        return {"count": jnp.int32(t), "master": jnp.asarray(master),
                "mu": jnp.asarray(mu), "nu": jnp.asarray(nu)}

    root = tempfile.mkdtemp(prefix="bench_elastic_")
    try:
        # -- 1. reshard throughput on a checkpoint-class state ----------
        n_big = int(args.param_elems)
        big, big_spec = sim_init(n_big, args.dp_save, hot=1)
        mgr = CheckpointManager(os.path.join(root, "big"), fsync=False)
        mgr.save(big, 1, block=True, elastic=big_spec)
        reshard_bytes = mgr.last_save_bytes
        template, _ = sim_init(n_big, args.dp_resume)
        t0 = time.perf_counter()
        got, _ = mgr.restore(target=template, allow_reshard=True)
        restore_ms = (time.perf_counter() - t0) * 1e3
        reshard_ms = mgr.last_reshard_ms
        reshard_ok = bool(
            reshard_ms > 0.0
            and np.array_equal(
                np.asarray(got["master"])[:n_big],
                np.asarray(big["master"])[:n_big]))
        gb = reshard_bytes / 1e9

        # -- 2. save → kill → resume-at-new-dp wall time + rejoin -------
        n = 4099
        ref_losses = []
        ref, _ = sim_init(n, args.dp_save)
        for _ in range(args.steps):
            ref = sim_step(n, ref, ref_losses)

        ckpt = os.path.join(root, "run")
        losses_a, losses_b = [], []
        state_a, spec_a = sim_init(n, args.dp_save)
        t0 = time.perf_counter()
        sup_a = TrainSupervisor(
            lambda st, i: sim_step(n, st, losses_a),
            CheckpointManager(ckpt, fsync=False), elastic=spec_a,
            dp_degree=args.dp_save, save_freq=2,
            chaos=TrainChaosPlan([KillRankAtStep(at_step=args.kill_at)]))
        sup_a.run(state_a, 0, args.steps)
        template_b, spec_b = sim_init(n, args.dp_resume)
        sup_b = TrainSupervisor(
            lambda st, i: sim_step(n, st, losses_b),
            CheckpointManager(ckpt, fsync=False, allow_reshard=True),
            elastic=spec_b, dp_degree=args.dp_resume)
        state_b, start = sup_b.resume(template_b)
        sup_b.run(state_b, start, args.steps - start)
        kill_resume_wall_ms = (time.perf_counter() - t0) * 1e3
        stitched = losses_a[:start] + losses_b
        rejoin_delta = (max(abs(a - b) for a, b in zip(stitched, ref_losses))
                        if len(stitched) == len(ref_losses) else float("inf"))
        restart = TrainSupervisor.read_restart(ckpt) or {}

        # -- 3. sentinel overhead A/B, paired at step granularity -------
        # every=4 is the sentinel's own amortization knob (the checksum
        # fuses into the grad sweep on a real mesh; the host sim pays it
        # explicitly, so the periodic gate carries the ≤5% claim).
        # Interleaving the on/off steps and comparing per-step MEDIANS
        # cancels scheduler drift a whole-run wall A/B cannot.
        import statistics

        sdc = SDCSentinel(every=4)
        straggler = StragglerSentinel(threshold=4.0)
        n_sent = 1 << 21  # a ~2M-param step so the ratio is stable
        flags = {"sdc": 0.0}
        st_on, _ = sim_init(n_sent, args.dp_save)
        st_off, _ = sim_init(n_sent, args.dp_save)
        on_ts, off_ts = [], []
        n_pairs = max(8, args.sentinel_steps) * 4

        def off_step(i):
            nonlocal st_off
            t0 = time.perf_counter()
            st_off = sim_step(n_sent, st_off)
            off_ts.append(time.perf_counter() - t0)

        def on_step(i):
            # the per-step sentinel work the supervisor drives: the
            # straggler robust-z over the rank gauge every step, the SDC
            # agreement check on due steps
            nonlocal st_on
            t0 = time.perf_counter()
            st_on = sim_step(n_sent, st_on)
            dt = time.perf_counter() - t0
            straggler.observe(i, [dt] * args.dp_save)
            if i % sdc.every == 0:
                sums = jnp.full((args.dp_save,),
                                float(np.asarray(st_on["master"]).sum()))
                flags["sdc"] += float(sdc.disagreement(sums))
            on_ts.append(time.perf_counter() - t0)

        def trimmed_mean(xs):
            xs = sorted(xs)
            k = len(xs) // 8  # drop the noisy 12.5% tails
            return statistics.fmean(xs[k:len(xs) - k])

        for i in range(n_pairs):
            # alternate which arm runs first so cache/scheduler position
            # bias cancels in the means
            first, second = (on_step, off_step) if i % 2 else (off_step,
                                                               on_step)
            first(i)
            second(i)
            if i == 3:  # first pairs warmed the allocator + jnp dispatch
                on_ts.clear()
                off_ts.clear()
        on_mean, off_mean = trimmed_mean(on_ts), trimmed_mean(off_ts)
        overhead = (on_mean - off_mean) / off_mean if off_mean > 0 else None
        straggler_fp = straggler.flags_total
        sdc_fp = flags["sdc"]

        ok = bool(
            reshard_ok
            and sup_a.exited == "killed"
            and sup_b.exited == "completed"
            and sup_b.counters["elastic_resumes_total"] == 1
            and rejoin_delta <= args.rejoin_tol
            and overhead is not None
            and overhead <= args.overhead_tol
            and straggler_fp == 0  # zero false positives on a clean run
            and sdc_fp == 0.0)
        rec = {
            "metric": name,
            "ok": ok,
            "reshard_ms": round(reshard_ms, 3),
            "reshard_ms_per_gb": round(reshard_ms / gb, 3) if gb else None,
            "reshard_bytes": reshard_bytes,
            "restore_ms": round(restore_ms, 3),
            "kill_resume_wall_ms": round(kill_resume_wall_ms, 3),
            "loss_rejoin_delta": rejoin_delta,
            "rejoin_tol": args.rejoin_tol,
            "sentinel_overhead_pct": (round(100 * overhead, 2)
                                      if overhead is not None else None),
            "overhead_tol_pct": round(100 * args.overhead_tol, 2),
            "straggler_flags_total": straggler_fp,
            "sdc_disagreements_total": sdc_fp,
            "retries_total": sup_a.counters["retries_total"]
            + sup_b.counters["retries_total"],
            "elastic_resumes_total":
                sup_b.counters["elastic_resumes_total"],
            "legal_resume_dp": restart.get("legal_resume_dp"),
            "dp_save": args.dp_save,
            "dp_resume": args.dp_resume,
            "steps": args.steps,
            "backend": jax.default_backend(),
        }
        line = json_record(**rec)
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        # ok:false is a bench FAILURE (a resume that drifted, a sentinel
        # that cried wolf, or a plane too expensive to leave on)
        return 0 if ok else 1
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())

"""Deterministic closed-/open-loop load generator for the serve engine.

The ROADMAP item-2 harness half: serving numbers mean nothing without a
workload model, and averages mean nothing without arrival bursts — tail
latency IS the product of queueing (arXiv 1909.09756's scale lesson;
arXiv 2512.22219's dispatch-latency analysis). This module generates a
**seeded, reproducible** workload and drives an ``InferenceEngine``
through it:

* **open loop** — Poisson arrivals at ``rate_rps`` (exponential gaps from
  a fixed seed) with optional superimposed **bursts** (every
  ``burst_every_s``, ``burst_size`` requests arrive at the same instant —
  the queue-building event that separates p99 from p50), long-tail
  (lognormal, clipped) prompt lengths and generation lengths. Arrivals
  are wall-clock scheduled: a request is submitted when its arrival time
  passes, whether or not the engine kept up — offered load is independent
  of completion, exactly what an SLO needs to be measured against.
* **closed loop** — a fixed number of in-flight requests; each
  retirement immediately submits the next. Measures capacity without
  queueing effects (the classic loadgen dual).
* **shared prefixes** — ``prefix_pool`` distinct "system prompts" of
  ``prefix_len`` tokens, mixed into a ``prefix_ratio`` fraction of
  requests (same seed -> same pool, same mixing). This is the workload
  the engine's prefix cache exists for: the acceptance record for
  ``tpu_watch.sh`` stage 11 (``SERVE_PREFIX_TPU.json``) runs it with
  ``--prefix-pool`` + ``--spec-k`` and must beat the stage-10 plain
  record on the same hardware.
* **per-tenant adapters** — ``n_adapters`` binds tenant ``t{i}`` to LoRA
  adapter ``ad{i % n_adapters}`` deterministically (no extra rng draws:
  an ``n_adapters=0`` workload is bit-identical to the pre-adapter one).
  This is the fleet-mix workload ``bench_serve_mh.py --lora`` drives for
  the ``tpu_watch.sh`` stage-20 record (adapter hit rate, warm-dispatch
  rate, aid=0 ``streams_equal``).

``run_workload`` drives the engine with ``retain_streams=False`` — state
stays O(slots + backlog) no matter how many requests flow — and returns
``engine.stats()`` (histquantiles + goodput-under-SLO). ``main`` builds
the pinned bench model, runs a Poisson+burst workload against a default
SLO and prints ONE ``json_record`` line (goodput req/s, TTFT/TPOT
p50/p99, violation counts) — ``benchmarks/bench_serve.py --loadgen``
calls straight into this, and ``tpu_watch.sh`` stage 10 banks and
regression-gates the line via ``apex_tpu.monitor.regress``.

Run: ``python benchmarks/loadgen.py [--out FILE] [--trace-dir DIR]``.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

__all__ = ["WorkloadConfig", "build_workload", "run_workload", "main"]


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """Seeded workload shape. ``mode="open"`` uses Poisson arrivals +
    bursts; ``mode="closed"`` keeps ``concurrency`` requests in flight
    (arrival times all 0)."""

    n_requests: int = 64
    mode: str = "open"                 # "open" | "closed"
    rate_rps: float = 8.0              # open: mean Poisson arrival rate
    burst_every_s: Optional[float] = 2.0  # open: burst period (None: off)
    burst_size: int = 4                # open: requests per burst instant
    concurrency: int = 8               # closed: in-flight target
    prompt_len_median: int = 24        # lognormal median prompt length
    prompt_len_sigma: float = 0.8      # long-tail spread (log-space std)
    prompt_len_min: int = 2
    prompt_len_max: int = 128
    max_new_median: int = 16           # lognormal median generation length
    max_new_sigma: float = 0.5
    max_new_min: int = 2
    max_new_max: int = 64
    # shared-prefix mixing: a pool of prefix_pool distinct "system
    # prompts" of prefix_len tokens each; a prefix_ratio fraction of
    # requests open with one of them (the rest are fully random) — the
    # workload shape the engine's prefix cache exists for. 0 disables.
    prefix_pool: int = 0
    prefix_len: int = 32
    prefix_ratio: float = 1.0
    # multi-tenant mixing (the cluster router's fairness knob): each
    # request is tagged tenant "t0".."t{n-1}", drawn from the SAME seeded
    # rng with probabilities proportional to tenant_weights (None: equal)
    # — deterministic like prefix_pool, so the WFQ path is drivable from
    # the bench and tests. 0 disables (every request tenant "default").
    n_tenants: int = 0
    tenant_weights: Optional[Tuple[float, ...]] = None
    # per-tenant LoRA adapter traffic (the serve.adapters knob): tenant
    # "t{i}" is bound to adapter "ad{i % n_adapters}" — a FIXED mapping,
    # no extra rng draws, so an n_adapters=0 workload stays bit-identical
    # to the pre-adapter one and the adapter mix follows the tenant mix
    # (tenant_weights skews which adapters are hot). Requires n_tenants
    # >= 1; the driver must load_adapter() "ad0".."ad{M-1}" before the
    # run or admission sheds the bound requests. 0 disables (no request
    # carries an adapter — the aid=0 transparency cohort).
    n_adapters: int = 0
    seed: int = 0

    def validate(self) -> None:
        if self.mode not in ("open", "closed"):
            raise ValueError(f"mode must be open|closed, got {self.mode!r}")
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.mode == "open" and self.rate_rps <= 0:
            raise ValueError("rate_rps must be positive for open loop")
        if self.mode == "closed" and self.concurrency < 1:
            raise ValueError("concurrency must be >= 1 for closed loop")
        if not (1 <= self.prompt_len_min <= self.prompt_len_max):
            raise ValueError("bad prompt length bounds")
        if not (1 <= self.max_new_min <= self.max_new_max):
            raise ValueError("bad max_new bounds")
        if self.prefix_pool < 0:
            raise ValueError("prefix_pool must be >= 0")
        if self.prefix_pool:
            if self.prefix_len < 1:
                raise ValueError("prefix_len must be >= 1")
            if not 0.0 < self.prefix_ratio <= 1.0:
                raise ValueError("prefix_ratio must be in (0, 1]")
        if self.n_tenants < 0:
            raise ValueError("n_tenants must be >= 0")
        if self.tenant_weights is not None:
            if len(self.tenant_weights) != self.n_tenants:
                raise ValueError(
                    f"tenant_weights has {len(self.tenant_weights)} "
                    f"entries for n_tenants={self.n_tenants}")
            if any(w <= 0 for w in self.tenant_weights):
                raise ValueError("tenant_weights must be positive")
        if self.n_adapters < 0:
            raise ValueError("n_adapters must be >= 0")
        if self.n_adapters and self.n_tenants < 1:
            raise ValueError("n_adapters > 0 needs n_tenants >= 1 "
                             "(adapters are bound per tenant)")


def _lognormal_int(rng, median: float, sigma: float, lo: int, hi: int,
                   size: int) -> np.ndarray:
    v = rng.lognormal(mean=np.log(median), sigma=sigma, size=size)
    return np.clip(np.round(v).astype(np.int64), lo, hi)


def build_workload(cfg: WorkloadConfig, vocab_size: int,
                   max_context: int) -> List[Tuple[float, Any]]:
    """The deterministic workload: ``[(arrival_s, Request), ...]`` sorted
    by arrival. Same config + seed -> identical request stream (uids,
    prompts, lengths, arrival instants), so records are comparable
    round-over-round — the canary discipline applied to load."""
    from apex_tpu.serve import Request

    cfg.validate()
    rng = np.random.default_rng(cfg.seed)
    n = cfg.n_requests
    # prompt must leave >= 1 position to generate inside max_context
    p_hi = min(cfg.prompt_len_max, max_context - 1)
    plens = _lognormal_int(rng, cfg.prompt_len_median, cfg.prompt_len_sigma,
                           cfg.prompt_len_min, p_hi, n)
    glens = _lognormal_int(rng, cfg.max_new_median, cfg.max_new_sigma,
                           cfg.max_new_min, cfg.max_new_max, n)
    # shared-prefix pool: the N "system prompts" are drawn FIRST from the
    # same seeded rng, so the pool is part of the deterministic workload
    prefixes: List[List[int]] = []
    pick = share = None
    if cfg.prefix_pool:
        plen = min(cfg.prefix_len, max_context - 2)
        prefixes = [rng.integers(0, vocab_size, size=plen).tolist()
                    for _ in range(cfg.prefix_pool)]
        pick = rng.integers(0, cfg.prefix_pool, size=n)
        share = rng.random(size=n) < cfg.prefix_ratio
    # tenant tags drawn from the same seeded stream (only when enabled, so
    # an n_tenants=0 workload is bit-identical to the pre-tenant one)
    tenants = None
    if cfg.n_tenants:
        w = np.asarray(cfg.tenant_weights
                       if cfg.tenant_weights is not None
                       else [1.0] * cfg.n_tenants, np.float64)
        tenants = rng.choice(cfg.n_tenants, size=n, p=w / w.sum())
    if cfg.mode == "closed":
        arrivals = np.zeros((n,))
    else:
        gaps = rng.exponential(1.0 / cfg.rate_rps, size=n)
        arrivals = np.cumsum(gaps)
        if cfg.burst_every_s:
            # bursts: every burst_every_s, the next burst_size arrivals
            # collapse onto the burst instant (offered load unchanged in
            # total, concentrated in time — the p99-making event)
            t, i = cfg.burst_every_s, 0
            while i < n:
                j = int(np.searchsorted(arrivals, t))
                k = min(j + cfg.burst_size, n)
                arrivals[j:k] = t
                if j >= n:
                    break
                i = k
                t += cfg.burst_every_s
            arrivals = np.sort(arrivals)
    out = []
    for i in range(n):
        toks = rng.integers(0, vocab_size, size=int(plens[i])).tolist()
        if prefixes and share[i]:
            # shared system prompt + the request's own tail, clipped to
            # leave >= 1 position to generate
            toks = (prefixes[int(pick[i])] + toks)[:max_context - 1]
        tenant = (f"t{int(tenants[i])}" if tenants is not None
                  else "default")
        adapter = (f"ad{int(tenants[i]) % cfg.n_adapters}"
                   if cfg.n_adapters and tenants is not None else None)
        out.append((float(arrivals[i]),
                    Request(f"lg{i:05d}", toks,
                            max_new_tokens=int(glens[i]),
                            tenant=tenant, adapter=adapter)))
    return out


def run_workload(engine, workload: List[Tuple[float, Any]],
                 time_scale: float = 1.0,
                 max_wall_s: float = 600.0) -> Dict[str, Any]:
    """Drive ``engine`` through the workload; returns ``engine.stats()``
    plus offered-load accounting.

    Open loop: requests are submitted when their (scaled) arrival time
    passes on the wall clock; the engine steps continuously while active
    and sleeps to the next arrival when idle. Closed loop (all arrivals
    0 with a ``concurrency``-bounded workload) degenerates to submit-all
    + drain, which is exactly the closed-loop semantics under a slot
    grid: the engine itself caps in-flight at ``num_slots``.
    ``time_scale`` compresses arrival times (tests); ``max_wall_s`` is a
    hard stop so a saturated engine still reports."""
    pending = sorted(workload, key=lambda aw: aw[0])
    t0 = time.perf_counter()
    submitted = 0
    deadline = t0 + max_wall_s
    while (pending or engine.active) and time.perf_counter() < deadline:
        now = time.perf_counter() - t0
        while pending and pending[0][0] * time_scale <= now:
            _, req = pending.pop(0)
            engine.submit(req)
            submitted += 1
        progressed = engine.step()
        if not progressed and pending:
            # idle: sleep to the next arrival instead of spinning
            wait = pending[0][0] * time_scale - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(min(wait, 0.05))
        elif not progressed and not pending:
            break  # drained
    wall = time.perf_counter() - t0
    stats = engine.stats()
    stats["offered"] = len(workload)
    stats["submitted"] = submitted
    last = workload[-1][0] * time_scale if workload else 0.0
    stats["offered_rps"] = (round(len(workload) / last, 3)
                            if last > 0 else None)
    stats["wall_s"] = round(wall, 3)
    return stats


def main(argv=None) -> int:
    import argparse

    from apex_tpu.utils.platform import (
        pin_cpu_if_requested,
        pin_cpu_if_tunnel_dead,
        pin_cpu_platform,
    )

    pin_cpu_if_requested()
    pin_cpu_if_tunnel_dead()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        pin_cpu_platform()

    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.sink import collect_provenance, set_provenance

    set_provenance(collect_provenance())  # after the pin: backend is final

    from apex_tpu.monitor import (
        EventLog,
        JsonlSink,
        SloSpec,
        json_record,
        read_jsonl,
        write_chrome_trace,
    )
    from apex_tpu.serve import InferenceEngine, ServeConfig
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--trace-dir", default=None,
                    help="also write events.jsonl + trace.json here")
    ap.add_argument("--n-requests", type=int, default=64)
    ap.add_argument("--rate-rps", type=float, default=8.0)
    ap.add_argument("--mode", default="open", choices=["open", "closed"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"])
    ap.add_argument("--ttft-budget", type=float, default=2000.0)
    ap.add_argument("--tpot-budget", type=float, default=200.0)
    ap.add_argument("--queue-budget", type=float, default=1000.0)
    # shared-prefix workload (the prefix-cache acceptance knob) + the
    # serve-throughput tier-2 engine knobs
    ap.add_argument("--prefix-pool", type=int, default=0,
                    help="N distinct shared system prompts (0: off)")
    ap.add_argument("--prefix-len", type=int, default=64)
    ap.add_argument("--prefix-ratio", type=float, default=0.75,
                    help="fraction of requests opening with a shared "
                         "prefix")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft length (0: off)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--megakernel", default="auto",
                    choices=["auto", "on", "off"],
                    help="fused per-layer decode block (serve.megakernel)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable content-addressed block reuse")
    args = ap.parse_args(argv)

    on_tpu = jax.default_backend() == "tpu"
    name = ("gpt_serve_prefix_goodput_slo" if args.prefix_pool
            else "gpt_serve_goodput_slo")
    if not on_tpu:
        name += "_CPU_FALLBACK"

    # the pinned bench model (bench_serve.py's canary constants)
    HIDDEN, LAYERS, HEADS, VOCAB, MAX_SEQ = 128, 2, 8, 512, 256
    SLOTS, BLOCK_SIZE = 4, 16
    cfg = GPTConfig(vocab_size=VOCAB, max_seq=MAX_SEQ, hidden=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS,
                    dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    wcfg = WorkloadConfig(n_requests=args.n_requests, mode=args.mode,
                          rate_rps=args.rate_rps, seed=args.seed,
                          prompt_len_max=MAX_SEQ // 2,
                          prefix_pool=args.prefix_pool,
                          prefix_len=args.prefix_len,
                          prefix_ratio=args.prefix_ratio)
    slo = SloSpec(ttft_ms=args.ttft_budget, tpot_ms=args.tpot_budget,
                  queue_ms=args.queue_budget)
    workload = build_workload(wcfg, VOCAB, MAX_SEQ)

    events = None
    sink = None
    events_path = None
    if args.trace_dir:
        os.makedirs(args.trace_dir, exist_ok=True)
        events_path = os.path.join(args.trace_dir, "events.jsonl")
        sink = JsonlSink(events_path, buffer_steps=64)
        events = EventLog(sink=sink)
    eng = InferenceEngine(
        params, cfg,
        ServeConfig(num_slots=SLOTS, block_size=BLOCK_SIZE,
                    kv_quant=args.kv_quant,
                    prefill_chunk=args.prefill_chunk,
                    prefix_cache=not args.no_prefix_cache,
                    spec_k=args.spec_k, megakernel=args.megakernel),
        events=events, slo=slo, retain_streams=False)
    stats = run_workload(eng, workload)
    if sink is not None:
        sink.close()
        write_chrome_trace(os.path.join(args.trace_dir, "trace.json"),
                           read_jsonl(events_path))

    slo_rep = stats.pop("slo_report")
    hists = stats.pop("hists")
    rec = {
        "metric": name,
        "ok": stats["completed"] == len(workload),
        "goodput_rps": slo_rep["goodput_rps"],
        "throughput_rps": slo_rep["throughput_rps"],
        "good_fraction": slo_rep["good_fraction"],
        "violations": slo_rep["violations"],
        **{k: stats.get(k) for k in (
            "offered", "submitted", "completed", "offered_rps",
            "generated_tokens", "tokens_per_s", "wall_s",
            "ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50", "tpot_ms_p99",
            "queue_ms_p50", "queue_ms_p99", "decode_step_ms_p50",
            "decode_step_ms_p99")},
        # the throughput-optimization headline fields (acceptance: the
        # shared-prefix record carries hit/acceptance rates)
        "prefix_hit_rate": stats.get("prefix_hit_rate"),
        "prefix_cache": stats.get("prefix_cache"),
        "spec_acceptance_rate": stats.get("spec_acceptance_rate"),
        "speculative": stats.get("speculative"),
        "prefill": stats.get("prefill"),
        "megakernel": stats.get("megakernel"),
        "compilations": eng.compile_counts(),
        "slo": slo.to_dict(),
        "hist_rel_error": round(eng.hists["ttft_ms"].spec.rel_error, 4),
        "workload": {"mode": wcfg.mode, "n": wcfg.n_requests,
                     "rate_rps": wcfg.rate_rps,
                     "burst_every_s": wcfg.burst_every_s,
                     "burst_size": wcfg.burst_size, "seed": wcfg.seed,
                     "prefix_pool": wcfg.prefix_pool,
                     "prefix_len": wcfg.prefix_len,
                     "prefix_ratio": wcfg.prefix_ratio,
                     "spec_k": args.spec_k,
                     "prefill_chunk": args.prefill_chunk},
        "hists": {k: hists[k] for k in ("ttft_ms", "tpot_ms")},
        "backend": jax.default_backend(),
    }
    line = json_record(**rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Rank-aware logging.

Reference analogue: ``apex/__init__.py:27-42`` installs a ``RankInfoFormatter``
that prefixes every log record with the caller's (data-parallel, tensor-parallel,
pipeline-parallel) rank triple obtained from ``parallel_state.get_rank_info``.

On TPU the equivalent host-level identity is ``jax.process_index`` (one process
may drive many chips); mesh-coordinate identity only exists inside a mesh
program, so the formatter shows process index / process count plus, when a
global mesh has been initialized (see ``apex_tpu.transformer.parallel_state``),
the mesh axis sizes.
"""

from __future__ import annotations

import logging
import sys


def _rank_info() -> str:
    try:
        import jax

        pidx, pcount = jax.process_index(), jax.process_count()
    except Exception:  # jax not importable / not initialized yet
        return "proc ?/?"
    info = f"proc {pidx}/{pcount}"
    try:
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            info += f" mesh {parallel_state.get_mesh_axes_str()}"
    except Exception:
        pass
    return info


class RankInfoFormatter(logging.Formatter):
    """Formatter prefixing records with process/mesh identity (ref apex/__init__.py:27-35)."""

    def format(self, record: logging.LogRecord) -> str:
        record.rank_info = _rank_info()
        return super().format(record)


_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - [%(rank_info)s] - %(message)s"
_configured_roots = set()


def get_logger(name: str = "apex_tpu") -> logging.Logger:
    """Return a rank-aware logger. The handler is installed once per top-level
    logger hierarchy, so names outside ``apex_tpu.*`` get the rank prefix too."""
    logger = logging.getLogger(name)
    root_name = name.split(".", 1)[0]
    if root_name not in _configured_roots:
        root = logging.getLogger(root_name)
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(RankInfoFormatter(_FORMAT))
        root.addHandler(handler)
        root.propagate = False
        _configured_roots.add(root_name)
    return logger

"""Rank-aware logging.

Reference analogue: ``apex/__init__.py:27-42`` installs a ``RankInfoFormatter``
that prefixes every log record with the caller's (data-parallel, tensor-parallel,
pipeline-parallel) rank triple obtained from ``parallel_state.get_rank_info``.

On TPU the equivalent host-level identity is ``jax.process_index`` (one process
may drive many chips); mesh-coordinate identity only exists inside a mesh
program, so the formatter shows process index / process count plus, when a
global mesh has been initialized (see ``apex_tpu.transformer.parallel_state``),
the mesh axis sizes.

Environment: ``APEX_TPU_LOG_LEVEL`` (e.g. ``DEBUG``, ``info``, ``30``) sets
the level of each configured top-level logger at first-configure time.
"""

from __future__ import annotations

import logging
import os
import sys


def _rank_info() -> str:
    try:
        import jax

        pidx, pcount = jax.process_index(), jax.process_count()
    except Exception:  # jax not importable / not initialized yet
        return "proc ?/?"
    info = f"proc {pidx}/{pcount}"
    try:
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            info += f" mesh {parallel_state.get_mesh_axes_str()}"
    except Exception:
        pass
    return info


class RankInfoFormatter(logging.Formatter):
    """Formatter prefixing records with process/mesh identity (ref apex/__init__.py:27-35)."""

    def format(self, record: logging.LogRecord) -> str:
        record.rank_info = _rank_info()
        return super().format(record)


_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - [%(rank_info)s] - %(message)s"
_configured_roots = set()


def _has_rank_handler(logger: logging.Logger) -> bool:
    """True if a rank-aware handler is already installed. Matched by class
    NAME, not identity: a pytest/notebook re-import of this module creates a
    fresh ``RankInfoFormatter`` class (and an empty ``_configured_roots``),
    and an ``isinstance`` check against the new class would miss the old
    module's handler — printing every record twice."""
    return any(
        type(h.formatter).__name__ == "RankInfoFormatter"
        for h in logger.handlers
        if h.formatter is not None
    )


def _env_level():
    """``APEX_TPU_LOG_LEVEL`` parsed as a level name or number, else None."""
    raw = os.environ.get("APEX_TPU_LOG_LEVEL", "").strip()
    if not raw:
        return None
    if raw.isdigit():
        return int(raw)
    level = logging.getLevelName(raw.upper())
    return level if isinstance(level, int) else None


def get_logger(name: str = "apex_tpu") -> logging.Logger:
    """Return a rank-aware logger. The handler is installed once per top-level
    logger hierarchy, so names outside ``apex_tpu.*`` get the rank prefix too.

    The returned logger carries a ``.metrics`` attribute — the
    ``<name>.metrics`` child logger the monitor's :class:`JsonlSink` uses
    for human-readable step lines — so telemetry text is filterable
    (``logging.getLogger("apex_tpu.monitor.metrics").setLevel(...)``)
    independently of the subsystem's own messages.
    """
    logger = logging.getLogger(name)
    root_name = name.split(".", 1)[0]
    if root_name not in _configured_roots:
        root = logging.getLogger(root_name)
        if not _has_rank_handler(root):
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(RankInfoFormatter(_FORMAT))
            root.addHandler(handler)
        root.propagate = False
        level = _env_level()
        if level is not None:
            root.setLevel(level)
        _configured_roots.add(root_name)
    logger.metrics = logging.getLogger(f"{name}.metrics")
    return logger

"""O1-style per-op mixed precision as a jaxpr-interpreting transform.

Reference: ``apex/amp/amp.py:68`` + ``wrap.py`` monkey-patch ~200 torch entry
points with cast wrappers because eager PyTorch has no graph to rewrite. JAX
traces to a jaxpr, so the same capability is a **function transform**:
:func:`autocast` traces the wrapped function, then re-evaluates the jaxpr with
per-primitive dtype rules from :mod:`apex_tpu.amp.lists` —

* whitelist (``dot_general``/``conv``): float inputs cast to the compute dtype
  (bf16/fp16) so they hit the MXU (ref ``wrap.py:10-29`` + cached_cast;
  no cast cache is needed — XLA CSEs the repeated weight casts),
* blacklist (exp/log/pow/reductions/...): float inputs cast to fp32
  (ref ``wrap.py:36-41`` maybe_float),
* everything else: mixed float inputs promoted to the widest present
  (ref ``wrap.py:43-63`` promote wrappers).

Higher-order primitives: ``scan``/``while``/``cond`` bodies are recursively
transformed with boundary casts so carry/branch signatures stay consistent;
``pjit`` regions are inlined; ``custom_jvp/vjp`` regions are left opaque at
their original dtypes (their authors chose those dtypes — and their custom
derivative rules must survive).

Composability: ``autocast`` runs at trace time, so ``jax.jit``, ``jax.grad``,
``shard_map`` etc. compose around it; under ``grad`` the casts are part of the
traced graph and AD differentiates through them (matching torch autocast
semantics, where casts are autograd ops).
"""

from __future__ import annotations

import contextvars
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.extend import core as jax_core
from jax.tree_util import tree_flatten, tree_unflatten

from apex_tpu.amp.lists import (
    CONTROL_FLOW_PRIM_NAMES,
    FP16_PRIMS,
    FP32_PRIMS,
    INLINE_PRIM_NAMES,
    OPAQUE_PRIM_NAMES,
)

_ACTIVE_COMPUTE_DTYPE: contextvars.ContextVar[Optional[Any]] = contextvars.ContextVar(
    "apex_tpu_autocast_compute_dtype", default=None
)


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)


def _cast(x, dtype):
    if _is_float(x) and jnp.result_type(x) != jnp.dtype(dtype):
        return lax.convert_element_type(x, dtype)
    return x


def _widest_float(vals):
    dt = None
    for v in vals:
        if _is_float(v):
            vdt = jnp.result_type(v)
            dt = vdt if dt is None else jnp.promote_types(dt, vdt)
    return dt


def _bind(prim, invals, params):
    """Bind an eqn the way core.eval_jaxpr does: recover callable
    sub-functions from stored jaxpr params first (custom_jvp/vjp, pjit, ...)."""
    subfuns, bind_params = prim.get_bind_params(params)
    out = prim.bind(*subfuns, *invals, **bind_params)
    return out if isinstance(out, (list, tuple)) else [out]


def _eval_autocast(jaxpr, consts, args, compute_dtype):
    env = {}

    def read(v):
        return v.val if isinstance(v, jax_core.Literal) else env[v]

    def write(v, val):
        env[v] = val

    for v, c in zip(jaxpr.constvars, consts):
        write(v, c)
    for v, a in zip(jaxpr.invars, args):
        write(v, a)

    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        prim = eqn.primitive
        params = dict(eqn.params)
        name = prim.name

        if name in INLINE_PRIM_NAMES:
            inner = params.get("jaxpr") or params.get("call_jaxpr")
            if hasattr(inner, "jaxpr"):  # ClosedJaxpr
                out = _eval_autocast(inner.jaxpr, inner.consts, invals, compute_dtype)
            else:
                out = _eval_autocast(inner, [], invals, compute_dtype)
        elif name in OPAQUE_PRIM_NAMES:
            invals = [
                _cast(val, var.aval.dtype) if _is_float(val) else val
                for val, var in zip(invals, eqn.invars)
            ]
            out = _bind(prim, invals, params)
        elif name in CONTROL_FLOW_PRIM_NAMES:
            out = _rebind_higher_order(eqn, invals, compute_dtype)
        elif prim in FP16_PRIMS:
            invals = [_cast(v, compute_dtype) for v in invals]
            # Whitelist ops *output* the compute dtype (ref wrap.py:10-29 —
            # the fp16 function returns fp16): downgrade an f32
            # preferred_element_type that only reflected the f32 trace. The
            # MXU still accumulates fp32 internally before rounding.
            if params.get("preferred_element_type") == jnp.float32:
                params["preferred_element_type"] = jnp.dtype(compute_dtype)
            out = _bind(prim, invals, params)
        elif prim in FP32_PRIMS:
            invals = [_cast(v, jnp.float32) for v in invals]
            out = _bind(prim, invals, params)
        else:
            wide = _widest_float(invals)
            if wide is not None and any(
                _is_float(v) and jnp.result_type(v) != wide for v in invals
            ):
                # Only promote where the primitive itself is dtype-polymorphic
                # over several float args (add/mul/concat/select...); prims
                # with a single float input are left alone.
                n_float = sum(1 for v in invals if _is_float(v))
                if n_float > 1:
                    invals = [_cast(v, wide) for v in invals]
            out = _bind(prim, invals, params)

        if prim.multiple_results:
            for v, o in zip(eqn.outvars, out):
                write(v, o)
        else:
            write(eqn.outvars[0], out[0])

    return [read(v) for v in jaxpr.outvars]


def _rebind_higher_order(eqn, invals, compute_dtype):
    """Re-trace scan/while/cond bodies under autocast with boundary casts so
    the loop-carry / branch-output signatures keep their traced dtypes."""
    prim = eqn.primitive
    params = dict(eqn.params)

    if prim.name == "scan":
        closed = params["jaxpr"]
        new_closed = _retrace_closed(closed, compute_dtype)
        params["jaxpr"] = new_closed
    elif prim.name == "while":
        params["cond_jaxpr"] = _retrace_closed(params["cond_jaxpr"], compute_dtype)
        params["body_jaxpr"] = _retrace_closed(params["body_jaxpr"], compute_dtype)
    elif prim.name == "cond":
        params["branches"] = tuple(
            _retrace_closed(b, compute_dtype) for b in params["branches"]
        )
    # Inputs must match the original signature dtypes.
    invals = [
        _cast(v, var.aval.dtype) if _is_float(v) else v
        for v, var in zip(invals, eqn.invars)
    ]
    return _bind(prim, invals, params)


def _retrace_closed(closed, compute_dtype):
    """Autocast a ClosedJaxpr, casting outputs back to their original dtypes."""
    inner_jaxpr, inner_consts = closed.jaxpr, closed.consts
    out_avals = [v.aval for v in inner_jaxpr.outvars]

    def body(*xs):
        outs = _eval_autocast(inner_jaxpr, inner_consts, list(xs), compute_dtype)
        return tuple(
            _cast(o, av.dtype) if _is_float(o) else o
            for o, av in zip(outs, out_avals)
        )

    in_structs = [
        jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype) for v in inner_jaxpr.invars
    ]
    return jax.make_jaxpr(body)(*in_structs)


def autocast(
    fn: Callable,
    compute_dtype=jnp.bfloat16,
    enabled: bool = True,
) -> Callable:
    """Wrap ``fn`` so its float ops run under the O1 per-op cast policy.

    Equivalent of running a model under ``amp.initialize(opt_level="O1")``
    (ref ``apex/amp/frontend.py:147-168`` + ``amp.py:68``). ``enabled=False``
    returns ``fn`` unchanged (ref ``handle.py:164`` ``disable_casts``).
    """
    if not enabled:
        return fn

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        flat_all, in_tree = tree_flatten((args, kwargs))
        # Non-array leaves (strings, None handled by tree, config flags,
        # python callables...) are static: closed over rather than traced —
        # the jaxpr-level analogue of jit's static_argnums.
        is_dynamic = [
            isinstance(x, (jax.Array, np.ndarray))
            or isinstance(x, (int, float, complex, bool, np.generic))
            for x in flat_all
        ]
        flat_args = [x for x, d in zip(flat_all, is_dynamic) if d]
        out_tree_box = []

        def f_flat(*flat):
            it = iter(flat)
            merged = [next(it) if d else x for x, d in zip(flat_all, is_dynamic)]
            a, k = tree_unflatten(in_tree, merged)
            out = fn(*a, **k)
            out_flat, out_tree = tree_flatten(out)
            out_tree_box.append(out_tree)
            return out_flat

        token = _ACTIVE_COMPUTE_DTYPE.set(compute_dtype)
        try:
            closed = jax.make_jaxpr(f_flat)(*flat_args)
        finally:
            _ACTIVE_COMPUTE_DTYPE.reset(token)
        out_flat = _eval_autocast(closed.jaxpr, closed.consts, list(flat_args), compute_dtype)
        return tree_unflatten(out_tree_box[0], out_flat)

    return wrapped


# ---------------------------------------------------------------------------
# User registration decorators (ref apex/amp/amp.py:30-64: half_function /
# float_function / promote_function and the register_* variants). In the
# trace-time design these insert explicit casts while an autocast trace is
# active; the interpreter then respects them (explicit convert_element_type is
# never rewritten).

def _region(fn, dtype_of):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        dt = dtype_of()
        if dt is None:  # no autocast active — behave like the raw function
            return fn(*args, **kwargs)
        args, kwargs = jax.tree_util.tree_map(
            lambda x: _cast(x, dt) if _is_float(x) else x, (args, kwargs)
        )
        return fn(*args, **kwargs)

    return wrapped


def half_function(fn: Callable) -> Callable:
    """Force ``fn``'s float inputs to the active compute dtype (ref amp.py:36)."""
    return _region(fn, _ACTIVE_COMPUTE_DTYPE.get)


def float_function(fn: Callable) -> Callable:
    """Force ``fn``'s float inputs to fp32 while autocast is active (ref amp.py:41)."""
    return _region(
        fn, lambda: jnp.float32 if _ACTIVE_COMPUTE_DTYPE.get() is not None else None
    )


def promote_function(fn: Callable) -> Callable:
    """Promote ``fn``'s float inputs to their widest dtype (ref amp.py:46)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if _ACTIVE_COMPUTE_DTYPE.get() is None:
            return fn(*args, **kwargs)
        leaves = [x for x in jax.tree_util.tree_leaves((args, kwargs)) if _is_float(x)]
        wide = _widest_float(leaves)
        if wide is not None:
            args, kwargs = jax.tree_util.tree_map(
                lambda x: _cast(x, wide) if _is_float(x) else x, (args, kwargs)
            )
        return fn(*args, **kwargs)

    return wrapped

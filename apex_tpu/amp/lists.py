"""Per-primitive cast policy tables — the trace-time analogue of amp's op lists.

Reference: ``apex/amp/lists/{functional,torch,tensor}_overrides.py`` classify
~200 torch entry points into FP16 (tensor-core ops), FP32 (numerically
sensitive), CASTS/promote (multi-arg widest-type), and BANNED. Here the
classification is over **JAX primitives**, which is both smaller and more
precise: whatever composite op a user calls (``jnp.softmax``, ``nn.gelu``)
decomposes into these primitives at trace time, so the policy catches
everything with no monkey-patching and no cache
(XLA CSEs repeated weight casts — the per-iteration cast cache of
``apex/amp/utils.py:95-140`` has no equivalent cost here).
"""

from __future__ import annotations

from jax import lax
from jax.extend import core as jax_core

# Ops whose FLOPs dominate and which the MXU runs natively in bf16/fp16
# (ref lists/torch_overrides.py:7-27 — BLAS + conv family).
FP16_PRIMS = {
    lax.dot_general_p,
    lax.conv_general_dilated_p,
}

# Numerically sensitive primitives kept in fp32
# (ref lists/torch_overrides.py:29-84 + functional_overrides.py:29-68:
# exp/log/pow family, softmax constituents, norms, losses, big reductions).
_FP32_PRIM_NAMES = [
    "exp",
    "exp2",
    "expm1",
    "log",
    "log1p",
    "logistic",
    "pow",
    "rsqrt",
    "erf",
    "erfc",
    "erf_inv",
    "acos",
    "acosh",
    "asin",
    "asinh",
    "atan",
    "atanh",
    "atan2",
    "cosh",
    "sinh",
    "tan",
    "digamma",
    "lgamma",
    "reduce_sum",
    "reduce_prod",
    "cumsum",
    "cumprod",
    "cumlogsumexp",
    "reduce_precision",
]


def _prims_by_name(names):
    out = set()
    for name in names:
        prim = getattr(lax, f"{name}_p", None)
        if isinstance(prim, jax_core.Primitive):
            out.add(prim)
    return out


FP32_PRIMS = _prims_by_name(_FP32_PRIM_NAMES)

# Everything else is "promote": run in the widest input dtype
# (ref lists/torch_overrides.py:86-111 CASTS — add/mul/cat/eq...). In JAX this
# is simply "cast mixed float inputs to the widest present", applied
# generically by the interpreter rather than enumerated.

# BANNED (ref functional_overrides.py:70-76): fp16 binary_cross_entropy is
# banned because log(sigmoid) saturates. There is no primitive-level
# equivalent to ban — the fp32 blacklist on exp/log already forces the
# sensitive part of any BCE decomposition to fp32 — so the table is empty.
BANNED_PRIMS: set = set()

# Higher-order primitive classification consumed by the autocast interpreter:
#
# INLINE: call-like wrappers whose bodies are evaluated directly (the jit
# boundary is re-established by the user's outer jit).
INLINE_PRIM_NAMES = {"pjit", "jit", "closed_call", "core_call", "remat", "checkpoint"}
# OPAQUE: custom-derivative regions rebound unchanged at their traced dtypes —
# their authors chose those dtypes, and the custom rules must survive.
OPAQUE_PRIM_NAMES = {
    "custom_jvp_call",
    "custom_vjp_call",
    "custom_vjp_call_jaxpr",
    "custom_jvp_call_jaxpr",
    "custom_lin",
}
# CONTROL FLOW: bodies re-traced under autocast with boundary casts so the
# carry/branch signatures keep their traced dtypes.
CONTROL_FLOW_PRIM_NAMES = {"scan", "while", "cond"}

"""Mixed precision (L2) — the trace-time re-design of ``apex.amp``.

Public surface (ref ``apex/amp/__init__.py`` + ``frontend.py:195`` +
``handle.py:17`` + ``amp.py:30-64``):

* :func:`initialize`, :func:`get_policy` — opt levels O0-O3 as declarative
  policies.
* :func:`autocast` — O1 per-op cast transform (replaces monkey-patching).
* :func:`scale_loss`, :func:`apply_grads` — dynamic loss scaling + skip-step.
* :class:`LossScaler` / :class:`LossScalerState` — the functional scaler.
* :func:`half_function` / :func:`float_function` / :func:`promote_function` —
  user registration decorators.
* :func:`state_dict` / :func:`load_state_dict` — checkpoint parity.
* :mod:`apex_tpu.amp.fp8` — the sub-8-bit tier: e4m3-forward /
  e5m2-gradient matmuls (``fp8.fp8_dot``) with per-tensor delayed
  scaling carried as a Metrics-pytree state; ``get_policy("FP8")`` is the
  policy declaration ``analyze.dtype_leak`` enforces.
"""

from apex_tpu.amp import fp8  # noqa: F401

from apex_tpu.amp.autocast import (  # noqa: F401
    autocast,
    float_function,
    half_function,
    promote_function,
)
from apex_tpu.amp.frontend import (  # noqa: F401
    AmpState,
    apply_grads,
    apply_grads_with_optimizer,
    cast_inputs,
    cast_params,
    default_norm_predicate,
    get_policy,
    initialize,
    load_state_dict,
    model_params,
    policy_compute_dtype,
    scale_loss,
    state_dict,
)
from apex_tpu.amp.scaler import LossScaler, LossScalerState  # noqa: F401

__all__ = [
    "AmpState",
    "LossScaler",
    "LossScalerState",
    "apply_grads",
    "apply_grads_with_optimizer",
    "autocast",
    "cast_inputs",
    "cast_params",
    "default_norm_predicate",
    "float_function",
    "fp8",
    "get_policy",
    "half_function",
    "initialize",
    "load_state_dict",
    "model_params",
    "policy_compute_dtype",
    "promote_function",
    "scale_loss",
    "state_dict",
]

"""amp frontend: opt-level presets, param casting, master weights, initialize.

Reference: ``apex/amp/frontend.py`` (``Properties`` + O0-O3 presets +
``initialize``), ``_initialize.py`` (model cast, forward patch, per-loss
scalers) and ``_process_optimizer.py`` (O2 master-weight machinery). The TPU
re-design is functional: instead of mutating models/optimizers in place, the
opt level resolves to a :class:`~apex_tpu.config.PrecisionConfig`, and the
master-weight flow is explicit pytree arithmetic inside the user's (jitted)
train step — which XLA fuses into the same single-sweep updates the reference
needs ``amp_C`` multi-tensor kernels for.

Typical O2 train step::

    amp_state = amp.initialize(params, opt_level="O2", loss_scale="dynamic")

    def train_step(amp_state, batch):
        model_params = amp.model_params(amp_state)        # bf16 cast-on-forward
        def loss_fn(p):
            loss = model.apply(p, batch)
            return amp_state.scaler_obj.scale_loss(loss, amp_state.scaler)
        grads = jax.grad(loss_fn)(model_params)
        new_master, amp_state, skipped = amp.apply_grads(
            amp_state, grads, lambda g, p: sgd_update(g, p))
        return amp_state
"""

from __future__ import annotations

import re
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.config import PrecisionConfig
from apex_tpu.amp.scaler import LossScaler, LossScalerState

# ---------------------------------------------------------------------------
# Opt-level presets (ref apex/amp/frontend.py:102-193)

_HALF = jnp.float16
_BF16 = jnp.bfloat16


def _preset(opt_level: str, half_dtype) -> PrecisionConfig:
    if opt_level == "O0":  # fp32 training (frontend.py:169-186)
        return PrecisionConfig(
            opt_level="O0",
            cast_model_type=None,
            compute_dtype=None,
            keep_batchnorm_fp32=None,
            master_weights=False,
            loss_scale=1.0,
        )
    if opt_level == "O1":  # per-op casting (frontend.py:147-168)
        return PrecisionConfig(
            opt_level="O1",
            cast_model_type=None,
            compute_dtype=half_dtype,
            keep_batchnorm_fp32=None,
            master_weights=None,
            loss_scale="dynamic",
        )
    if opt_level == "O2":  # half model + fp32 masters + fp32 norms (frontend.py:124-146)
        return PrecisionConfig(
            opt_level="O2",
            cast_model_type=half_dtype,
            compute_dtype=None,
            keep_batchnorm_fp32=True,
            master_weights=True,
            loss_scale="dynamic",
        )
    if opt_level == "O3":  # pure half, perf ceiling (frontend.py:102-123)
        return PrecisionConfig(
            opt_level="O3",
            cast_model_type=half_dtype,
            compute_dtype=None,
            keep_batchnorm_fp32=False,
            master_weights=False,
            loss_scale=1.0,
        )
    if opt_level == "FP8":  # sub-8-bit tier: e4m3 fwd / e5m2 grad dots
        # (apex_tpu.amp.fp8) with per-tensor delayed scaling — the
        # per-tensor scales replace the global loss scale (1.0), masters
        # stay fp32, norms stay wide (only the declared matmul sites
        # narrow). compute_dtype is THE policy declaration dtype_leak
        # verifies compiled steps against.
        import jax.numpy as _jnp
        return PrecisionConfig(
            opt_level="FP8",
            cast_model_type=None,
            compute_dtype=_jnp.float8_e4m3fn,
            keep_batchnorm_fp32=True,
            master_weights=True,
            loss_scale=1.0,
        )
    raise ValueError(
        f"Unexpected optimization level {opt_level!r} "
        "(options are 'O0', 'O1', 'O2', 'O3', 'FP8')"
    )


def policy_compute_dtype(policy: PrecisionConfig):
    """The effective low-precision dtype a policy declares for compute —
    the O2/O3 model-cast dtype, else the O1 per-op compute dtype, else
    ``None`` (O0: full precision, nothing to leak). This is THE policy-
    region declaration ``apex_tpu.analyze.dtype_leak`` verifies compiled
    steps against: a program whose dots run f32 under a policy that
    declares bf16 here is flagged as a leak."""
    dt = getattr(policy, "cast_model_type", None) or \
        getattr(policy, "compute_dtype", None)
    return jnp.dtype(dt) if dt is not None else None


def get_policy(
    opt_level: str = "O0", half_dtype=_BF16, **overrides
) -> PrecisionConfig:
    """Resolve an opt level + kwarg overrides to a PrecisionConfig
    (ref ``frontend.py:195-360`` property-override flow). ``half_dtype``
    defaults to bf16 — the TPU-native half type; pass ``jnp.float16`` for
    strict fp16 parity."""
    cfg = _preset(opt_level, half_dtype)
    if overrides:
        cfg = cfg.replace(**overrides)
    return cfg


# ---------------------------------------------------------------------------
# Param casting (ref _initialize.py:177-203 + fp16_utils/fp16util.py:60)

_NORM_COMPONENT = re.compile(
    # after lowercasing and stripping underscores:
    # [fused|mixedfused|sync]?[batch|group|layer|rms|instance]?norm[suffix]
    r"((fused|mixedfused|sync)?(batch|group|layer|rms|instance)?norm[a-z0-9]{0,3}"
    r"|(bn|gn|ln)[a-z0-9]{0,3})$"
)


def default_norm_predicate(path: str) -> bool:
    """Heuristic for "is this a normalization param" from its pytree path —
    the analogue of ``convert_network`` skipping ``_BatchNorm`` modules
    (ref ``fp16_utils/fp16util.py:60-88``). Matches flax-style scope components
    like ``BatchNorm_0``, ``FusedLayerNorm_2``, ``layer_norm``, ``ln_f``,
    ``bn1``. Pass a custom predicate to :func:`initialize` when your scopes
    don't follow these conventions."""
    return any(
        _NORM_COMPONENT.fullmatch(c.lower().replace("_", ""))
        for c in path.split("/")
    )


def _path_str(path) -> str:
    """Normalize a tree_map_with_path key path to 'a/b/c' form."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def cast_params(
    params: Any,
    policy: PrecisionConfig,
    is_norm_param: Callable[[str], bool] = default_norm_predicate,
) -> Any:
    """Cast a param pytree per the policy: float leaves → ``cast_model_type``,
    except normalization params when ``keep_batchnorm_fp32``
    (ref ``_initialize.py:177-182``)."""
    if policy.cast_model_type is None:
        return params
    target = policy.cast_model_type

    def leaf(path, x):
        if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
            return x
        if policy.keep_batchnorm_fp32 and is_norm_param(_path_str(path)):
            return x.astype(jnp.float32)
        return x.astype(target)

    return jax.tree_util.tree_map_with_path(leaf, params)


def cast_inputs(args: Any, policy: PrecisionConfig) -> Any:
    """Cast float inputs to the model compute type — the analogue of the
    patched ``model.forward`` input cast (ref ``_initialize.py:194-203``)."""
    if policy.cast_model_type is None:
        return args
    t = policy.cast_model_type
    return jax.tree_util.tree_map(
        lambda x: x.astype(t)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        args,
    )


# ---------------------------------------------------------------------------
# initialize + master-weight step (ref _process_optimizer.py)

class AmpState(NamedTuple):
    """Everything ``amp.initialize`` hangs off the model/optimizer in the
    reference, as one explicit checkpointable pytree."""

    master_params: Any  # fp32 masters when policy.master_weights, else model params
    scaler: LossScalerState
    policy: PrecisionConfig  # static (hashable dataclass)
    is_norm_param: Callable[[str], bool]  # static: the keep-fp32 predicate
    # scaler config is reconstructible from policy; kept object-free for jit.


jax.tree_util.register_pytree_node(
    AmpState,
    lambda s: ((s.master_params, s.scaler), (s.policy, s.is_norm_param)),
    lambda aux, kids: AmpState(kids[0], kids[1], aux[0], aux[1]),
)


def make_scaler(policy: PrecisionConfig) -> LossScaler:
    return LossScaler(policy.loss_scale)


def initialize(
    params: Any,
    opt_level: str = "O0",
    half_dtype=_BF16,
    is_norm_param: Callable[[str], bool] = default_norm_predicate,
    **overrides,
) -> Tuple[AmpState, PrecisionConfig]:
    """Functional ``amp.initialize`` (ref ``frontend.py:195``): resolve the
    policy, build fp32 masters if the policy wants them, and init the scaler.

    Returns ``(amp_state, policy)``. Model params for the forward pass come
    from :func:`model_params`; the optimizer runs on ``amp_state.master_params``.
    """
    policy = get_policy(opt_level, half_dtype, **overrides)
    if policy.master_weights:
        masters = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32)
            if jnp.issubdtype(jnp.result_type(x), jnp.floating)
            else x,
            params,
        )
    else:
        masters = params
    scaler = make_scaler(policy)
    return AmpState(masters, scaler.init_state(), policy, is_norm_param), policy


def model_params(state: AmpState) -> Any:
    """Model-dtype view of the masters — cast-on-forward (the O2 equivalent of
    keeping a fp16 model copy + ``_master_params_to_model_params`` after each
    step, ref ``_process_optimizer.py:14-25``; here it is a pure cast XLA
    fuses into the first consumer). Uses the ``is_norm_param`` predicate
    captured by :func:`initialize`."""
    return cast_params(state.master_params, state.policy, state.is_norm_param)


def scale_loss(loss: jnp.ndarray, state: AmpState) -> jnp.ndarray:
    """Ref ``handle.py:17`` ``scale_loss`` context entry."""
    return make_scaler(state.policy).scale_loss(loss, state.scaler)


def _unscale_and_check(state: AmpState, grads: Any, mp_axes):
    """Shared unscale + overflow-check + scale-update prelude."""
    scaler = make_scaler(state.policy)
    out_dtype = jnp.float32 if state.policy.master_weights else None
    grads, found_inf = scaler.unscale(grads, state.scaler, out_dtype=out_dtype)
    if mp_axes is not None:
        found_inf = LossScaler.all_reduce_found_inf(found_inf, mp_axes)
    new_scaler_state, skipped = scaler.update_scale(state.scaler, found_inf)
    return grads, new_scaler_state, skipped


def _guard_tree(skipped, new, old):
    """where-guard instead of lax.cond: both sides are cheap elementwise; a
    select keeps the step shape static and fuses (ref skip-step semantics,
    handle.py:131-158). Non-array leaves roll back too when eager."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(skipped, o, n)
        if hasattr(n, "dtype")
        else (o if skipped else n),
        new,
        old,
    )


def apply_grads(
    state: AmpState,
    grads: Any,
    update_fn: Callable[[Any, Any], Any],
    mp_axes: Optional[Any] = None,
) -> Tuple[AmpState, jnp.ndarray]:
    """Unscale grads, check overflow, run ``update_fn(grads, masters) ->
    new_masters`` unless skipping, update the scale.

    This is the exit path of ``with amp.scale_loss(...)`` plus the patched
    ``optimizer.step`` (ref ``handle.py:272-300`` + ``scaler.py:152-217``):
    one fused unscale+check sweep, a where-guarded update, scale adjustment.
    ``mp_axes``: mesh axis name(s) to psum the overflow flag over (the
    Megatron GradScaler behavior, ``transformer/amp/grad_scaler.py:25-60``).
    Returns ``(new_state, skipped)``.
    """
    grads, new_scaler_state, skipped = _unscale_and_check(state, grads, mp_axes)
    new_masters = update_fn(grads, state.master_params)
    guarded = _guard_tree(skipped, new_masters, state.master_params)
    return AmpState(guarded, new_scaler_state, state.policy, state.is_norm_param), skipped


def apply_grads_with_optimizer(
    state: AmpState,
    grads: Any,
    tx,  # optax.GradientTransformation
    opt_state: Any,
    mp_axes: Optional[Any] = None,
) -> Tuple[AmpState, Any, jnp.ndarray]:
    """:func:`apply_grads` specialized for an optax transform: unscale, check
    overflow, run ``tx.update`` on the masters, guard both the params and the
    optimizer state on overflow. Returns ``(amp_state, opt_state, skipped)``.

    This is the whole of the reference's patched ``optimizer.step`` +
    ``_post_amp_backward`` pipeline (``_process_optimizer.py:161-204,345-365``)
    in one call.
    """
    from apex_tpu.optimizers._common import apply_updates

    grads, new_scaler_state, skipped = _unscale_and_check(state, grads, mp_axes)
    updates, new_opt_state = tx.update(grads, opt_state, state.master_params)
    new_masters = apply_updates(state.master_params, updates)
    guarded_params = _guard_tree(skipped, new_masters, state.master_params)
    guarded_opt = _guard_tree(skipped, new_opt_state, opt_state)
    return (
        AmpState(guarded_params, new_scaler_state, state.policy, state.is_norm_param),
        guarded_opt,
        skipped,
    )


# ---------------------------------------------------------------------------
# Checkpointing (ref frontend.py:361-401)

def state_dict(state: AmpState) -> dict:
    scaler = make_scaler(state.policy)
    return {"loss_scaler0": scaler.state_dict(state.scaler)}


def load_state_dict(state: AmpState, d: dict) -> AmpState:
    scaler = make_scaler(state.policy)
    return state._replace(scaler=scaler.load_state_dict(d["loss_scaler0"]))

"""fp8 policy tier — e4m3 forward / e5m2 gradient with delayed scaling.

The sub-8-bit training recipe (Transformer Engine / FP8-LM lineage)
applied to the functional amp design: matmul operands are cast to
``float8_e4m3fn`` on the forward and the incoming cotangent to
``float8_e5m2`` on the backward (gradients need e5m2's 4× dynamic range;
activations/weights need e4m3's extra mantissa bit), each tensor carrying
a **per-tensor scale** chosen by *delayed scaling*: the scale used at step
``k`` is derived from the rolling amax history of steps ``< k``, so the
cast is a pure function of carried state — no data-dependent host sync,
no recompilation. The state rides the jitted step exactly like the
loss-scaler / EF-residual pytrees, and :func:`fp8_metrics` flattens it
onto the :class:`~apex_tpu.monitor.Metrics` pipeline (scales, amaxes, and
the ``fp8_overflow_rate`` saturation fraction the TPU watcher gates).

The one structural wrinkle is the backward: a custom-VJP backward cannot
emit a primal output, so the *gradient-side* amax observation travels as
the COTANGENT of the gradient tensor-state argument (the established
TE-JAX/flax ``q_dot_dq`` idiom). Concretely:

* forward-side state (``x``/``w`` halves) updates flow out of
  :func:`fp8_dot` as ordinary outputs;
* the gradient-side half updates arrive in ``jax.grad``'s slot for the
  state argument — differentiate the loss w.r.t. the fp8 state too and
  stitch the two with :func:`merge_state_grads`::

      def loss_fn(params, fp8_state):
          y, st1 = fp8.fp8_dot(x, params["w1"], fp8_state["l1"])
          ...
          return loss, new_fwd_states

      (loss, fwd_states), grads = jax.value_and_grad(
          loss_fn, argnums=(0, 1), has_aux=True)(params, fp8_state)
      fp8_state = fp8.merge_state_grads(fwd_states, grads[1])

:func:`fp8_policy` is the amp-side declaration —
``get_policy("FP8")`` resolves to a ``PrecisionConfig`` whose
``compute_dtype`` is e4m3, which is what
``apex_tpu.analyze.dtype_leak`` verifies compiled steps against (fp8 dots
pass; a smuggled fp32 dot under the policy fails).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Pytree = Any

E4M3 = jnp.dtype(jnp.float8_e4m3fn)
E5M2 = jnp.dtype(jnp.float8_e5m2)


def fp8_max(dtype) -> float:
    """Largest finite value of an fp8 dtype (448 for e4m3fn, 57344 for
    e5m2) — the clip bound of :func:`cast_fp8` and the numerator of the
    delayed-scaling rule."""
    try:
        import ml_dtypes
        return float(ml_dtypes.finfo(dtype).max)
    except Exception:  # pragma: no cover - ml_dtypes ships with jax
        return {E4M3: 448.0, E5M2: 57344.0}[jnp.dtype(dtype)]


@dataclasses.dataclass(frozen=True)
class Fp8Recipe:
    """Static delayed-scaling knobs (the TE recipe surface).

    ``history_len``: amax-history window (scales react within this many
    steps to a dynamic-range shift). ``margin``: scale = fp8_max /
    (max(history) · 2^margin) — a safety headroom in powers of two.
    ``fwd_dtype`` / ``grad_dtype``: the e4m3/e5m2 split.
    """

    history_len: int = 16
    margin: float = 0.0
    fwd_dtype: Any = E4M3
    grad_dtype: Any = E5M2

    def __post_init__(self):
        if self.history_len < 1:
            raise ValueError("history_len must be >= 1")
        if self.margin < 0:
            raise ValueError("margin must be >= 0")


class Fp8TensorState(NamedTuple):
    """Per-tensor delayed-scaling state: the scale the NEXT cast uses and
    the rolling amax history it was derived from, plus the last observed
    saturation fraction (elements clipping at the fp8 max — the
    ``fp8_overflow_rate`` telemetry)."""

    scale: jnp.ndarray          # f32 scalar
    amax_history: jnp.ndarray   # (history_len,) f32
    overflow_rate: jnp.ndarray  # f32 scalar, last cast's clip fraction


def init_tensor_state(recipe: Fp8Recipe = Fp8Recipe()) -> Fp8TensorState:
    return Fp8TensorState(scale=jnp.float32(1.0),
                          amax_history=jnp.zeros((recipe.history_len,),
                                                 jnp.float32),
                          overflow_rate=jnp.float32(0.0))


class Fp8DotState(NamedTuple):
    """The three tensor states of one fp8 matmul site: forward operand
    casts (``x``, ``w`` — e4m3) and the backward cotangent cast (``g`` —
    e5m2)."""

    x: Fp8TensorState
    w: Fp8TensorState
    g: Fp8TensorState


def init_dot_state(recipe: Fp8Recipe = Fp8Recipe()) -> Fp8DotState:
    return Fp8DotState(*(init_tensor_state(recipe) for _ in range(3)))


def init_fp8_state(names, recipe: Fp8Recipe = Fp8Recipe()
                   ) -> Dict[str, Fp8DotState]:
    """One :class:`Fp8DotState` per named matmul site."""
    return {str(n): init_dot_state(recipe) for n in names}


# ---------------------------------------------------------------------------
# cast + delayed-scale update


def cast_fp8(x, scale, dtype):
    """Scale, saturate and narrow to fp8. The scale is state, never data:
    ``stop_gradient`` so the backward differentiates the MATH, not the
    bookkeeping."""
    s = lax.stop_gradient(scale)
    m = fp8_max(dtype)
    return jnp.clip(x.astype(jnp.float32) * s, -m, m).astype(dtype)


def _observe(x, scale, dtype):
    """(amax, overflow_rate) of casting ``x`` at ``scale`` — the
    quantities the delayed-scaling update consumes."""
    ax = jnp.abs(x.astype(jnp.float32))
    amax = jnp.max(ax)
    over = jnp.mean((ax * lax.stop_gradient(scale)
                     > fp8_max(dtype)).astype(jnp.float32))
    return amax, over


def update_tensor_state(state: Fp8TensorState, amax, overflow_rate,
                        dtype, recipe: Fp8Recipe = Fp8Recipe()
                        ) -> Fp8TensorState:
    """Delayed scaling: roll ``amax`` into the history and derive the
    NEXT step's scale from the history maximum (so the scale at step k is
    a pure function of steps < k+1 — no in-step data dependence). A
    still-empty history (all zeros) keeps scale 1."""
    hist = jnp.concatenate([state.amax_history[1:],
                            jnp.reshape(amax, (1,)).astype(jnp.float32)])
    hmax = jnp.max(hist)
    new_scale = jnp.where(
        (hmax > 0) & jnp.isfinite(hmax),
        fp8_max(dtype) / (hmax * 2.0 ** recipe.margin),
        state.scale)
    return Fp8TensorState(scale=new_scale.astype(jnp.float32),
                          amax_history=hist,
                          overflow_rate=jnp.float32(overflow_rate))


# ---------------------------------------------------------------------------
# the fp8 matmul: e4m3 forward operands, e5m2 backward cotangent.
# custom_vjp so the backward dots also run on fp8 operands (the whole point
# — XLA would otherwise transpose the forward in fp32).


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fp8_dot(x, w, state: Fp8DotState, recipe: Fp8Recipe):
    qx = cast_fp8(x, state.x.scale, recipe.fwd_dtype)
    qw = cast_fp8(w, state.w.scale, recipe.fwd_dtype)
    y = lax.dot_general(qx, qw, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return (y / (state.x.scale * state.w.scale)).astype(x.dtype)


def _fp8_dot_fwd(x, w, state, recipe):
    qx = cast_fp8(x, state.x.scale, recipe.fwd_dtype)
    qw = cast_fp8(w, state.w.scale, recipe.fwd_dtype)
    y = lax.dot_general(qx, qw, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    y = (y / (state.x.scale * state.w.scale)).astype(x.dtype)
    return y, (qx, qw, state)


def _fp8_dot_bwd(recipe, res, dy):
    qx, qw, state = res
    sg = lax.stop_gradient(state.g.scale)
    qdy = cast_fp8(dy, sg, recipe.grad_dtype)
    nb = qx.ndim - 1  # batch dims of x
    # dx = dy @ w.T — e5m2 × e4m3 operands, f32 accumulate
    dx = lax.dot_general(qdy, qw, (((qdy.ndim - 1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dx = dx / (sg * lax.stop_gradient(state.w.scale))
    # dw = x.T @ dy — contract over every batch dim
    bdims = tuple(range(nb))
    dw = lax.dot_general(qx, qdy, ((bdims, bdims), ((), ())),
                         preferred_element_type=jnp.float32)
    dw = dw / (lax.stop_gradient(state.x.scale) * sg)
    # the gradient-side state update travels as the state cotangent (the
    # q_dot_dq idiom): harvest with jax.grad w.r.t. the state argument +
    # merge_state_grads
    amax_g, over_g = _observe(dy, sg, recipe.grad_dtype)
    new_g = update_tensor_state(state.g, amax_g, over_g,
                                recipe.grad_dtype, recipe)
    zero = jax.tree_util.tree_map(jnp.zeros_like, state.x)
    dstate = Fp8DotState(x=zero, w=zero, g=new_g)
    # the wrapper normalized both operands to f32, so f32 cotangents
    # match the primal avals by construction
    return dx.astype(jnp.float32), dw.astype(jnp.float32), dstate


_fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def fp8_dot(x, w, state: Fp8DotState, recipe: Fp8Recipe = Fp8Recipe()):
    """``x @ w`` with e4m3 forward operands and an e5m2 backward
    cotangent, per-tensor delayed scaling.

    Returns ``(y, new_state)`` where ``new_state`` carries the FORWARD
    halves' updates (``x``/``w`` amax histories + scales); the ``g`` half
    is returned unchanged — its update arrives as the cotangent of
    ``state`` when the caller differentiates w.r.t. it (see the module
    docstring and :func:`merge_state_grads`). ``x``: (..., k); ``w``:
    (k, n). The result is f32 (the dots accumulate f32 and the scales
    divide out there; narrow at the call site if the surrounding policy
    wants it).
    """
    y = _fp8_dot(x.astype(jnp.float32), w.astype(jnp.float32), state,
                 recipe)
    amax_x, over_x = _observe(x, state.x.scale, recipe.fwd_dtype)
    amax_w, over_w = _observe(w, state.w.scale, recipe.fwd_dtype)
    new_state = Fp8DotState(
        x=update_tensor_state(state.x, amax_x, over_x,
                              recipe.fwd_dtype, recipe),
        w=update_tensor_state(state.w, amax_w, over_w,
                              recipe.fwd_dtype, recipe),
        g=state.g)
    return y, new_state


def merge_state_grads(fwd_states: Pytree, state_grads: Pytree) -> Pytree:
    """Stitch one step's new fp8 state: the forward halves from the
    :func:`fp8_dot` outputs, the gradient halves from ``jax.grad``'s slot
    for the state argument (where the backward parked them)."""
    def merge(fwd: Fp8DotState, g: Fp8DotState) -> Fp8DotState:
        return Fp8DotState(x=fwd.x, w=fwd.w, g=g.g)

    return jax.tree_util.tree_map(
        merge, fwd_states, state_grads,
        is_leaf=lambda v: isinstance(v, Fp8DotState))


# ---------------------------------------------------------------------------
# policy declaration + telemetry + checkpointing


def fp8_policy():
    """The amp-side fp8 declaration: a ``PrecisionConfig`` whose
    ``compute_dtype`` is e4m3 — what ``amp.policy_compute_dtype`` resolves
    and ``analyze.dtype_leak`` enforces. Per-tensor scaling replaces the
    global loss scale (1.0)."""
    from apex_tpu.amp.frontend import get_policy

    return get_policy("FP8")


def fp8_metrics(state: Pytree, prefix: str = "fp8") -> Dict[str, Any]:
    """Flatten an fp8 state pytree to Metrics-ready named scalars: per-site
    scales and amaxes plus the headline ``{prefix}_overflow_rate`` (max
    saturation fraction across every cast site — lower is better, the
    watcher-gated field)."""
    out: Dict[str, Any] = {}
    rates = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            state, is_leaf=lambda v: isinstance(v, Fp8DotState))[0]:
        if not isinstance(leaf, Fp8DotState):
            continue
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        name = name or "dot"
        for half in ("x", "w", "g"):
            ts: Fp8TensorState = getattr(leaf, half)
            out[f"{prefix}_{name}_{half}_scale"] = ts.scale
            out[f"{prefix}_{name}_{half}_amax"] = jnp.max(ts.amax_history)
            rates.append(ts.overflow_rate)
    if rates:
        out[f"{prefix}_overflow_rate"] = jnp.max(jnp.stack(rates))
    return out


def state_dict(state: Pytree) -> Dict[str, Any]:
    """Flat, revision-stable serialization (the EF-residual/loss-scaler
    pattern): leaves keyed by flat index + the treedef string, so a resume
    against different code fails loudly instead of mis-binding."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    return {
        "treedef": str(treedef),
        "leaves": {str(i): np.asarray(x) for i, x in enumerate(leaves)},
    }


def load_state_dict(state_template: Pytree, d: Dict[str, Any]) -> Pytree:
    """Restore onto the live structure; validates treedef + leaf shapes."""
    leaves, treedef = jax.tree_util.tree_flatten(state_template)
    if d.get("treedef") is not None and d["treedef"] != str(treedef):
        raise ValueError(
            "fp8 state does not match the live structure:\n"
            f"  saved: {d['treedef']}\n  live:  {treedef}")
    if len(d["leaves"]) != len(leaves):
        raise ValueError(
            f"fp8 state has {len(d['leaves'])} saved leaves, live "
            f"structure has {len(leaves)}")
    new = []
    for i, want in enumerate(leaves):
        got = jnp.asarray(d["leaves"][str(i)], want.dtype)
        if got.shape != jnp.shape(want):
            raise ValueError(
                f"fp8 state leaf {i} shape mismatch: saved {got.shape}, "
                f"live {jnp.shape(want)}")
        new.append(got)
    return jax.tree_util.tree_unflatten(treedef, new)

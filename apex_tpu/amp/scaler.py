"""Dynamic loss scaling as a pure functional transform.

Reference: ``apex/amp/scaler.py:33-217`` (``LossScaler``) — scale the loss
before backward, unscale gradients with a fused multi-tensor sweep + overflow
check, then adjust the scale (×2 after 2000 clean steps, ÷2 on overflow,
min/max bounds) and skip the optimizer step on overflow.

TPU re-design: the scaler is a tiny pytree (:class:`LossScalerState`) threaded
through the jitted train step — no mutable singleton, no D2H ``.item()`` sync
(the reference pays one at ``scaler.py:206``). The overflow check is
``jnp.isfinite`` reduced over the grad pytree (XLA fuses this into the unscale
sweep, which is what ``amp_C.multi_tensor_scale`` hand-fuses), the step-skip
is a ``lax.cond``/``where`` on device, and the whole thing is checkpointable
because the state is explicit.

bf16 on TPU generally does not need loss scaling (same exponent range as
fp32); this exists for capability parity and for genuine fp16 use.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp


class LossScalerState(NamedTuple):
    """Checkpointable scaler state (ref ``scaler.py:33-64`` attributes;
    ``hysteresis_left`` is the Megatron GradScaler consecutive-overflow
    tolerance counter, ref ``transformer/amp/grad_scaler.py:61-106``)."""

    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray  # i32 scalar — clean steps since last growth
    hysteresis_left: jnp.ndarray  # i32 scalar — overflows until backoff


class LossScaler:
    """Static scaler config + pure methods over :class:`LossScalerState`.

    ``LossScaler("dynamic")`` reproduces the reference's dynamic policy
    (init 2**16, ×2/2000, ÷2 on overflow, max 2**24 — ``scaler.py:33-60,197-217``);
    ``LossScaler(128.0)`` is a static scale (update is a no-op).
    """

    def __init__(
        self,
        loss_scale: Union[str, float] = "dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: Optional[float] = None,
        max_loss_scale: float = 2.0 ** 24,
        backoff_factor: Optional[float] = None,
        hysteresis: int = 1,
    ):
        if loss_scale == "dynamic":
            self.dynamic = True
            self._init_scale = init_scale
        else:
            self.dynamic = False
            self._init_scale = float(loss_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        # shrink multiplier on overflow; the reference uses 1/scale_factor
        # (scaler.py:203), torch-style GradScaler exposes it separately.
        self.backoff_factor = (
            backoff_factor if backoff_factor is not None else 1.0 / scale_factor
        )
        self.min_loss_scale = min_loss_scale if min_loss_scale is not None else 1.0
        self.max_loss_scale = max_loss_scale
        # N consecutive overflows are tolerated before the scale backs off
        # (Megatron default 2; 1 = back off immediately, the apex.amp policy)
        self.hysteresis = int(hysteresis)

    # -- state ------------------------------------------------------------
    def init_state(self) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.asarray(self._init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            hysteresis_left=jnp.asarray(self.hysteresis, jnp.int32),
        )

    def loss_scale(self, state: LossScalerState) -> jnp.ndarray:
        return state.loss_scale

    # -- train-step ops ---------------------------------------------------
    def scale_loss(self, loss: jnp.ndarray, state: LossScalerState) -> jnp.ndarray:
        """Ref ``handle.py:270`` (yield ``loss.float() * loss_scale``). The
        result stays fp32 — a 2**16 scale overflows an fp16 loss of 1.0."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(
        self,
        grads: Any,
        state: LossScalerState,
        out_dtype: Optional[jnp.dtype] = jnp.float32,
    ) -> Tuple[Any, jnp.ndarray]:
        """Unscale a grad pytree and detect overflow in the same sweep.

        Ref ``scaler.py:94-150`` (``unscale`` via ``multi_tensor_scale`` with
        the fused inf/nan flag). Returns ``(unscaled_grads, found_inf)`` where
        ``found_inf`` is a f32 scalar 0/1 (f32 so it can ride a psum across
        model-parallel axes, ref ``transformer/amp/grad_scaler.py:25-60``).
        ``out_dtype=None`` keeps each leaf's dtype (the no-master-weights
        path); fp32 is the O2 master-grad path.
        """
        inv = 1.0 / state.loss_scale

        leaves = jax.tree_util.tree_leaves(grads)
        finite = (
            jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]).all()
            if leaves
            else jnp.asarray(True)
        )
        out = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(
                g.dtype if out_dtype is None else out_dtype
            ),
            grads,
        )
        found_inf = (~finite).astype(jnp.float32)
        return out, found_inf

    def update_scale(
        self, state: LossScalerState, found_inf: jnp.ndarray
    ) -> Tuple[LossScalerState, jnp.ndarray]:
        """Adjust the scale; return ``(new_state, should_skip)``.

        Ref ``scaler.py:197-217``: on overflow halve (bounded below) and reset
        the growth counter; after ``scale_window`` clean steps double (bounded
        above). ``should_skip`` is a traced bool — feed it to ``lax.cond`` or
        ``jnp.where`` around the optimizer update (the functional equivalent of
        the reference's patched ``optimizer.step``, ``handle.py:131-158``).
        """
        overflow = found_inf > 0
        if not self.dynamic:
            return state, overflow

        new_unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        grow = new_unskipped >= self.scale_window
        # hysteresis (Megatron-LM DynamicGradScaler semantics): each overflow
        # spends one credit, backoff fires at zero credits, and credits
        # refill ONLY when the scale grows after scale_window consecutive
        # clean steps — a lone clean step between overflows does not reset
        # the tolerance. hysteresis=1 degenerates to immediate backoff.
        new_hyst = jnp.where(
            overflow, state.hysteresis_left - 1,
            jnp.where(grow, self.hysteresis, state.hysteresis_left))
        backoff = overflow & (new_hyst <= 0)
        new_scale = jnp.where(
            backoff,
            jnp.maximum(state.loss_scale * self.backoff_factor, self.min_loss_scale),
            jnp.where(
                grow,
                jnp.minimum(state.loss_scale * self.scale_factor, self.max_loss_scale),
                state.loss_scale,
            ),
        )
        new_unskipped = jnp.where(grow, 0, new_unskipped)
        return LossScalerState(
            new_scale, new_unskipped.astype(jnp.int32),
            jnp.maximum(new_hyst, 0).astype(jnp.int32)), overflow

    # -- telemetry --------------------------------------------------------
    @staticmethod
    def metrics(state: LossScalerState, found_inf: Optional[jnp.ndarray] = None,
                metrics: Optional[Any] = None) -> Any:
        """Record scaler telemetry into a :class:`apex_tpu.monitor.Metrics`
        (in-graph, like everything else in this class): ``loss_scale``, the
        per-step ``overflow`` flag, and — when the Metrics is threaded
        through the step as a carry — cumulative ``overflow_total`` /
        ``skipped_total`` counters (identical under the dynamic policy:
        every overflow step is a skipped step). Pass ``metrics=None`` to
        start a fresh pytree; pass last step's to keep the counters."""
        from apex_tpu.monitor import Metrics  # lazy: amp has no hard dep

        m = Metrics() if metrics is None else metrics
        entries = {"loss_scale": state.loss_scale}
        if found_inf is not None:
            overflow = (jnp.asarray(found_inf) > 0).astype(jnp.float32)
            entries["overflow"] = overflow
            m = m.accumulate(overflow_total=overflow,
                             skipped_total=overflow)
        return m.record(**entries)

    # -- distributed ------------------------------------------------------
    @staticmethod
    def all_reduce_found_inf(
        found_inf: jnp.ndarray, axis_names: Union[str, Sequence[str]]
    ) -> jnp.ndarray:
        """Max-reduce the overflow flag across model-parallel axes so every
        rank agrees on skipping (ref ``transformer/amp/grad_scaler.py:25-60``,
        which all-reduces ``found_inf`` with MAX over the MP group). Call
        inside the mesh program."""
        return jax.lax.pmax(found_inf, axis_names)

    # -- checkpointing (ref frontend.py:361-401, scaler state entries) -----
    def state_dict(self, state: LossScalerState) -> dict:
        return {
            "loss_scale": float(state.loss_scale),
            "unskipped": int(state.unskipped),
            "hysteresis_left": int(state.hysteresis_left),
        }

    def load_state_dict(self, d: dict) -> LossScalerState:
        # A corrupt checkpoint must not resurrect a NaN/0/negative scale:
        # scale_loss multiplies it into every loss, so one bad restore
        # poisons every subsequent step with no overflow to catch it (the
        # unscale by 1/NaN is NaN too — found_inf fires forever and the
        # dynamic policy can never recover). Validate here, at the one
        # place checkpoints re-enter the scaler.
        import math

        raw = float(d["loss_scale"])
        if not math.isfinite(raw) or raw <= 0.0:
            raise ValueError(
                f"restored loss_scale {raw!r} is not a finite positive "
                "number — the checkpoint's scaler state is corrupt; "
                "re-initialize the scaler or resume from an older "
                "checkpoint")
        # and clamp into this scaler's configured bounds (a checkpoint
        # written under different min/max settings stays usable). Static
        # scalers keep the stored value — min/max only govern the dynamic
        # adjustment policy.
        scale = (min(max(raw, self.min_loss_scale), self.max_loss_scale)
                 if self.dynamic else raw)
        return LossScalerState(
            loss_scale=jnp.asarray(scale, jnp.float32),
            unskipped=jnp.asarray(d["unskipped"], jnp.int32),
            # pre-hysteresis checkpoints: full credits (the configured value)
            hysteresis_left=jnp.asarray(
                d.get("hysteresis_left", self.hysteresis), jnp.int32),
        )

"""Native (C++) runtime components, compiled on first use with g++.

The compute path is JAX/XLA/Pallas; these are the host-side runtime pieces
the reference also keeps native (SURVEY §2.2 note: "C++ only where an actual
host-side runtime component is required"). Build: ``build_lib()`` compiles
``dataloader.cpp`` to a cached ``.so`` with the system g++ (no pybind11 —
plain C ABI consumed via ctypes). Falls back gracefully: consumers must
treat ``build_lib() is None`` as "use the numpy path".
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import tempfile
from typing import Optional

_DIR = pathlib.Path(__file__).resolve().parent
_SRC = _DIR / "dataloader.cpp"
_lib = None
_tried = False


def _cache_path() -> pathlib.Path:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    root = pathlib.Path(os.environ.get("APEX_TPU_NATIVE_CACHE",
                                       _DIR / "_build"))
    return root / f"dataloader_{tag}.so"


def build_lib() -> Optional[ctypes.CDLL]:
    """Compile (once) and dlopen the native core; None if no toolchain."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    so = _cache_path()
    try:
        if not so.exists():
            so.parent.mkdir(parents=True, exist_ok=True)
            with tempfile.TemporaryDirectory() as td:
                tmp = pathlib.Path(td) / so.name
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", str(_SRC), "-o", str(tmp)],
                    check=True, capture_output=True)
                os.replace(tmp, so)
        lib = ctypes.CDLL(str(so))
        lib.al_create.restype = ctypes.c_void_p
        lib.al_create.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        lib.al_submit.restype = ctypes.c_uint64
        lib.al_submit.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int64, ctypes.c_void_p]
        lib.al_wait.restype = ctypes.c_int
        lib.al_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.al_normalize_u8_f32.restype = None
        lib.al_normalize_u8_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        lib.al_destroy.restype = None
        lib.al_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib

// Threaded batch-assembly core for the TPU input pipeline.
//
// Role (reference context): the reference's training input path is the
// examples' prefetcher (examples/imagenet/main_amp.py:265 — a CUDA-stream
// prefetcher that overlaps H2D copy + normalize with compute) plus the NVIDIA
// DALI ecosystem; its csrc/ runtime pieces (apex_C flatten, multi-tensor
// bucketing) are likewise native. On TPU the device-side work belongs to XLA,
// but the HOST side — gathering sample rows into contiguous batches and
// normalizing uint8 image data to float — is real CPU work that would
// otherwise serialize with the training loop under the GIL. This core does it
// in C++ worker threads with a request/ready ring, so Python only moves
// pointers.
//
// C API (ctypes-consumed, see apex_tpu/data/loader.py):
//   al_create(source, n_items, item_bytes, n_workers, queue_depth)
//   al_submit(loader, indices, n_idx, out_buffer)   -> ticket id
//   al_wait(loader, ticket)                         -> 0 on success
//   al_normalize_u8_f32(src, dst, n, c, mean[c], std[c], n_threads)
//   al_destroy(loader)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  uint64_t ticket;
  std::vector<int64_t> indices;
  uint8_t* out;
};

struct Loader {
  const uint8_t* source;
  int64_t n_items;
  int64_t item_bytes;
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::unordered_map<uint64_t, int> done;  // ticket -> status
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  std::atomic<uint64_t> next_ticket{1};
  bool stopping = false;

  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [&] { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        req = std::move(queue.front());
        queue.pop_front();
      }
      int status = 0;
      for (size_t i = 0; i < req.indices.size(); ++i) {
        int64_t idx = req.indices[i];
        if (idx < 0 || idx >= n_items) {
          status = 1;
          continue;
        }
        std::memcpy(req.out + i * item_bytes, source + idx * item_bytes,
                    static_cast<size_t>(item_bytes));
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        done[req.ticket] = status;
      }
      cv_done.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* al_create(const void* source, int64_t n_items, int64_t item_bytes,
                int n_workers, int /*queue_depth*/) {
  auto* l = new Loader();
  l->source = static_cast<const uint8_t*>(source);
  l->n_items = n_items;
  l->item_bytes = item_bytes;
  if (n_workers < 1) n_workers = 1;
  for (int i = 0; i < n_workers; ++i) {
    l->workers.emplace_back([l] { l->worker_loop(); });
  }
  return l;
}

uint64_t al_submit(void* loader, const int64_t* indices, int64_t n_idx,
                   void* out) {
  auto* l = static_cast<Loader*>(loader);
  Request req;
  req.ticket = l->next_ticket.fetch_add(1);
  req.indices.assign(indices, indices + n_idx);
  req.out = static_cast<uint8_t*>(out);
  {
    std::lock_guard<std::mutex> lock(l->mu);
    l->queue.push_back(std::move(req));
  }
  l->cv_work.notify_one();
  return req.ticket;
}

int al_wait(void* loader, uint64_t ticket) {
  auto* l = static_cast<Loader*>(loader);
  std::unique_lock<std::mutex> lock(l->mu);
  l->cv_done.wait(lock, [&] { return l->done.count(ticket) > 0; });
  int status = l->done[ticket];
  l->done.erase(ticket);
  return status;
}

// uint8 HWC image block -> float32, (x/255 - mean[c]) / std[c], threaded.
void al_normalize_u8_f32(const uint8_t* src, float* dst, int64_t n,
                         int64_t c, const float* mean, const float* stddev,
                         int n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::vector<float> scale(c), shift(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    shift[ch] = -mean[ch] / stddev[ch];
  }
  int64_t total = n * c;
  auto work = [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      int64_t ch = i % c;
      dst[i] = static_cast<float>(src[i]) * scale[ch] + shift[ch];
    }
  };
  if (n_threads == 1) {
    work(0, total);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (total + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t b = t * chunk;
    int64_t e = b + chunk < total ? b + chunk : total;
    if (b >= e) break;
    threads.emplace_back(work, b, e);
  }
  for (auto& th : threads) th.join();
}

void al_destroy(void* loader) {
  auto* l = static_cast<Loader*>(loader);
  {
    std::lock_guard<std::mutex> lock(l->mu);
    l->stopping = true;
  }
  l->cv_work.notify_all();
  for (auto& th : l->workers) th.join();
  delete l;
}

}  // extern "C"

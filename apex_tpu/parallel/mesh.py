"""Device-mesh construction — the TPU-native replacement for process groups.

Reference analogue: ``apex/transformer/parallel_state.py:57-185`` builds four
families of ``torch.distributed`` process groups (data-parallel, tensor-MP,
pipeline-MP, model-parallel) by slicing the flat rank list. On TPU the single
source of truth is one ``jax.sharding.Mesh`` with named axes; every "process
group" becomes a named axis (or tuple of axes) passed to ``lax.psum`` /
``all_gather`` / ``ppermute``, and "grouped" collectives (e.g. SyncBN process
groups, ``apex/parallel/__init__.py:58-95``) become collectives over a subset
of axes.

Axis order is chosen for the hardware, innermost-last so the highest-traffic
axis gets the fastest-varying device placement (contiguous ICI neighbours):
``("dp", "pp", "sp", "tp")``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

# Canonical axis names, outermost → innermost.
DP_AXIS = "dp"
PP_AXIS = "pp"
SP_AXIS = "sp"
TP_AXIS = "tp"
AXIS_ORDER: Tuple[str, ...] = (DP_AXIS, PP_AXIS, SP_AXIS, TP_AXIS)


def axis_size(axis_name, mesh: Optional[Mesh] = None) -> int:
    """Size of a mesh axis.

    Two calling conventions share this door:

    * ``axis_size(name)`` — the bound size from inside a mesh program.
      ``lax.axis_size`` on graft jax; on stock 0.4.37 that spelling does
      not exist, so ``jax.core.axis_frame(name)`` reads the traced axis
      env instead. Modules on the serve-plan path resolve the world size
      through here so a ``ParallelismPlan``-sharded engine runs on either
      toolchain (the same compatibility contract as the shard_map
      ``check_vma``/``check_rep`` shim in ``serve.sharded``).
    * ``axis_size(mesh, name)`` — static lookup outside any trace,
      ``mesh.shape[name]``.
    """
    if isinstance(axis_name, Mesh):  # legacy (mesh, axis) argument order
        return axis_name.shape[mesh]
    if mesh is not None:
        return mesh.shape[axis_name]
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return jax.core.axis_frame(axis_name)


def build_mesh(
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    dp: int = -1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global 4-axis mesh.

    ``dp=-1`` means "all remaining devices". Raises if the requested product
    does not divide the device count (mirrors the divisibility assertions in
    ``apex/transformer/parallel_state.py:80-90``).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    model = tp * pp * sp
    if dp == -1:
        if n % model != 0:
            raise ValueError(
                f"device count {n} is not divisible by tp*pp*sp = {model}"
            )
        dp = n // model
    if dp * model != n:
        raise ValueError(
            f"mesh shape dp={dp} pp={pp} sp={sp} tp={tp} requires {dp * model} "
            f"devices, have {n}"
        )
    shape = (dp, pp, sp, tp)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except (ImportError, ValueError, NotImplementedError) as e:
        # create_device_mesh optimizes placement for the physical ICI topology;
        # when it can't handle the shape, fall back to flat order but say so —
        # TP neighbours may no longer be contiguous ICI rings.
        from apex_tpu._logging import get_logger

        get_logger(__name__).warning(
            "mesh_utils.create_device_mesh failed (%s); falling back to flat "
            "device order — collective bandwidth may be degraded", e
        )
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, axis_names=AXIS_ORDER)


def build_hybrid_mesh(
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    dp_per_slice: int = -1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """DCN×ICI hybrid mesh for multi-slice / multi-host pods.

    Layout follows the standard scaling recipe: data parallelism is the
    ONLY axis that crosses the slice (DCN) boundary — its collectives are
    one bandwidth-tolerant psum per step — while tp/pp/sp stay inside a
    slice riding ICI. The reference reaches the same goal with NCCL
    process groups laid out host-major (``parallel_state.py:76-90``'s
    "adjacent ranks on the same DGX box" note); here
    ``mesh_utils.create_hybrid_device_mesh`` encodes it against the real
    slice topology (``device.slice_index``).

    ``dp_per_slice=-1`` means all remaining devices within each slice. On
    a single slice (or a simulation whose devices carry no slice index)
    this degrades to :func:`build_mesh` — same axes, ICI-only placement.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    slice_ids = sorted({getattr(d, "slice_index", 0) for d in devices})
    num_slices = len(slice_ids)
    if num_slices <= 1:
        return build_mesh(tp=tp, pp=pp, sp=sp, dp=dp_per_slice,
                          devices=devices)
    per_slice = len(devices) // num_slices
    model = tp * pp * sp
    if dp_per_slice == -1:
        if per_slice % model:
            raise ValueError(
                f"devices per slice ({per_slice}) not divisible by "
                f"tp*pp*sp = {model}")
        dp_per_slice = per_slice // model
    if dp_per_slice * model != per_slice:
        raise ValueError(
            f"dp_per_slice={dp_per_slice} x tp*pp*sp={model} != devices "
            f"per slice ({per_slice})")
    from jax.experimental import mesh_utils

    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(dp_per_slice, pp, sp, tp),
        dcn_mesh_shape=(num_slices, 1, 1, 1),
        devices=devices)
    return Mesh(dev_array, axis_names=AXIS_ORDER)


def model_parallel_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes forming the "model-parallel group" (ref parallel_state.py:110-120):
    everything except data parallel."""
    return tuple(a for a in mesh.axis_names if a != DP_AXIS)

"""Synchronized BatchNorm over a mesh axis.

Reference: two implementations —
python (``apex/parallel/sync_batchnorm.py:9-120`` + ``sync_batchnorm_kernel.py``:
local mean & sqr-mean, two ``all_reduce(SUM)``s, unbiased running-var update,
custom backward allreducing ``mean_dy`` / ``mean_dy_xmu``) and the optimized
CUDA path (``optimized_sync_batchnorm*.py`` + ``csrc/welford.cu``: local
Welford, single fused all_gather of [mean,var,count], ``welford_parallel``
merge, fused kernels, channels-last, group BN via ``process_group``).

TPU re-design: the statistics collectives are ``lax.psum`` of
``[sum, sum_sq, count]`` over the mesh axis (one fused psum — the analogue of
the optimized path's single combined all_gather; the Welford merge is
algebraically identical to merging (sum, sum_sq) and the fp32 accumulation
keeps it stable). The backward needs **no custom kernel**: JAX differentiates
through the forward psums, and the transpose of psum is exactly the
``mean_dy``/``mean_dy_xmu`` allreduce pair of the reference backward
(``sync_batchnorm_kernel.py:80-119``). "BN groups"
(``create_syncbn_process_group``, ``apex/parallel/__init__.py:58-95``) map to
``axis_index_groups`` on the psum.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import DP_AXIS


def create_syncbn_process_group(group_size: int, world_size: int):
    """Partition ``world_size`` ranks into contiguous groups of ``group_size``
    for grouped-stat BN (ref ``apex/parallel/__init__.py:58-95``). Returns the
    ``axis_index_groups`` argument for the psum."""
    if group_size == 0 or group_size >= world_size:
        return None
    if world_size % group_size != 0:
        raise ValueError(
            f"group_size {group_size} must divide world size {world_size}"
        )
    return [
        list(range(i, i + group_size)) for i in range(0, world_size, group_size)
    ]


def sync_batch_stats(
    x,
    reduce_axes,
    axis_name: Optional[str],
    axis_index_groups=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Cross-replica mean/var: one psum of the packed [sum, sum_sq, count]
    (the optimized path's single collective, ``optimized_sync_batchnorm_kernel.py:36-41``).
    Returns (mean, var, total_count) as fp32, per channel."""
    x32 = x.astype(jnp.float32)
    local_sum = jnp.sum(x32, axis=reduce_axes)
    local_sq = jnp.sum(x32 * x32, axis=reduce_axes)
    count = 1
    for a in reduce_axes:
        count *= x.shape[a]
    local_count = jnp.full_like(local_sum, float(count))
    packed = jnp.stack([local_sum, local_sq, local_count])
    if axis_name is not None:
        if axis_index_groups is None:
            packed = lax.psum(packed, axis_name)
        else:
            # Grouped reduction. shard_map does not support axis_index_groups
            # on psum, so gather the whole axis and slice out this rank's
            # (contiguous, uniform) group — the groups produced by
            # create_syncbn_process_group.
            gsize = len(axis_index_groups[0])
            if any(
                list(g) != list(range(i * gsize, (i + 1) * gsize))
                for i, g in enumerate(axis_index_groups)
            ):
                raise ValueError(
                    "axis_index_groups must be contiguous, uniform, and "
                    "aligned (group i covers ranks [i*gsize, (i+1)*gsize)) — "
                    "the groups create_syncbn_process_group produces"
                )
            gathered = lax.all_gather(packed, axis_name)  # (world, 3, C)
            gid = lax.axis_index(axis_name) // gsize
            grp = lax.dynamic_slice_in_dim(gathered, gid * gsize, gsize, 0)
            packed = jnp.sum(grp, axis=0)
    total_sum, total_sq, total_count = packed[0], packed[1], packed[2]
    mean = total_sum / total_count
    # E[x²]−E[x]² can go (slightly) negative by cancellation at small counts;
    # rsqrt(negative + eps) would be nan — clamp (the reference's Welford
    # formulation avoids this by construction, csrc/welford.cu)
    var = jnp.maximum(total_sq / total_count - mean * mean, 0.0)
    return mean, var, total_count


class SyncBatchNorm(nn.Module):
    """flax module with the reference's semantics (constructor mirrors
    ``optimized_sync_batchnorm.py:9-20``: ``momentum``, ``eps``, affine flags,
    ``process_group`` → ``axis_index_groups``, ``channel_last`` → the channel
    axis is always last here, NHWC being the TPU-native layout anyway).

    Stats sync across ``axis_name`` during training; running stats live in the
    ``batch_stats`` collection with the unbiased m/(m-1) correction
    (ref ``sync_batchnorm.py:96-104``). Call with ``use_running_average=True``
    for eval (no collectives, matching the reference eval path).
    """

    features: Optional[int] = None  # None: inferred from x.shape[-1]
    momentum: float = 0.1
    eps: float = 1e-5
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = DP_AXIS
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    param_dtype: jnp.dtype = jnp.float32
    fuse_relu: bool = False  # ref optimized path's fuse_relu option

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        reduce_axes = tuple(range(x.ndim - 1))
        features = self.features if self.features is not None else x.shape[-1]
        # During flax init there is no mesh axis bound — compute local stats
        # (same shapes, no collectives), like nn.BatchNorm's axis_name handling.
        axis_name = None if self.is_initializing() else self.axis_name
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )

        if use_running_average and self.track_running_stats:
            # eval path; with track_running_stats=False batch stats are used
            # even in eval (torch/apex semantics).
            mean, var = ra_mean.value, ra_var.value
        elif not self.track_running_stats:
            mean, var, _ = sync_batch_stats(
                x, reduce_axes, axis_name, self.axis_index_groups
            )
        else:
            mean, var, total_count = sync_batch_stats(
                x, reduce_axes, axis_name, self.axis_index_groups
            )
            if not self.is_initializing():
                # unbiased running var: m/(m-1) (ref sync_batchnorm.py:98-103)
                m = total_count
                unbiased = var * m / jnp.maximum(m - 1.0, 1.0)
                ra_mean.value = (
                    (1 - self.momentum) * ra_mean.value + self.momentum * mean
                )
                ra_var.value = (
                    (1 - self.momentum) * ra_var.value + self.momentum * unbiased
                )

        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.eps)
        if self.affine:
            w = self.param(
                "scale", nn.initializers.ones, (features,), self.param_dtype
            )
            b = self.param(
                "bias", nn.initializers.zeros, (features,), self.param_dtype
            )
            y = y * w + b
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(x.dtype)


def convert_syncbn_model(module: nn.Module, axis_name: str = DP_AXIS) -> nn.Module:
    """Best-effort analogue of ``apex.parallel.convert_syncbn_model``
    (``apex/parallel/__init__.py:21-57``): return a copy of a flax module with
    ``nn.BatchNorm`` submodule *fields* replaced by :class:`SyncBatchNorm`.

    flax modules are frozen dataclasses, so only directly-held BatchNorm
    attributes can be swapped generically (nested conversion belongs in the
    model definition — accept a ``norm_cls`` there, as
    ``apex_tpu.models.resnet`` does)."""
    changes = {}
    for name in getattr(module, "__dataclass_fields__", {}):
        val = getattr(module, name, None)
        if isinstance(val, nn.BatchNorm):
            changes[name] = SyncBatchNorm(
                features=None,  # inferred from input, like nn.BatchNorm
                momentum=1.0 - val.momentum,  # flax momentum is the decay
                eps=val.epsilon,
                axis_name=axis_name,
            )
    return module.clone(**changes) if changes else module

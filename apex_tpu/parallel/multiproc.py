"""Multi-host process bootstrap (ref ``apex/parallel/multiproc.py``).

Reference: a pre-``torchrun`` one-node launcher that spawns ``world_size``
subprocesses with ``--rank i`` (:12-35).

TPU re-design: TPU pods do not spawn per-device processes from Python — the
platform runner starts one process per host and JAX discovers peers. This
module provides the idiomatic equivalents:

* :func:`initialize_distributed` — ``jax.distributed.initialize`` from env
  (coordinator address / process id / count), the ``--rank``/``--world-size``
  analogue for multi-host DCN meshes.
* ``python -m apex_tpu.parallel.multiproc N -- cmd...`` — a local fan-out
  that runs ``cmd`` N times with ``RANK``/``WORLD_SIZE`` env set, for
  CPU-simulation workflows mirroring the reference CLI.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` with env-var fallbacks
    (COORDINATOR_ADDRESS / WORLD_SIZE|NPROCS / RANK|PROCESS_ID). No-op when
    single-process and no coordinator is configured."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("WORLD_SIZE") or os.environ.get("NPROCS")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("RANK") or os.environ.get("PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator_address is None and (num_processes or 1) <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 3 or argv[1] != "--":
        print("usage: python -m apex_tpu.parallel.multiproc N -- cmd [args...]",
              file=sys.stderr)
        return 2
    world = int(argv[0])
    cmd = argv[2:]
    procs = []
    for rank in range(world):
        env = dict(os.environ, RANK=str(rank), WORLD_SIZE=str(world))
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        rc = rc or p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())

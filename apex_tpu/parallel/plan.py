"""ParallelismPlan — every parallelism decision as ONE declarative object.

Before this module, composing the repo's parallel machinery was pairwise
wiring: examples hand-threaded DDP construction, compression configs, ZeRO
optimizer knobs, mesh shapes, overlap flags and checkpoint managers, and
every new strategy (now FSDP) would have multiplied the plumbing again.
The reference has the same disease in ``parallel_state.py`` (four process
group families built by hand at every call site); the GSPMD helpers the
SNIPPETS collect solve it with one mesh + named specs. ``ParallelismPlan``
is that idea for the whole stack:

* **mesh axes** — dp/tp/pp/sp sizes, validated against ``mesh.AXIS_ORDER``
  and the device count at :meth:`mesh` time (indivisible shapes fail
  loudly, at construction, with the arithmetic in the message);
* **data strategy** — ``"ddp"`` (replicated params, bucketed allreduce),
  ``"zero1"`` (``DistributedFusedAdam/LAMB``: sharded optimizer state),
  ``"fsdp"`` (``apex_tpu.fsdp``: sharded parameters, gather-on-demand);
* **wire policy** — one ``CompressionConfig`` for the gradient leg, an
  optional int8 ``weight_gather`` codec for the FSDP param gather, the
  ZeRO-1 ``e5m2_allgather`` transport;
* **overlap** — ``overlap_comm`` for the decomposed collective-matmul
  rings (TP boundaries via ``GPTConfig.overlap_comm``, FSDP weights via
  ``matmul_param_gather``);
* **kernel policy** — the ``fused_update`` Pallas tail mode;
* **composition hooks** — :meth:`checkpoint_manager` (resilience) and
  :meth:`hbm_params_bytes` / :meth:`describe` (monitor/accounting), so
  examples and benchmarks configure EVERYTHING through the plan.

Presets cover the recipes the examples/benchmarks ship::

    plan = ParallelismPlan.preset("fsdp+tp", tp=4)
    mesh = plan.mesh()                 # validated dp×pp×sp×tp Mesh
    opt = plan.build_optimizer(lr=1e-3)  # FSDPAdam riding plan.fsdp()
    print(plan.describe())
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

from apex_tpu.parallel.mesh import AXIS_ORDER, DP_AXIS, build_mesh

DATA_STRATEGIES = ("ddp", "zero1", "fsdp")
PRESETS = ("ddp", "zero1", "fsdp", "fsdp+tp")
OPTIMIZERS = ("adam", "lamb")


@dataclasses.dataclass(frozen=True)
class ParallelismPlan:
    """Declarative parallelism config; every field is validated at
    construction so a bad plan dies with a message, never mid-trace."""

    # data-parallel strategy (the ZeRO ladder rung)
    data: str = "ddp"
    # mesh shape: dp=-1 means "all remaining devices"
    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    # axis names — must come from mesh.AXIS_ORDER (one mesh vocabulary
    # program-wide; a typo'd axis dies here, not as an unbound-name trace
    # error deep inside a collective)
    dp_axis: str = DP_AXIS
    # wire policies
    compression: Optional[Any] = None  # CompressionConfig for the grad leg
    weight_gather: Optional[Any] = None  # int8 codec, FSDP param gather
    e5m2_allgather: bool = False  # ZeRO-1 param all-gather transport
    # overlap + kernels
    overlap_comm: bool = False
    bidirectional: bool = False
    fused_update: str = "auto"
    # optimizer family for the sharded strategies
    optimizer: str = "adam"

    def __post_init__(self):
        if self.data not in DATA_STRATEGIES:
            raise ValueError(
                f"data must be one of {DATA_STRATEGIES}, got {self.data!r}")
        if self.optimizer not in OPTIMIZERS:
            raise ValueError(
                f"optimizer must be one of {OPTIMIZERS}, "
                f"got {self.optimizer!r}")
        if self.dp_axis not in AXIS_ORDER:
            raise ValueError(
                f"dp_axis {self.dp_axis!r} is not a mesh axis; the mesh "
                f"vocabulary is {AXIS_ORDER}")
        for name in ("tp", "pp", "sp"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"{name} must be a positive int, got {v!r}")
        if not isinstance(self.dp, int) or (self.dp < 1 and self.dp != -1):
            raise ValueError(
                f"dp must be a positive int or -1 (all remaining devices), "
                f"got {self.dp!r}")
        if self.e5m2_allgather and self.data != "zero1":
            raise ValueError(
                "e5m2_allgather is the ZeRO-1 param-gather transport; "
                f"data={self.data!r} does not gather from a ZeRO-1 "
                "optimizer (FSDP's analogue is weight_gather=)")
        if self.weight_gather is not None and self.data != "fsdp":
            raise ValueError(
                "weight_gather is the FSDP param-gather codec; it has no "
                f"wire to ride under data={self.data!r}")
        if self.data == "fsdp" and self.optimizer != "adam":
            raise ValueError(
                "fsdp currently ships an Adam(W) shard optimizer only "
                "(FSDPAdam); optimizer='lamb' is a ZeRO-1 recipe")
        from apex_tpu.ops.fused_update import resolve_fused

        resolve_fused(self.fused_update)
        if self.data == "fsdp":
            self.fsdp()  # runs the FSDP codec validation eagerly

    # -- presets -----------------------------------------------------------
    @classmethod
    def preset(cls, name: str, **overrides) -> "ParallelismPlan":
        """The named recipes the examples/benchmarks expose as ``--plan``:
        ``ddp`` | ``zero1`` | ``fsdp`` | ``fsdp+tp`` (fsdp over dp composed
        with tensor parallelism + overlapped rings; default tp=2)."""
        if name not in PRESETS:
            raise ValueError(
                f"unknown plan preset {name!r}; presets: {PRESETS}")
        base = {
            "ddp": dict(data="ddp"),
            "zero1": dict(data="zero1"),
            "fsdp": dict(data="fsdp"),
            "fsdp+tp": dict(data="fsdp", tp=2, overlap_comm=True),
        }[name]
        base.update(overrides)
        return cls(**base)

    # -- mesh --------------------------------------------------------------
    def mesh(self, devices: Optional[Sequence[Any]] = None):
        """The validated dp×pp×sp×tp Mesh (``build_mesh`` raises with the
        divisibility arithmetic when the device count does not fit)."""
        return build_mesh(tp=self.tp, pp=self.pp, sp=self.sp, dp=self.dp,
                          devices=devices)

    def model_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if a != self.dp_axis)

    # -- component builders ------------------------------------------------
    def ddp(self, **kw):
        """The bucketed-allreduce DDP helper (data='ddp')."""
        if self.data != "ddp":
            raise ValueError(
                f"plan.data={self.data!r}: gradients ride the sharded "
                "optimizer's reduce-scatter, not a DDP allreduce")
        from apex_tpu.parallel.distributed import DistributedDataParallel

        return DistributedDataParallel(
            axis=self.dp_axis, compression=self.compression, **kw)

    def fsdp(self, **kw):
        """The ZeRO-3 engine (data='fsdp')."""
        if self.data != "fsdp":
            raise ValueError(f"plan.data={self.data!r} is not fsdp")
        from apex_tpu.fsdp import FSDP

        return FSDP(axis_name=self.dp_axis, compression=self.compression,
                    weight_gather=self.weight_gather,
                    bidirectional=self.bidirectional, **kw)

    def build_optimizer(self, lr: float = 1e-3, **kw):
        """The plan's optimizer: ``zero1`` → ``DistributedFusedAdam/LAMB``
        (sharded state, its own reduce-scatter/all-gather); ``fsdp`` →
        ``FSDPAdam`` (shard-only step); ``ddp`` → plain ``FusedAdam/LAMB``
        (pair with :meth:`ddp`'s ``average_gradients``)."""
        if self.data == "zero1":
            from apex_tpu.contrib.optimizers import (
                DistributedFusedAdam,
                DistributedFusedLAMB,
            )

            cls = (DistributedFusedAdam if self.optimizer == "adam"
                   else DistributedFusedLAMB)
            kwargs = dict(lr=lr, axis_name=self.dp_axis,
                          compression=self.compression,
                          fused_update=self.fused_update, **kw)
            if self.optimizer == "adam":
                kwargs["e5m2_allgather"] = self.e5m2_allgather
            elif self.e5m2_allgather:
                raise ValueError(
                    "e5m2_allgather is a DistributedFusedAdam option")
            return cls(**kwargs)
        if self.data == "fsdp":
            from apex_tpu.fsdp import FSDPAdam

            return FSDPAdam(fsdp=self.fsdp(), lr=lr,
                            fused_update=self.fused_update, **kw)
        from apex_tpu.optimizers import FusedAdam, FusedLAMB

        cls = FusedAdam if self.optimizer == "adam" else FusedLAMB
        return cls(lr=lr, **kw)

    def checkpoint_manager(self, directory: str,
                           allow_reshard: bool = False, **kw):
        """The resilience composition hook: an atomic manifested
        ``CheckpointManager`` — FSDP/ZeRO shard pytrees ride its
        fingerprinted (per-shard, under multi-process) manifest path.

        ``allow_reshard=True`` opts the manager's restores into the
        topology-elastic path (:mod:`apex_tpu.resilience.reshard`): a
        checkpoint saved with an ``elastic=`` spec (the plan's optimizers
        build one via ``elastic_spec(params, dp)``) restores onto a
        DIFFERENT dp degree's block-aligned layout, bitwise — the elastic
        resume `examples/*/--elastic` drives through
        :class:`~apex_tpu.resilience.TrainSupervisor`."""
        from apex_tpu.resilience import CheckpointManager

        return CheckpointManager(directory, allow_reshard=allow_reshard,
                                 **kw)

    def gpt_overrides(self) -> dict:
        """``GPTConfig`` fields this plan pins (benchmarks/tests splice
        them with ``dataclasses.replace``)."""
        out = {}
        if self.tp > 1:
            out["megatron_sp"] = True
            out["overlap_comm"] = self.overlap_comm
        return out

    # -- accounting / description ------------------------------------------
    def hbm_params_bytes(self, params_or_meta, world: int) -> dict:
        """Modeled per-chip param+grad+optimizer-state HBM of THIS plan's
        data strategy (``fsdp/accounting.py``)."""
        from apex_tpu.contrib.optimizers._sharding import shard_multiple_lcm
        from apex_tpu.fsdp.accounting import hbm_params_bytes

        return hbm_params_bytes(
            params_or_meta, strategy=self.data, world=world,
            shard_multiple=shard_multiple_lcm(self.compression,
                                              self.weight_gather))

    def describe(self) -> str:
        """The resolved plan, printable — the examples' ``--plan`` echo."""
        wire = self.compression.policy if self.compression else "fp32"
        wgather = (self.weight_gather.policy if self.weight_gather
                   else ("e5m2" if self.e5m2_allgather else "model-dtype"))
        lines = [
            f"ParallelismPlan(data={self.data}, optimizer={self.optimizer})",
            f"  mesh: dp={self.dp if self.dp != -1 else 'auto'} pp={self.pp}"
            f" sp={self.sp} tp={self.tp} (axes {AXIS_ORDER})",
            f"  grad wire: {wire}; param gather: "
            + (wgather if self.data != "ddp" else "n/a (replicated)"),
            f"  overlap_comm={self.overlap_comm}"
            f" bidirectional={self.bidirectional}"
            f" fused_update={self.fused_update}",
        ]
        return "\n".join(lines)

"""ParallelismPlan — every parallelism decision as ONE declarative object.

Before this module, composing the repo's parallel machinery was pairwise
wiring: examples hand-threaded DDP construction, compression configs, ZeRO
optimizer knobs, mesh shapes, overlap flags and checkpoint managers, and
every new strategy (now FSDP) would have multiplied the plumbing again.
The reference has the same disease in ``parallel_state.py`` (four process
group families built by hand at every call site); the GSPMD helpers the
SNIPPETS collect solve it with one mesh + named specs. ``ParallelismPlan``
is that idea for the whole stack:

* **mesh axes** — dp/tp/pp/sp sizes, validated against ``mesh.AXIS_ORDER``
  and the device count at :meth:`mesh` time (indivisible shapes fail
  loudly, at construction, with the arithmetic in the message);
* **data strategy** — ``"ddp"`` (replicated params, bucketed allreduce),
  ``"zero1"`` (``DistributedFusedAdam/LAMB``: sharded optimizer state),
  ``"fsdp"`` (``apex_tpu.fsdp``: sharded parameters, gather-on-demand);
* **wire policy** — one ``CompressionConfig`` for the gradient leg, an
  optional int8 ``weight_gather`` codec for the FSDP param gather, the
  ZeRO-1 ``e5m2_allgather`` transport;
* **overlap** — ``overlap_comm`` for the decomposed collective-matmul
  rings (TP boundaries via ``GPTConfig.overlap_comm``, FSDP weights via
  ``matmul_param_gather``);
* **kernel policy** — the ``fused_update`` Pallas tail mode;
* **composition hooks** — :meth:`checkpoint_manager` (resilience) and
  :meth:`hbm_params_bytes` / :meth:`describe` (monitor/accounting), so
  examples and benchmarks configure EVERYTHING through the plan.

Presets cover the recipes the examples/benchmarks ship::

    plan = ParallelismPlan.preset("fsdp+tp", tp=4)
    mesh = plan.mesh()                 # validated dp×pp×sp×tp Mesh
    opt = plan.build_optimizer(lr=1e-3)  # FSDPAdam riding plan.fsdp()
    print(plan.describe())
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

from apex_tpu.parallel.mesh import AXIS_ORDER, DP_AXIS, build_mesh

DATA_STRATEGIES = ("ddp", "zero1", "fsdp")
PRESETS = ("ddp", "zero1", "fsdp", "fsdp+tp")
OPTIMIZERS = ("adam", "lamb")
# inference residency strategies (apex_tpu.serve.sharded): which term of
# the plan carries the model when it does not fit one chip's HBM
SERVE_STRATEGIES = ("tp", "pp", "fsdp")


@dataclasses.dataclass(frozen=True)
class ParallelismPlan:
    """Declarative parallelism config; every field is validated at
    construction so a bad plan dies with a message, never mid-trace."""

    # data-parallel strategy (the ZeRO ladder rung)
    data: str = "ddp"
    # mesh shape: dp=-1 means "all remaining devices"
    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    # axis names — must come from mesh.AXIS_ORDER (one mesh vocabulary
    # program-wide; a typo'd axis dies here, not as an unbound-name trace
    # error deep inside a collective)
    dp_axis: str = DP_AXIS
    # wire policies
    compression: Optional[Any] = None  # CompressionConfig for the grad leg
    weight_gather: Optional[Any] = None  # int8 codec, FSDP param gather
    e5m2_allgather: bool = False  # ZeRO-1 param all-gather transport
    # overlap + kernels
    overlap_comm: bool = False
    bidirectional: bool = False
    fused_update: str = "auto"
    # optimizer family for the sharded strategies
    optimizer: str = "adam"

    def __post_init__(self):
        if self.data not in DATA_STRATEGIES:
            raise ValueError(
                f"data must be one of {DATA_STRATEGIES}, got {self.data!r}")
        if self.optimizer not in OPTIMIZERS:
            raise ValueError(
                f"optimizer must be one of {OPTIMIZERS}, "
                f"got {self.optimizer!r}")
        if self.dp_axis not in AXIS_ORDER:
            raise ValueError(
                f"dp_axis {self.dp_axis!r} is not a mesh axis; the mesh "
                f"vocabulary is {AXIS_ORDER}")
        for name in ("tp", "pp", "sp"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"{name} must be a positive int, got {v!r}")
        if not isinstance(self.dp, int) or (self.dp < 1 and self.dp != -1):
            raise ValueError(
                f"dp must be a positive int or -1 (all remaining devices), "
                f"got {self.dp!r}")
        if self.e5m2_allgather and self.data != "zero1":
            raise ValueError(
                "e5m2_allgather is the ZeRO-1 param-gather transport; "
                f"data={self.data!r} does not gather from a ZeRO-1 "
                "optimizer (FSDP's analogue is weight_gather=)")
        if self.weight_gather is not None and self.data != "fsdp":
            raise ValueError(
                "weight_gather is the FSDP param-gather codec; it has no "
                f"wire to ride under data={self.data!r}")
        if self.data == "fsdp" and self.optimizer != "adam":
            raise ValueError(
                "fsdp currently ships an Adam(W) shard optimizer only "
                "(FSDPAdam); optimizer='lamb' is a ZeRO-1 recipe")
        from apex_tpu.ops.fused_update import resolve_fused

        resolve_fused(self.fused_update)
        if self.data == "fsdp":
            self.fsdp()  # runs the FSDP codec validation eagerly

    # -- presets -----------------------------------------------------------
    @classmethod
    def preset(cls, name: str, **overrides) -> "ParallelismPlan":
        """The named recipes the examples/benchmarks expose as ``--plan``:
        ``ddp`` | ``zero1`` | ``fsdp`` | ``fsdp+tp`` (fsdp over dp composed
        with tensor parallelism + overlapped rings; default tp=2)."""
        if name not in PRESETS:
            raise ValueError(
                f"unknown plan preset {name!r}; presets: {PRESETS}")
        base = {
            "ddp": dict(data="ddp"),
            "zero1": dict(data="zero1"),
            "fsdp": dict(data="fsdp"),
            "fsdp+tp": dict(data="fsdp", tp=2, overlap_comm=True),
        }[name]
        base.update(overrides)
        return cls(**base)

    # -- mesh --------------------------------------------------------------
    def mesh(self, devices: Optional[Sequence[Any]] = None):
        """The validated dp×pp×sp×tp Mesh (``build_mesh`` raises with the
        divisibility arithmetic when the device count does not fit)."""
        return build_mesh(tp=self.tp, pp=self.pp, sp=self.sp, dp=self.dp,
                          devices=devices)

    def model_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if a != self.dp_axis)

    # -- component builders ------------------------------------------------
    def ddp(self, **kw):
        """The bucketed-allreduce DDP helper (data='ddp')."""
        if self.data != "ddp":
            raise ValueError(
                f"plan.data={self.data!r}: gradients ride the sharded "
                "optimizer's reduce-scatter, not a DDP allreduce")
        from apex_tpu.parallel.distributed import DistributedDataParallel

        return DistributedDataParallel(
            axis=self.dp_axis, compression=self.compression, **kw)

    def fsdp(self, **kw):
        """The ZeRO-3 engine (data='fsdp')."""
        if self.data != "fsdp":
            raise ValueError(f"plan.data={self.data!r} is not fsdp")
        from apex_tpu.fsdp import FSDP

        return FSDP(axis_name=self.dp_axis, compression=self.compression,
                    weight_gather=self.weight_gather,
                    bidirectional=self.bidirectional, **kw)

    def build_optimizer(self, lr: float = 1e-3, **kw):
        """The plan's optimizer: ``zero1`` → ``DistributedFusedAdam/LAMB``
        (sharded state, its own reduce-scatter/all-gather); ``fsdp`` →
        ``FSDPAdam`` (shard-only step); ``ddp`` → plain ``FusedAdam/LAMB``
        (pair with :meth:`ddp`'s ``average_gradients``)."""
        if self.data == "zero1":
            from apex_tpu.contrib.optimizers import (
                DistributedFusedAdam,
                DistributedFusedLAMB,
            )

            cls = (DistributedFusedAdam if self.optimizer == "adam"
                   else DistributedFusedLAMB)
            kwargs = dict(lr=lr, axis_name=self.dp_axis,
                          compression=self.compression,
                          fused_update=self.fused_update, **kw)
            if self.optimizer == "adam":
                kwargs["e5m2_allgather"] = self.e5m2_allgather
            elif self.e5m2_allgather:
                raise ValueError(
                    "e5m2_allgather is a DistributedFusedAdam option")
            return cls(**kwargs)
        if self.data == "fsdp":
            from apex_tpu.fsdp import FSDPAdam

            return FSDPAdam(fsdp=self.fsdp(), lr=lr,
                            fused_update=self.fused_update, **kw)
        from apex_tpu.optimizers import FusedAdam, FusedLAMB

        cls = FusedAdam if self.optimizer == "adam" else FusedLAMB
        return cls(lr=lr, **kw)

    def checkpoint_manager(self, directory: str,
                           allow_reshard: bool = False, **kw):
        """The resilience composition hook: an atomic manifested
        ``CheckpointManager`` — FSDP/ZeRO shard pytrees ride its
        fingerprinted (per-shard, under multi-process) manifest path.

        ``allow_reshard=True`` opts the manager's restores into the
        topology-elastic path (:mod:`apex_tpu.resilience.reshard`): a
        checkpoint saved with an ``elastic=`` spec (the plan's optimizers
        build one via ``elastic_spec(params, dp)``) restores onto a
        DIFFERENT dp degree's block-aligned layout, bitwise — the elastic
        resume `examples/*/--elastic` drives through
        :class:`~apex_tpu.resilience.TrainSupervisor`."""
        from apex_tpu.resilience import CheckpointManager

        return CheckpointManager(directory, allow_reshard=allow_reshard,
                                 **kw)

    def gpt_overrides(self) -> dict:
        """``GPTConfig`` fields this plan pins (benchmarks/tests splice
        them with ``dataclasses.replace``)."""
        out = {}
        if self.tp > 1:
            out["megatron_sp"] = True
            out["overlap_comm"] = self.overlap_comm
        return out

    # -- serving (apex_tpu.serve.sharded) ----------------------------------
    def serve_strategy(self) -> str:
        """Which residency strategy carries the model at inference:
        ``"tp"`` (head/vocab-sharded compute), ``"pp"`` (staged layer
        shards streaming activations) or ``"fsdp"`` (resident weight
        shards, gather-on-demand). Exactly ONE plan term may shard the
        model — the serving tier has no composed-strategy programs yet —
        and a plan that shards nothing is refused: the single-chip
        engine needs no plan."""
        sharded = []
        if self.tp > 1:
            sharded.append("tp")
        if self.pp > 1:
            sharded.append("pp")
        if self.data == "fsdp":
            sharded.append("fsdp")
        if len(sharded) > 1:
            raise NotImplementedError(
                f"plan shards the model {len(sharded)} ways at once "
                f"({'+'.join(sharded)}); serve.sharded composes ONE "
                "residency strategy per engine — split tp/pp/fsdp into "
                "separate plans (composed-strategy serving is future "
                "work; 'fsdp+tp' is a TRAINING preset)")
        if not sharded:
            raise ValueError(
                f"plan (data={self.data!r}, tp=1, pp=1) shards nothing "
                "at inference — the model fits or it doesn't, and this "
                "plan keeps it whole either way. Use the plain "
                "InferenceEngine, or set tp=/pp= or data='fsdp'")
        return sharded[0]

    def serve_overrides(self) -> dict:
        """The engine fields this plan pins at INFERENCE — the serving
        mirror of :meth:`gpt_overrides` (``serve.sharded.build_engine``
        splices them). Validates that the plan is inference-legal:
        knobs that exist only to feed an optimizer step are refused
        here, with the arithmetic, because serving would carry their
        cost and never cash it in.
        """
        if self.e5m2_allgather:
            # before the blanket zero1 refusal: the knob deserves its own
            # arithmetic (construction already pins e5m2 to data='zero1')
            raise ValueError(
                "e5m2_allgather is the ZeRO-1 optimizer param-gather "
                "transport (master shards -> model params, once per "
                "step); inference gathers from no optimizer — the "
                "serving analogue is weight_gather= on an fsdp plan")
        if self.data == "zero1":
            raise ValueError(
                "data='zero1' shards OPTIMIZER state only — params and "
                "grads stay replicated full-model, so a ZeRO-1 plan "
                "serves nothing a single chip doesn't (inference runs "
                "zero optimizer steps). Use tp=/pp= or data='fsdp'")
        if self.compression is not None and self.compression.error_feedback:
            raise ValueError(
                f"compression policy {self.compression.policy!r} carries "
                "an fp32 error-feedback residual (4 B/element — more HBM "
                "than the int8 wire it compensates saves) that telescopes "
                "into the NEXT optimizer step; inference runs none, so "
                "the residual is dead weight. Use policy 'int8'/'int4' "
                "or compression=None for serving plans")
        strategy = self.serve_strategy()
        out: dict = {"strategy": strategy,
                     "overlap_comm": self.overlap_comm}
        if strategy == "tp":
            out["tp"] = self.tp
        elif strategy == "pp":
            out["pp"] = self.pp
        else:
            out["dp_axis"] = self.dp_axis
            out["weight_gather"] = self.weight_gather
        return out

    def _serve_story(self) -> str:
        """One line of residency story for :meth:`describe` — field-based
        (never raises: a training-only plan still describes itself)."""
        wgather = (self.weight_gather.policy if self.weight_gather
                   else "model-dtype")
        if self.tp > 1 and self.pp == 1 and self.data != "fsdp":
            exits = ("overlapped rings" if self.overlap_comm
                     else "monolithic psum")
            return (f"TP — heads/vocab sharded {self.tp}-way, KV pools "
                    f"hold local heads; q_len>1 row exits {exits}, "
                    "q_len=1 monolithic")
        if self.pp > 1 and self.tp == 1 and self.data != "fsdp":
            return (f"PP — {self.pp} staged layer shards stream "
                    "activations (credit-windowed microbatches); each "
                    "stage owns its layers' KV pools")
        if self.data == "fsdp" and self.tp == 1 and self.pp == 1:
            return ("FSDP — block-aligned layer-weight shards resident, "
                    f"gathered on demand per layer ({wgather} wire); "
                    "embed/head + KV replicated")
        if self.tp > 1 or self.pp > 1 or self.data == "fsdp":
            return "composed model sharding — training-only (no serve tier)"
        return "single-chip engine (model unsharded at inference)"

    # -- accounting / description ------------------------------------------
    def hbm_params_bytes(self, params_or_meta, world: int) -> dict:
        """Modeled per-chip param+grad+optimizer-state HBM of THIS plan's
        data strategy (``fsdp/accounting.py``)."""
        from apex_tpu.contrib.optimizers._sharding import shard_multiple_lcm
        from apex_tpu.fsdp.accounting import hbm_params_bytes

        return hbm_params_bytes(
            params_or_meta, strategy=self.data, world=world,
            shard_multiple=shard_multiple_lcm(self.compression,
                                              self.weight_gather))

    def hbm_serve_bytes(self, params_or_meta, world: int,
                        kv_bytes: float = 0.0,
                        num_layers: Optional[int] = None) -> dict:
        """Modeled per-chip HBM of THIS plan's serve residency strategy —
        params + KV cache, NO grads or optimizer state (the inference-mode
        model in ``fsdp/accounting.py``). ``kv_bytes``: this chip's KV
        pool bytes (``serve.kv_cache.kv_cache_bytes`` of the LOCAL
        config). The headline proof: ``hbm_model_bytes`` of the unsharded
        model vs a chip budget, then ``total`` of each strategy under it."""
        from apex_tpu.contrib.optimizers._sharding import shard_multiple_lcm
        from apex_tpu.fsdp.accounting import hbm_serve_bytes

        return hbm_serve_bytes(
            params_or_meta, strategy=self.serve_strategy(), world=world,
            kv_bytes=kv_bytes, num_layers=num_layers,
            shard_multiple=shard_multiple_lcm(None, self.weight_gather))

    def describe(self) -> str:
        """The resolved plan, printable — the examples' ``--plan`` echo."""
        wire = self.compression.policy if self.compression else "fp32"
        wgather = (self.weight_gather.policy if self.weight_gather
                   else ("e5m2" if self.e5m2_allgather else "model-dtype"))
        lines = [
            f"ParallelismPlan(data={self.data}, optimizer={self.optimizer})",
            f"  mesh: dp={self.dp if self.dp != -1 else 'auto'} pp={self.pp}"
            f" sp={self.sp} tp={self.tp} (axes {AXIS_ORDER})",
            f"  grad wire: {wire}; param gather: "
            + (wgather if self.data != "ddp" else "n/a (replicated)"),
            f"  overlap_comm={self.overlap_comm}"
            f" bidirectional={self.bidirectional}"
            f" fused_update={self.fused_update}",
            f"  serve: {self._serve_story()}",
        ]
        return "\n".join(lines)

"""Data-parallel runtime (L4) — ref ``apex/parallel/__init__.py``.

Exports mirror the reference surface: ``DistributedDataParallel`` (bucketed,
overlap-friendly gradient averaging as a functional transform), ``Reducer``,
``SyncBatchNorm`` + ``convert_syncbn_model``, ``LARC``, and mesh helpers.
"""

from apex_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    DP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    build_mesh,
)

__all__ = [
    "AXIS_ORDER",
    "DP_AXIS",
    "PP_AXIS",
    "SP_AXIS",
    "TP_AXIS",
    "build_mesh",
    "DistributedDataParallel",
    "ParallelismPlan",
    "Reducer",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "LARC",
]


def __getattr__(name):
    try:
        if name in ("DistributedDataParallel", "Reducer"):
            from apex_tpu.parallel import distributed

            return getattr(distributed, name)
        if name == "ParallelismPlan":
            from apex_tpu.parallel.plan import ParallelismPlan

            return ParallelismPlan
        if name in ("SyncBatchNorm", "convert_syncbn_model"):
            from apex_tpu.parallel import sync_batchnorm

            return getattr(sync_batchnorm, name)
        if name == "LARC":
            from apex_tpu.parallel.larc import LARC

            return LARC
    except ModuleNotFoundError as e:
        raise AttributeError(
            f"module 'apex_tpu.parallel' has no attribute {name!r} ({e})"
        ) from e
    raise AttributeError(f"module 'apex_tpu.parallel' has no attribute {name!r}")

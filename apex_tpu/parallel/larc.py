"""LARC — Layer-wise Adaptive Rate Clipping/scaling.

Reference: ``apex/parallel/LARC.py:5-107``, a wrapper around any optimizer
that rescales each param's gradient by an adaptive local LR before the inner
step (``step`` at ``:78``)::

    local_lr = trust_coefficient * ||p|| / (||g|| + weight_decay * ||p|| + eps)
    clip mode:  g' = (g + wd*p) * min(local_lr / lr, 1)
    scale mode: g' = (g + wd*p) * local_lr
    params with ||p|| == 0 or ||g|| == 0 pass through unchanged

The wrapper zeroes the inner optimizer's own weight decay (the reference
temporarily sets group['weight_decay']=0 and folds wd into the grad) — so
construct the inner transform with ``weight_decay=0``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import Schedule, tree_map, value_at


def larc_transform(
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    lr: Optional[Schedule] = None,
) -> optax.GradientTransformation:
    """The grad-rescaling stage as a standalone transform; chain it before the
    inner optimizer: ``optax.chain(larc_transform(...), FusedSGD(lr, ...))``.
    ``lr`` is required in clip mode (the reference divides by group['lr'])."""
    if clip and lr is None:
        raise ValueError("clip mode requires the lr used by the inner optimizer")

    def init(params):
        return optax.ScaleByScheduleState(count=jnp.zeros((), jnp.int32))

    def update(grads, state, params):
        if params is None:
            raise ValueError("LARC requires params in update()")
        count = state.count + 1
        step_lr = value_at(lr, count) if lr is not None else None

        def leaf(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            g_norm = jnp.sqrt(jnp.sum(g32 * g32))
            adaptive = (
                trust_coefficient * p_norm / (g_norm + weight_decay * p_norm + eps)
            )
            if clip:
                adaptive = jnp.minimum(adaptive / step_lr, 1.0)
            adaptive = jnp.where((p_norm > 0) & (g_norm > 0), adaptive, 1.0)
            out = (g32 + weight_decay * p32) * adaptive
            return out.astype(g.dtype)

        return tree_map(leaf, grads, params), optax.ScaleByScheduleState(count=count)

    return optax.GradientTransformation(init, update)


def LARC(
    inner: optax.GradientTransformation,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    lr: Optional[Schedule] = None,
) -> optax.GradientTransformation:
    """Wrap ``inner`` with LARC (ref ``LARC.py:5`` constructor semantics)."""
    return optax.chain(
        larc_transform(trust_coefficient, clip, eps, weight_decay, lr), inner
    )

"""Data-parallel gradient synchronization — the DDP capability as a mesh
program.

Reference: ``apex/parallel/distributed.py:129-639`` — bucketed, comm/compute-
overlapped NCCL allreduce driven by per-param grad hooks: first backward
records arrival order, buckets are flattened (``apex_C.flatten``), optionally
cast fp32, pre-divided, allreduced on side streams, averaged and unflattened
back (``allreduce_bucket:425-470``), with options ``message_size``,
``allreduce_always_fp32``, ``gradient_average``, ``gradient_predivide_factor``,
``delay_allreduce``, ``num_allreduce_streams``.

TPU re-design: grads come out of ``jax.grad`` as one pytree, so "hook-driven
readiness" disappears; the capability that remains is (a) the collective
itself (``lax.psum`` over the ``dp`` mesh axis), (b) dtype policy, (c)
pre/post scaling, and (d) **bucketing** — concatenating many small grads into
a few flat buffers so the ICI sees large transfers (the reference's
``message_size`` batching; XLA also combines small all-reduces itself, this
makes the batching explicit and deterministic).

Comm/compute overlap: the per-bucket collectives are emitted inside the
jitted step so XLA's latency-hiding scheduler interleaves them with
independent work, replacing the reference's manual side streams + events
(``distributed.py:411-470``) — but a ``lax.scan`` is a scheduling barrier:
accumulate microbatch grads in a scan and every bucket's reduce waits for
the whole loop. :meth:`DistributedDataParallel.accumulate_and_average`
restores the reference's hook-driven overlap shape (``overlap_reductions``,
``delay_allreduce=False``): it scans all-but-the-last microbatch, runs the
LAST microbatch's backward unrolled outside the scan, and emits the bucket
reduces in **reverse production order** — each bucket's collective depends
only on its own leaves' final contributions, so the late-layer buckets
(whose grads finalize first in backward) launch while the front of the
backward is still computing. :meth:`average_gradients` emits the same
reverse order on the barriered path, where it is a free scheduler hint.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm.collectives import (
    CompressionConfig,
    allreduce_wire_bytes,
    compressed_allreduce,
    fold_seed,
)
from apex_tpu.comm.error_feedback import init_error_feedback
from apex_tpu.parallel.mesh import DP_AXIS


def _flatten_buckets(leaves: List[jnp.ndarray], message_size: int):
    """Group leaf indices into buckets of ~message_size elements per dtype
    (ref bucket construction, ``distributed.py:283-318`` + ``message_size``
    default 10M elements)."""
    buckets = []  # list of (dtype, [leaf_idx...])
    current = {}
    counts = {}
    for i, g in enumerate(leaves):
        dt = g.dtype
        current.setdefault(dt, []).append(i)
        counts[dt] = counts.get(dt, 0) + g.size
        if counts[dt] >= message_size:
            buckets.append((dt, current.pop(dt)))
            counts[dt] = 0
    for dt, idxs in current.items():
        if idxs:
            buckets.append((dt, idxs))
    return buckets


def _rebuild(comm_state, new_leaves):
    """Re-hang updated residual leaves on the comm_state structure."""
    if comm_state is None or new_leaves is None:
        return comm_state
    treedef = jax.tree_util.tree_structure(comm_state)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _record_comm_metrics(metrics, bucket_bytes, baseline_bytes):
    """Record per-bucket + total modeled wire bytes and the compression
    ratio into a monitor ``Metrics`` (all trace-time constants).
    ``bucket_bytes``/``baseline_bytes`` are keyed by BUCKET INDEX (tree
    order), so the ``comm_bucket{i}_bytes`` labels are stable however the
    reduction emission order is scheduled."""
    total = float(sum(bucket_bytes.values()))
    base = float(sum(baseline_bytes.values()))
    entries = {f"comm_bucket{i}_bytes": bucket_bytes[i]
               for i in sorted(bucket_bytes)}
    entries["comm_wire_bytes"] = total
    entries["comm_compression_ratio"] = base / total if total else 1.0
    return metrics.record(**entries)


class DistributedDataParallel:
    """Functional DDP: ``grads = ddp.average_gradients(grads)`` inside the
    mesh program (shard_map/pjit body). Mirrors the reference constructor
    options (``distributed.py:162-253``) that still have meaning under XLA.
    """

    def __init__(
        self,
        axis: str = DP_AXIS,
        message_size: int = 10_000_000,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        flat_buckets: bool = True,
        compression: Optional[CompressionConfig] = None,
    ):
        self.axis = axis
        self.message_size = message_size
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.flat_buckets = flat_buckets
        self.compression = compression

    def _world(self):
        # inside a mesh program the axis size is static
        return lax.axis_size(self.axis)

    def init_comm_state(self, grads_template: Any) -> Optional[Any]:
        """Error-feedback residuals for ``compression='int8_ef'`` — one fp32
        leaf per grad leaf, carried through the step like the loss-scaler
        state and threaded back into :meth:`average_gradients` via
        ``comm_state``. ``None`` for policies with no step-to-step state."""
        if self.compression is not None and self.compression.error_feedback:
            return init_error_feedback(grads_template)
        return None

    def comm_state_dict(self, comm_state: Any) -> Optional[dict]:
        """Serialize the error-feedback comm state for a checkpoint
        (``None`` stays ``None``) — the resilience manifest path: include
        the returned dict in the pytree handed to
        :class:`apex_tpu.resilience.CheckpointManager` (or any
        ``state_dict`` blob) so a resumed run keeps its residuals instead
        of silently restarting EF from zero."""
        from apex_tpu.comm import error_feedback as ef

        return None if comm_state is None else ef.state_dict(comm_state)

    def load_comm_state_dict(self, comm_state_template: Any,
                             d: Optional[dict]) -> Optional[Any]:
        """Inverse of :meth:`comm_state_dict`; validates the stored
        structure against the live one (from :meth:`init_comm_state`)."""
        from apex_tpu.comm import error_feedback as ef

        return None if d is None else ef.load_state_dict(
            comm_state_template, d)

    def replicate(self, params: Any) -> Any:
        """Mark params as per-replica (device-varying) inside the mesh
        program — the analogue of each DDP rank holding its own module copy.

        This matters for AD semantics: JAX's shard_map auto-inserts a psum
        when differentiating w.r.t. *replicated* values (the transpose of the
        implicit broadcast), which would double-count with
        :meth:`average_gradients`. Differentiate w.r.t.
        ``ddp.replicate(params)`` and the gradients come back per-replica,
        exactly like the reference's per-process ``.grad`` buffers, ready for
        the explicit allreduce."""
        return jax.tree_util.tree_map(
            lambda p: lax.pcast(p, self.axis, to="varying"), params
        )

    def average_gradients(self, grads: Any, enabled: bool = True,
                          comm_state: Optional[Any] = None, seed=None,
                          metrics: Optional[Any] = None) -> Any:
        """The allreduce_bucket pipeline (ref ``distributed.py:425-470``):
        [flatten] → [fp32 cast] → predivide → psum → postdivide → unflatten.
        Must be called inside a mesh program with ``self.axis`` bound.

        ``enabled``: static python bool — the functional form of the ref's
        ``disable_allreduce``/torch-DDP ``no_sync``. There is deliberately no
        stateful context-manager variant: under ``jit`` a mutable flag is
        frozen at trace time, so an accumulate-then-sync loop must instead
        trace two specializations (``enabled=False`` for accumulation
        microbatches, ``enabled=True`` for the boundary step) or accumulate
        on device and allreduce once — see
        ``pipeline_parallel/schedules/fwd_bwd_no_pipelining.py``.

        With a :class:`~apex_tpu.comm.CompressionConfig` the psum is the
        quantized two-pass allreduce (``comm/collectives.py``) — int8 codes
        + fp32 block scales on the wire. Policy ``int8_ef`` additionally
        threads the error-feedback residual: pass ``comm_state`` (from
        :meth:`init_comm_state`) and the return becomes ``(grads,
        new_comm_state)``; the residual lives in the same predivided units
        the wire carries, so ``gradient_predivide_factor`` composes. Under
        AMP those units include the loss scale: non-finite compression
        errors (overflow steps) are dropped rather than carried, and a
        dynamic-scale change mis-scales one step's correction by the
        ratio before EF re-absorbs it (the ZeRO optimizers, which see the
        scale, carry their residual unscaled instead).
        ``seed``: int32 scalar for ``stochastic_rounding`` (fold the step
        count in for fresh streams). Compressed results come off a final
        all-gather — replicated by construction, so programs that assert
        value-movement types need ``check_vma=False`` (the pattern
        ``tests/test_distributed_optimizers.py`` already uses for the ZeRO
        all-gathers).

        ``metrics``: an :class:`apex_tpu.monitor.Metrics` to record comm
        telemetry into — per-bucket modeled bytes-on-wire
        (``comm_bucket{i}_bytes``, ring model, identical to what
        ``comm.accounting`` prices off the compiled HLO), the
        ``comm_wire_bytes`` total, and ``comm_compression_ratio``
        (uncompressed-wire / actual-wire; 1.0 without compression). The
        values are trace-time constants — recording them never adds device
        work. When passed, the updated Metrics is appended to the return:
        ``grads`` → ``(grads, metrics)``; ``(grads, comm_state)`` →
        ``(grads, comm_state, metrics)``.
        """
        if not isinstance(enabled, bool):
            raise TypeError(
                f"enabled must be a static python bool, got {enabled!r}")
        cfg = self.compression
        compressing = cfg is not None and cfg.enabled
        if compressing and cfg.error_feedback and comm_state is None:
            raise ValueError(
                "compression policy 'int8_ef' carries state: pass comm_state="
                "ddp.init_comm_state(grads) and thread the returned state")
        # per-bucket modeled (actual, uncompressed-baseline) wire bytes —
        # python floats from static shapes, keyed by bucket index (tree
        # order) so the labels are emission-order-independent
        bucket_bytes: dict = {}
        baseline_bytes: dict = {}

        # uniform calling convention: state appended iff passed in, then
        # metrics iff passed in
        def wrap(g, s):
            out = (g,)
            if comm_state is not None:
                out += (s,)
            if metrics is not None:
                out += (_record_comm_metrics(metrics, bucket_bytes,
                                             baseline_bytes),)
            return out[0] if len(out) == 1 else out

        if not enabled:
            return wrap(grads, comm_state)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if not leaves:
            return wrap(grads, comm_state)
        world = self._world()

        def _account(bi: int, n: int, dtype) -> None:
            base_item = 4 if self.allreduce_always_fp32 else dtype.itemsize
            bucket_bytes[bi] = allreduce_wire_bytes(n, base_item, world, cfg)
            baseline_bytes[bi] = allreduce_wire_bytes(n, base_item, world,
                                                     None)

        # Predivide is applied unconditionally before the allreduce — it is
        # the fp16/bf16 overflow guard; only the post-multiply is gated on
        # gradient_average (ref distributed.py:445-454).
        pre = 1.0 / self.gradient_predivide_factor
        post = self.gradient_predivide_factor / world if self.gradient_average else 1.0

        res_leaves = (jax.tree_util.tree_flatten(comm_state)[0]
                      if comm_state is not None else None)
        new_res = list(res_leaves) if res_leaves is not None else None

        def _reduce_flat(flat, residual=None, bucket_seed=None):
            """-> (reduced flat, new residual or None). Traced under the
            canonical ``comm`` monitor span so the allreduce shows up as
            its own phase in trace/pyprof reports."""
            from apex_tpu.monitor.trace import span

            with span("comm"):
                if compressing:
                    comm = flat.astype(jnp.float32)
                    if pre != 1.0:
                        comm = comm * pre
                    comm, residual = compressed_allreduce(
                        comm, self.axis, cfg, residual=residual,
                        seed=bucket_seed)
                else:
                    comm = (flat.astype(jnp.float32)
                            if self.allreduce_always_fp32 else flat)
                    if pre != 1.0:
                        comm = comm * pre
                    comm = lax.psum(comm, self.axis)
                if post != 1.0:
                    comm = comm * post
            return comm, residual

        def _bucket_seed(i):
            # hash-combined, not seed+i: a step-counter seed must not make
            # bucket i at step s replay bucket i+1 at step s-1
            return None if seed is None else fold_seed(seed, i)

        # Reverse production order (satellite of the overlap work): the
        # backward emits the LAST layers' grads first, so the highest-index
        # buckets/leaves (tree order tracks forward order) finalize
        # earliest — emitting their reduces first is the reference's
        # arrival-order trick (``distributed.py:283-318``): the scheduler
        # sees launchable collectives while the front of the backward is
        # still computing. Pure emission-order change: bucket contents,
        # seeds and metric labels stay keyed by bucket index.
        if not self.flat_buckets:
            out = [None] * len(leaves)
            for i in reversed(range(len(leaves))):
                g = leaves[i]
                r = res_leaves[i].reshape(-1) if res_leaves is not None \
                    else None
                _account(i, g.size, g.dtype)
                red, r_new = _reduce_flat(g.reshape(-1), r, _bucket_seed(i))
                out[i] = red.reshape(g.shape).astype(g.dtype)
                if new_res is not None and r_new is not None:
                    new_res[i] = r_new.reshape(res_leaves[i].shape)
            return wrap(jax.tree_util.tree_unflatten(treedef, out),
                        _rebuild(comm_state, new_res))

        out = [None] * len(leaves)
        buckets = _flatten_buckets(leaves, self.message_size)
        for bi in reversed(range(len(buckets))):
            _dt, idxs = buckets[bi]
            flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
            _account(bi, flat.size, flat.dtype)
            residual = None
            if res_leaves is not None:
                residual = jnp.concatenate(
                    [res_leaves[i].reshape(-1) for i in idxs])
            red, r_new = _reduce_flat(flat, residual, _bucket_seed(bi))
            offset = 0
            for i in idxs:
                n = leaves[i].size
                out[i] = red[offset : offset + n].reshape(leaves[i].shape).astype(
                    leaves[i].dtype
                )
                if new_res is not None and r_new is not None:
                    new_res[i] = r_new[offset : offset + n].reshape(
                        res_leaves[i].shape)
                offset += n
        return wrap(jax.tree_util.tree_unflatten(treedef, out),
                    _rebuild(comm_state, new_res))

    def accumulate_and_average(
        self,
        value_and_grad_fn,
        params: Any,
        microbatches: Any,
        *,
        microbatch_keys: Optional[Any] = None,
        unroll: int = 1,
        enabled: bool = True,
        comm_state: Optional[Any] = None,
        seed=None,
        metrics: Optional[Any] = None,
    ):
        """Grad accumulation with overlap-scheduled reduction — the
        reference's ``overlap_reductions`` (``delay_allreduce=False``)
        rebuilt for XLA scheduling.

        The barriered recipe (``forward_backward_no_pipelining`` + one
        :meth:`average_gradients` after it) hides nothing: a ``lax.scan``
        releases ALL its outputs at once, so every bucket's collective
        waits for the full backward. This method restructures the same
        math — scan the first ``M-1`` microbatches, run the LAST
        microbatch's backward **unrolled outside the scan**, and emit the
        bucket reduces (via :meth:`average_gradients`, reverse production
        order) against it: each bucket's collective depends only on its
        own leaves' final-microbatch contributions, which materialize
        progressively through the unrolled backward, so the late-layer
        buckets launch while the early layers' dX/dW GEMMs are still
        running — grad-hook arrival-order overlap, from dataflow alone.

        ``value_and_grad_fn(params, microbatch[, key]) -> (loss, grads)``
        (close over ``ddp.replicate`` / loss scaling as needed);
        ``microbatches``: pytree with leading dim ``M``;
        ``microbatch_keys``: optional ``[M, ...]`` per-microbatch PRNG
        keys. Remaining kwargs go to :meth:`average_gradients`.

        Returns ``(mean_loss, grads[, comm_state][, metrics])`` —
        **loss-curve-identical** to the barriered path: the scan
        accumulates ``(((g₁+g₂)+…)+g_{M-1})`` and the peeled step adds
        ``g_M`` last, the exact association the full scan performs, and
        the reduction math is shared — only the schedule changes
        (``tests/test_overlap.py`` pins the equality, int8+EF included).
        """
        leaves = jax.tree_util.tree_leaves(microbatches)
        if not leaves:
            raise ValueError("microbatches is an empty pytree")
        m = leaves[0].shape[0]

        def call(mb, key):
            from apex_tpu.monitor.trace import span

            with span("fwd_bwd"):
                return (value_and_grad_fn(params, mb) if key is None
                        else value_and_grad_fn(params, mb, key))

        def take(i):
            return jax.tree_util.tree_map(lambda x: x[i], microbatches)
        last_key = (None if microbatch_keys is None
                    else microbatch_keys[m - 1])
        if m > 1:
            head = jax.tree_util.tree_map(lambda x: x[: m - 1], microbatches)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

            def body(acc, mk):
                mb, key = mk
                loss_sum, gacc = acc
                l, g = call(mb, key)
                return (loss_sum + l,
                        jax.tree_util.tree_map(jnp.add, gacc, g)), None

            if microbatch_keys is not None:
                (loss_sum, gacc), _ = lax.scan(
                    body, (jnp.zeros(()), zeros),
                    (head, microbatch_keys[: m - 1]), unroll=unroll)
            else:
                (loss_sum, gacc), _ = lax.scan(
                    lambda acc, mb: body(acc, (mb, None)),
                    (jnp.zeros(()), zeros), head, unroll=unroll)
            l_last, g_last = call(take(m - 1), last_key)
            loss_sum = loss_sum + l_last
            grads = jax.tree_util.tree_map(jnp.add, gacc, g_last)
        else:
            loss_sum, grads = call(take(0), last_key)
        red = self.average_gradients(grads, enabled=enabled,
                                     comm_state=comm_state, seed=seed,
                                     metrics=metrics)
        red = red if isinstance(red, tuple) else (red,)
        return (loss_sum / m,) + red

    def broadcast_params(self, params: Any) -> Any:
        """Make all ranks along the axis agree on rank-0's values (ref param
        broadcast at DDP init, ``distributed.py:254``). Implemented as a
        masked psum — same result as gathering and taking index 0, but 1x
        memory and ordinary allreduce traffic instead of a world-times-size
        gather."""
        # is_zero is device-varying; mixing it in makes the select varying
        # regardless of whether params came in replicated or per-replica.
        is_zero = lax.axis_index(self.axis) == 0
        return jax.tree_util.tree_map(
            lambda p: lax.psum(
                jnp.where(is_zero, p, jnp.zeros_like(p)), self.axis
            ),
            params,
        )


class Reducer:
    """Manual-sync variant (ref ``apex/parallel/distributed.py:89-128``):
    broadcast once, then ``reduce`` when the user says so — no averaging
    options, raw sum like the reference."""

    def __init__(self, axis: str = DP_AXIS):
        self.axis = axis

    def reduce(self, tree: Any) -> Any:
        return jax.tree_util.tree_map(lambda g: lax.psum(g, self.axis), tree)

    def broadcast_params(self, params: Any) -> Any:
        return DistributedDataParallel(axis=self.axis).broadcast_params(params)

"""Fused MLP — whole-network GEMM+bias+activation chain.

Reference: ``apex/mlp/mlp.py:8-80`` + ``csrc/mlp_cuda.cu`` (``mlp_cuda``):
a C++ loop of cuBLAS GemmEx calls with fused bias+relu/sigmoid epilogues and
a single pre-sized workspace, because eager torch would materialize every
intermediate and launch separate bias/activation kernels.

TPU re-design: the chain written as one jitted function IS the fused version —
XLA emits GEMMs with fused epilogues and keeps intermediates in registers/VMEM
where possible; bf16 inputs hit the MXU. The module matches the reference
constructor (``mlp_sizes``, ``bias``, ``activation`` in {none, relu, sigmoid}).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

_ACTS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_forward(x, kernels, biases=None, activation: str = "relu"):
    """Functional core. ``kernels``: list of (in, out) matrices; activation is
    applied after every layer except the last (ref ``mlp.py:20-24`` — the
    reference applies activation on hidden layers only)."""
    if activation not in _ACTS:
        raise ValueError(f"activation must be one of {sorted(_ACTS)}")
    act = _ACTS[activation]
    h = x
    n = len(kernels)
    for i, k in enumerate(kernels):
        h = h @ k
        if biases is not None:
            h = h + biases[i]
        if i < n - 1:
            h = act(h)
    return h


class MLP(nn.Module):
    """Ref ``apex/mlp/mlp.py:26-80`` (constructor takes the full size list,
    e.g. ``MLP([in, h1, h2, out])``)."""

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        sizes = list(self.mlp_sizes)
        if len(sizes) < 2:
            raise ValueError("mlp_sizes needs at least [in, out]")
        kernels = []
        biases = [] if self.bias else None
        for i in range(len(sizes) - 1):
            k = self.param(
                f"kernel_{i}",
                nn.initializers.variance_scaling(1.0, "fan_in", "uniform"),
                (sizes[i], sizes[i + 1]),
                self.param_dtype,
            )
            kernels.append(k)
            if self.bias:
                biases.append(
                    self.param(
                        f"bias_{i}", nn.initializers.zeros, (sizes[i + 1],),
                        self.param_dtype,
                    )
                )
        return mlp_forward(x, kernels, biases, self.activation)

from apex_tpu.mlp.mlp import MLP, mlp_forward  # noqa: F401

__all__ = ["MLP", "mlp_forward"]

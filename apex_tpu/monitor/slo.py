"""Declarative SLOs → goodput / violation accounting over rolling windows.

The ROADMAP item-2 currency is **goodput-under-SLO**: requests per second
that met EVERY latency budget, not raw throughput (a saturated engine can
post great tokens/s while every request blows its TTFT budget — MLPerf
inference draws the same line between "offered" and "completed within
bound"). This module is the accounting side:

* :class:`SloSpec` — the declarative budget set: TTFT (ms), per-output-
  token latency (TPOT, ms), max queue wait (ms), end-to-end (ms). ``None``
  budgets don't constrain. :meth:`SloSpec.check` classifies one retired
  request's measurements.
* :class:`SloTracker` — per-retirement :meth:`~SloTracker.observe` feeds
  lifetime counters, per-budget violation counts, per-metric
  :class:`~apex_tpu.monitor.hist.Histogram`\\ s (p50/p99 come from the
  bounded-error buckets, not a per-request list — O(1) memory over
  millions of requests) and a rolling window (default 60 s, monotonic
  timestamps) over which goodput/throughput rates are reported.
* :meth:`SloTracker.report` — one JSON-serializable dict (goodput req/s,
  violation counts, quantiles) that drops straight into a
  ``json_record`` line; ``benchmarks/loadgen.py`` emits exactly this.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, Optional

from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, HistSpec, Histogram

__all__ = ["SloSpec", "SloTracker"]

# the measured dimensions a retirement reports, in report order
DIMENSIONS = ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """Latency budgets, all in ms; ``None`` leaves a dimension
    unconstrained. A request is GOOD iff every constrained dimension is
    within budget (inclusive)."""

    ttft_ms: Optional[float] = None    # time to first token
    tpot_ms: Optional[float] = None    # mean per-output-token latency
    queue_ms: Optional[float] = None   # submit -> admitted wait
    e2e_ms: Optional[float] = None     # submit -> retired

    def validate(self) -> None:
        for dim in DIMENSIONS:
            v = getattr(self, dim)
            if v is not None and v <= 0:
                raise ValueError(f"{dim} budget must be positive, got {v}")

    def budgets(self) -> Dict[str, float]:
        return {d: getattr(self, d) for d in DIMENSIONS
                if getattr(self, d) is not None}

    def check(self, **measured: Optional[float]) -> Dict[str, bool]:
        """Violation flags per CONSTRAINED dimension (True = violated).
        A missing/None measurement never violates (e.g. tpot of a
        single-token request is undefined)."""
        out = {}
        for dim, budget in self.budgets().items():
            v = measured.get(dim)
            out[dim] = v is not None and v > budget
        return out

    def to_dict(self) -> Dict[str, float]:
        return self.budgets()


class SloTracker:
    """Rolling goodput/violation accounting against one :class:`SloSpec`.

    ``observe`` once per retired request with whatever dimensions were
    measured; ``report`` at any time. ``window_s`` bounds the rate
    window; counters and histograms are lifetime. The clock defaults to
    ``time.perf_counter`` — share the :class:`~apex_tpu.monitor.events.
    EventLog`'s clock (pass ``clock=log.now_ms`` scaled) only if you need
    the two aligned; rates only ever subtract this tracker's own stamps.
    """

    def __init__(self, spec: SloSpec, window_s: float = 60.0,
                 hist_spec: Optional[HistSpec] = None,
                 hists: Optional[Dict[str, Histogram]] = None,
                 clock: Callable[[], float] = time.perf_counter):
        spec.validate()
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        self.spec = spec
        self.window_s = float(window_s)
        self._clock = clock
        self._t0 = clock()
        self.completed = 0
        self.good = 0
        self.violations: Dict[str, int] = {d: 0 for d in spec.budgets()}
        # hists= shares a caller's Histogram instances (the serve engine
        # passes its own, so one retirement folds each latency exactly
        # once and engine.stats + slo_report read one source of truth)
        if hists is not None and set(hists) != set(DIMENSIONS):
            raise ValueError(
                f"hists must cover exactly {DIMENSIONS}, "
                f"got {tuple(sorted(hists))}")
        self.hists: Dict[str, Histogram] = hists if hists is not None else {
            d: Histogram(hist_spec or DEFAULT_LATENCY_SPEC)
            for d in DIMENSIONS}
        # rolling (t, good) pairs, pruned to window_s on observe/report
        self._window: collections.deque = collections.deque()

    def observe(self, t: Optional[float] = None,
                **measured: Optional[float]) -> bool:
        """Account one retired request (dimensions from
        :data:`DIMENSIONS`, ms). Returns whether it met the SLO."""
        now = self._clock() if t is None else t
        for dim, v in measured.items():
            if dim not in self.hists:
                raise ValueError(f"unknown dimension {dim!r}; "
                                 f"expected one of {DIMENSIONS}")
            if v is not None:
                self.hists[dim].add([float(v)])
        flags = self.spec.check(**measured)
        ok = not any(flags.values())
        self.completed += 1
        self.good += ok
        for dim, bad in flags.items():
            self.violations[dim] += bad
        self._window.append((now, ok))
        self._prune(now)
        return ok

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        w = self._window
        while w and w[0][0] < cutoff:
            w.popleft()

    def report(self, quantiles=(0.5, 0.99)) -> Dict[str, Any]:
        """Goodput/violation snapshot, JSON-serializable. Rates are over
        ``min(window_s, elapsed)`` so short runs aren't diluted by the
        empty part of the window."""
        now = self._clock()
        self._prune(now)
        elapsed = max(now - self._t0, 1e-9)
        span = min(self.window_s, elapsed)
        in_window = len(self._window)
        good_in_window = sum(ok for _, ok in self._window)
        rep: Dict[str, Any] = {
            "completed": self.completed,
            "good": self.good,
            "goodput_rps": round(good_in_window / span, 4),
            "throughput_rps": round(in_window / span, 4),
            "good_fraction": (round(self.good / self.completed, 4)
                              if self.completed else None),
            "window_s": round(span, 3),
            "slo": self.spec.to_dict(),
            "violations": dict(self.violations),
        }
        for dim in DIMENSIONS:
            h = self.hists[dim]
            if h.total == 0:
                continue
            for q in quantiles:
                v = h.quantile(q)
                rep[f"{dim}_p{int(q * 100)}"] = (round(v, 3)
                                                 if v is not None else None)
        return rep

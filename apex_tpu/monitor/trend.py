"""Longitudinal trend gating over banked watcher records (tier 4).

``monitor.regress`` diffs two records pairwise, so a 15% gate never trips
on a 3%-per-week drift: each hourly record sits inside tolerance of the
one before it while the series walks away. This module closes that gap
with an append-only HISTORY per watcher stage and robust drift detection
over the whole series:

* **history** — one ``trend_point`` JSONL line per banked record
  (:func:`append_history` rides ``json_record``, so entries carry the
  schema stamp and — when the emitting process set one — the shared
  provenance dict: git sha / jax version / backend / hostname, without
  which a detected drift can't be tied to what changed);
  :func:`load_history` reads it back through ``read_jsonl`` (rotation-
  and crash-tail-tolerant like every sink in the repo).
* **detection** (:func:`detect_trends`) — per flattened metric key
  (polarity from ``regress.classify_metric``; unclassifiable keys are
  skipped, never guessed):

  - *step changes*: robust z of the recent ``window`` records' median
    against the older records' median, scaled by 1.4826·MAD (floored at
    ``rel_floor`` of the baseline so a zero-variance series isn't a
    hair-trigger). Beyond ``threshold`` in the BAD direction → drift.
  - *slow drifts*: Theil–Sen slope (median of pairwise slopes — robust
    to outlier records) over the full series; a projected total move
    beyond ``threshold`` scales in the bad direction → drift, even when
    every pairwise hop stayed under the regress gate.

  Good-direction moves never flag (an improvement is not a drift), and
  the report carries a ``drift_score`` (max bad |z| / threshold; 0 when
  clean) — itself lower-better under regress.
* **CLI** — ``python -m apex_tpu.monitor.trend append HISTORY RECORD
  [--stage S]`` banks a record into the history;
  ``python -m apex_tpu.monitor.trend check HISTORY [--window W]
  [--threshold Z] [--min-records N]`` prints the verdict table to stderr,
  one ``json_record`` line to stdout, and exits 1 on drift — the
  tpu_watch stages run both next to (never instead of) the pairwise
  regress gate. A history shorter than ``--min-records`` passes
  trivially: the gate arms itself as evidence accumulates.
"""

from __future__ import annotations

import statistics
import sys
from typing import Any, Dict, Iterable, List, Mapping, Optional

from apex_tpu.monitor.regress import (
    classify_metric,
    flatten_record,
    load_record,
)
from apex_tpu.monitor.sink import json_record, read_jsonl

__all__ = ["append_history", "detect_trends", "load_history", "main",
           "theil_sen_slope"]

DEFAULT_WINDOW = 5
DEFAULT_THRESHOLD = 6.0
DEFAULT_MIN_RECORDS = 8
# MAD floor as a fraction of the baseline median: series quieter than
# this are treated as having this much noise (a 0.1% wiggle on a
# dead-flat series is not a changepoint)
DEFAULT_REL_FLOOR = 0.02


def append_history(path: str, record: Mapping[str, Any],
                   stage: Optional[str] = None) -> str:
    """Append one banked record to a trend history file; returns the
    written line. Provenance rides automatically when the process set
    one (``sink.set_provenance``)."""
    line = json_record(kind="trend_point", stage=stage, record=dict(record))
    import os

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(line + "\n")
    return line


def load_history(path: str, stage: Optional[str] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The banked records (oldest first) from a history file, optionally
    filtered by stage and truncated to the newest ``limit``."""
    pts = [r["record"] for r in read_jsonl(path)
           if r.get("kind") == "trend_point"
           and isinstance(r.get("record"), dict)
           and (stage is None or r.get("stage") == stage)]
    return pts[-limit:] if limit else pts


def theil_sen_slope(ys: List[float]) -> float:
    """Median of all pairwise slopes (per record-index step) — the
    robust trend estimator: up to ~29% outlier records can't move it."""
    n = len(ys)
    if n < 2:
        return 0.0
    slopes = [(ys[j] - ys[i]) / (j - i)
              for i in range(n) for j in range(i + 1, n)]
    return statistics.median(slopes)


def _mad_scale(xs: List[float], rel_floor: float) -> float:
    m = statistics.median(xs)
    mad = statistics.median([abs(x - m) for x in xs])
    return max(1.4826 * mad, rel_floor * abs(m), 1e-12)


def detect_trends(history: Iterable[Mapping[str, Any]], *,
                  window: int = DEFAULT_WINDOW,
                  threshold: float = DEFAULT_THRESHOLD,
                  min_records: int = DEFAULT_MIN_RECORDS,
                  rel_floor: float = DEFAULT_REL_FLOOR,
                  rules: Optional[Mapping[str, str]] = None
                  ) -> Dict[str, Any]:
    """Drift report over a record series (oldest first). Returns
    ``{ok, n_records, checked, drifts: [...], drift_score, ...}``."""
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    recs = [flatten_record(r) for r in history]
    n = len(recs)
    report: Dict[str, Any] = {"ok": True, "n_records": n, "checked": 0,
                              "window": window, "threshold": threshold,
                              "min_records": min_records,
                              "drifts": [], "drift_score": 0.0}
    if n < min_records or n < window + 3:
        return report  # not armed yet — never block on a thin history
    keys = sorted(set(recs[-1]) if recs else ())
    score = 0.0
    for key in keys:
        direction = classify_metric(key, rules)
        if direction is None:
            continue
        xs = [r[key] for r in recs if key in r]
        if len(xs) < min_records or len(xs) < window + 3:
            continue
        report["checked"] += 1
        base, recent = xs[:-window], xs[-window:]
        scale = _mad_scale(base, rel_floor)
        m, r = statistics.median(base), statistics.median(recent)
        z = (r - m) / scale
        bad_z = z > 0 if direction == "lower" else -z > 0
        slope = theil_sen_slope(xs)
        projected = slope * (len(xs) - 1)
        bad_slope = projected > 0 if direction == "lower" else projected < 0
        kind = None
        if bad_z and abs(z) > threshold:
            kind = "step"
        elif bad_slope and abs(projected) > threshold * scale:
            kind = "slope"
        if kind is None:
            continue
        report["drifts"].append({
            "key": key, "direction": direction, "kind": kind,
            "baseline_median": round(m, 6), "recent_median": round(r, 6),
            "z": round(z, 3), "slope_per_record": round(slope, 6),
            "projected_move": round(projected, 6),
        })
        score = max(score, abs(z) / threshold,
                    abs(projected) / (threshold * scale))
    report["ok"] = not report["drifts"]
    report["drift_score"] = round(score if report["drifts"] else 0.0, 4)
    return report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="longitudinal trend gate over banked bench records")
    sub = ap.add_subparsers(dest="cmd", required=True)

    ap_a = sub.add_parser("append", help="bank one record into a history")
    ap_a.add_argument("history")
    ap_a.add_argument("record", help="record file (json / jsonl / wrapper)")
    ap_a.add_argument("--stage", default=None)

    ap_c = sub.add_parser("check", help="drift-gate a history (exit 1)")
    ap_c.add_argument("history")
    ap_c.add_argument("--stage", default=None)
    ap_c.add_argument("--window", type=int, default=DEFAULT_WINDOW)
    ap_c.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    ap_c.add_argument("--min-records", type=int,
                      default=DEFAULT_MIN_RECORDS)
    ap_c.add_argument("--limit", type=int, default=64,
                      help="newest records considered (default 64)")
    args = ap.parse_args(argv)

    if args.cmd == "append":
        from apex_tpu.monitor import sink as _sink

        # stamp provenance for THIS append only — an in-process caller
        # (tests, a watcher embedding main()) must not find the module
        # global mutated after we return
        prior = _sink._PROVENANCE
        if prior is None:
            _sink.set_provenance(_sink.collect_provenance())
        try:
            rec = load_record(args.record)
            append_history(args.history, rec, stage=args.stage)
            n = len(load_history(args.history, stage=args.stage))
            print(json_record(metric="trend_append", history=args.history,
                              stage=args.stage, n_records=n), flush=True)
        finally:
            _sink.set_provenance(prior)
        return 0

    history = load_history(args.history, stage=args.stage,
                           limit=args.limit)
    report = detect_trends(history, window=args.window,
                           threshold=args.threshold,
                           min_records=args.min_records)
    print(f"trend: {report['n_records']} records, "
          f"{report['checked']} metrics checked "
          f"(window {args.window}, z > {args.threshold:g}): "
          f"{len(report['drifts'])} drifts", file=sys.stderr)
    for d in report["drifts"]:
        print(f"  DRIFT[{d['kind']}] {d['key']}: "
              f"{d['baseline_median']:g} -> {d['recent_median']:g} "
              f"(z={d['z']:g}, slope={d['slope_per_record']:g}/rec, "
              f"{d['direction']}-better)", file=sys.stderr)
    print(json_record(metric="trend_report", history=args.history,
                      stage=args.stage, **report), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Declarative alert rules over scraped fleet series — firings as events.

Monitor tier 3's decision layer. Before this module, the autoscaler and
chaos recovery paths peeked gauges ad hoc (``queue_depth >= N and
occupancy >= x`` inline in the cluster tick); a scaling or recovery
decision left no artifact saying WHY it fired. Here the conditions are
**data** — declarative rules evaluated over the
:class:`~apex_tpu.monitor.registry.FleetView` the
:class:`~apex_tpu.monitor.registry.FleetScraper` produces — and every
transition is a first-class event (``alert_fire`` / ``alert_resolve``)
on the cluster's one shared clock, in the same JSONL stream and Chrome
trace as the request lifecycles it explains.

Rule shapes (all deterministic, all clock-free — they count consecutive
EVALUATIONS, which the cluster runs once per scrape tick):

* :class:`AlertRule` — a conjunction of :class:`Condition` thresholds
  (``backlog_tokens > X`` AND ``occupancy >= y``) that must hold for
  ``for_ticks`` consecutive evaluations before firing (the Prometheus
  ``for:`` clause). Each condition aggregates its matching series
  (``sum``/``max``/``min``/``avg``) so one rule reads per-worker,
  per-tenant or rolled-up values.
* :class:`AbsenceRule` — fires when a series (optionally
  label-filtered) is MISSING from the view for ``for_ticks``
  evaluations — the "heartbeat absent" / "worker stopped exporting"
  shape. A scrape miss IS the signal.
* :class:`RateRule` — fires when a series has RISEN by more than
  ``min_increase`` over the last ``window_ticks`` evaluations
  (``shed_rate rising``) — trend detection over the scrape history,
  O(window) state.

:class:`AlertEngine` evaluates the rule set, maintains firing state
(fire once on the False→True transition, resolve on True→False),
emits the events, and keeps the ledger (``alerts_fired_total``,
``active()``, ``summary()``). Detectors that cannot be expressed as a
scrape-series rule (the membership heartbeat check with its slow-tick
beat floor) route their verdicts through :meth:`AlertEngine.fire` so
the ledger, the events and the consumers see ONE alert plane either
way — the cluster's autoscaler and migration paths act on firings, not
on gauges.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from apex_tpu.monitor.registry import FleetView

__all__ = ["AbsenceRule", "AlertEngine", "AlertFiring", "AlertRule",
           "Condition", "RateRule"]

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}
_AGGS = ("sum", "max", "min", "avg")


@dataclasses.dataclass(frozen=True)
class Condition:
    """One threshold over a (possibly label-filtered) series set:
    ``agg(series(name) where labels ⊆ series.labels) op value``.
    Missing series never satisfy a condition (use :class:`AbsenceRule`
    to alert on absence)."""

    series: str
    op: str
    value: float
    agg: str = "sum"
    labels: Optional[Mapping[str, str]] = None

    def validate(self) -> None:
        if self.op not in _OPS:
            raise ValueError(f"op must be one of {tuple(_OPS)}, "
                             f"got {self.op!r}")
        if self.agg not in _AGGS:
            raise ValueError(f"agg must be one of {_AGGS}, "
                             f"got {self.agg!r}")

    def evaluate(self, view: FleetView) -> Optional[float]:
        """The aggregated value (None when no series matches)."""
        vals = []
        want = dict(self.labels or {})
        for labels, v in view.series(self.series):
            if all(labels.get(k) == str(v2) for k, v2 in want.items()):
                vals.append(v)
        if not vals:
            return None
        if self.agg == "sum":
            return float(sum(vals))
        if self.agg == "max":
            return float(max(vals))
        if self.agg == "min":
            return float(min(vals))
        return float(sum(vals) / len(vals))

    def holds(self, view: FleetView) -> bool:
        v = self.evaluate(view)
        return v is not None and _OPS[self.op](v, self.value)


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """Threshold rule: every condition must hold for ``for_ticks``
    consecutive evaluations. ``severity="page"`` firings additionally
    trigger the flight-recorder escalation dump in the cluster."""

    name: str
    conditions: Sequence[Condition] = ()
    for_ticks: int = 1
    severity: str = "warn"          # "warn" | "page"

    def validate(self) -> None:
        if not self.conditions:
            raise ValueError(f"{self.name}: needs at least one condition")
        if self.for_ticks < 1:
            raise ValueError(f"{self.name}: for_ticks must be >= 1")
        if self.severity not in ("warn", "page"):
            raise ValueError(f"{self.name}: severity must be 'warn' or "
                             f"'page', got {self.severity!r}")
        for c in self.conditions:
            c.validate()

    def holds(self, view: FleetView) -> bool:
        return all(c.holds(view) for c in self.conditions)

    def context(self, view: FleetView) -> Dict[str, Any]:
        return {c.series: c.evaluate(view) for c in self.conditions}


@dataclasses.dataclass(frozen=True)
class AbsenceRule:
    """Fires when ``series`` (with ``labels``, when given) is absent
    from the view for ``for_ticks`` consecutive evaluations."""

    name: str
    series: str
    labels: Optional[Mapping[str, str]] = None
    for_ticks: int = 1
    severity: str = "warn"

    def validate(self) -> None:
        if self.for_ticks < 1:
            raise ValueError(f"{self.name}: for_ticks must be >= 1")
        if self.severity not in ("warn", "page"):
            raise ValueError(f"{self.name}: severity must be 'warn' or "
                             f"'page', got {self.severity!r}")

    def holds(self, view: FleetView) -> bool:
        want = dict(self.labels or {})
        for labels, _ in view.series(self.series):
            if all(labels.get(k) == str(v) for k, v in want.items()):
                return False
        return True

    def context(self, view: FleetView) -> Dict[str, Any]:
        return {"absent": self.series,
                **({"labels": dict(self.labels)} if self.labels else {})}


@dataclasses.dataclass(frozen=True)
class RateRule:
    """Fires when the aggregated series rose by more than
    ``min_increase`` between the evaluation ``window_ticks`` ago and
    now (strictly rising trend — the "shed_rate rising" shape)."""

    name: str
    series: str
    min_increase: float = 0.0
    window_ticks: int = 3
    agg: str = "sum"
    severity: str = "warn"

    def validate(self) -> None:
        if self.window_ticks < 1:
            raise ValueError(f"{self.name}: window_ticks must be >= 1")
        if self.severity not in ("warn", "page"):
            raise ValueError(f"{self.name}: severity must be 'warn' or "
                             f"'page', got {self.severity!r}")
        if self.agg not in _AGGS:
            raise ValueError(f"{self.name}: agg must be one of {_AGGS}")


@dataclasses.dataclass
class AlertFiring:
    """One fire transition (the ledger entry and the event payload)."""

    rule: str
    severity: str
    t_ms: float
    context: Dict[str, Any]


class AlertEngine:
    """Evaluates a rule set per scrape tick; fires on transitions.

    ``events``: an :class:`~apex_tpu.monitor.events.EventLog` receiving
    ``alert_fire``/``alert_resolve`` (the JSONL/trace artifact);
    ``on_fire``: callable per firing (the cluster's escalation hook)."""

    def __init__(self, rules: Sequence[Any] = (), events: Any = None,
                 on_fire: Optional[Callable[[AlertFiring], Any]] = None):
        names = set()
        for r in rules:
            if not isinstance(r, (AlertRule, AbsenceRule, RateRule)):
                raise TypeError(f"not an alert rule: {r!r}")
            r.validate()
            if r.name in names:
                raise ValueError(f"duplicate rule name {r.name!r}")
            names.add(r.name)
        self.rules = list(rules)
        self._events = events
        self._on_fire = on_fire
        self._true_ticks: Dict[str, int] = {r.name: 0 for r in self.rules}
        self._active: Dict[str, AlertFiring] = {}
        # RateRule history: per-rule deque of the last window+1 values
        self._history: Dict[str, collections.deque] = {
            r.name: collections.deque(maxlen=r.window_ticks + 1)
            for r in self.rules if isinstance(r, RateRule)}
        self.alerts_fired_total = 0
        self.alerts_resolved_total = 0
        self.firings: List[AlertFiring] = []

    # -- evaluation --------------------------------------------------------
    def _rule_state(self, rule: Any, view: FleetView) -> bool:
        if isinstance(rule, RateRule):
            cond = Condition(series=rule.series, op=">",
                             value=float("-inf"), agg=rule.agg)
            v = cond.evaluate(view)
            hist = self._history[rule.name]
            if v is not None:
                hist.append(v)
            if v is None or len(hist) <= rule.window_ticks:
                return False
            return (hist[-1] - hist[0]) > rule.min_increase
        return rule.holds(view)

    def evaluate(self, view: FleetView,
                 t_ms: float = 0.0) -> List[AlertFiring]:
        """One evaluation pass; returns the NEW firings (transitions to
        active this pass). Resolve transitions emit events but are not
        returned — consumers act on fires."""
        fired: List[AlertFiring] = []
        for rule in self.rules:
            holds = self._rule_state(rule, view)
            n = self._true_ticks[rule.name] + 1 if holds else 0
            self._true_ticks[rule.name] = n
            need = getattr(rule, "for_ticks", 1)
            if holds and n >= need and rule.name not in self._active:
                ctx = (rule.context(view)
                       if hasattr(rule, "context") else
                       {rule.series: self._history[rule.name][-1]})
                fired.append(self._fire(rule.name, rule.severity, t_ms,
                                        ctx))
            elif not holds and rule.name in self._active:
                del self._active[rule.name]
                self.alerts_resolved_total += 1
                if self._events is not None:
                    self._events.emit("alert_resolve", t_ms=t_ms,
                                      rule=rule.name)
        return fired

    def fire(self, name: str, t_ms: float, severity: str = "warn",
             **context: Any) -> AlertFiring:
        """External-detector entry point: a verdict reached OUTSIDE the
        scrape loop (the membership heartbeat check, a watchdog) lands
        in the same ledger, events and hooks as an evaluated rule. The
        firing is one-shot (no active state to resolve — the external
        detector owns its lifecycle)."""
        return self._fire(name, severity, t_ms, dict(context),
                          track_active=False)

    def _fire(self, name: str, severity: str, t_ms: float,
              context: Dict[str, Any],
              track_active: bool = True) -> AlertFiring:
        firing = AlertFiring(rule=name, severity=severity, t_ms=t_ms,
                             context=context)
        if track_active:
            self._active[name] = firing
        self.alerts_fired_total += 1
        self.firings.append(firing)
        if self._events is not None:
            self._events.emit("alert_fire", t_ms=t_ms, rule=name,
                              severity=severity,
                              **{f"ctx_{k}": v for k, v in context.items()
                                 if isinstance(v, (int, float, str,
                                                   type(None)))})
        if self._on_fire is not None:
            self._on_fire(firing)
        return firing

    # -- readout -----------------------------------------------------------
    def active(self, name: Optional[str] = None) -> Any:
        """Active alert names (or whether ``name`` is active)."""
        if name is not None:
            return name in self._active
        return sorted(self._active)

    def stats(self) -> Dict[str, Any]:
        return {
            "rules": len(self.rules),
            "alerts_fired_total": self.alerts_fired_total,
            "alerts_resolved_total": self.alerts_resolved_total,
            "active": self.active(),
        }

    def summary(self) -> List[Dict[str, Any]]:
        """JSON-ready firing ledger (for bench records)."""
        return [{"rule": f.rule, "severity": f.severity,
                 "t_ms": round(f.t_ms, 3), "context": f.context}
                for f in self.firings]

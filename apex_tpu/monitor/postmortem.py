"""``python -m apex_tpu.monitor.postmortem DIR`` — rebuild the crash
timeline from flight-recorder dumps alone.

The read side of :mod:`~apex_tpu.monitor.flight`: after a chaos kill, a
watchdog fire or an alert escalation, each worker's bounded ring was
dumped atomically into a directory. This CLI merges every surviving
dump into ONE causally-ordered timeline — the per-worker rings share
the cluster's one monotonic clock, so sorting by ``t_ms`` IS the fleet
timeline — and answers the postmortem questions without any other
artifact:

* what happened in the last N seconds before each dump (``--last-s``,
  default: everything the rings held);
* which requests were in flight, per TRACE id (the merged streams are
  deduplicated and reconstructed per trace — a migrated request whose
  events span two workers' dumps reads as one request, not two);
* which alerts fired, which workers died, what each worker's final
  records were.

Human table to **stderr**, one machine-readable ``json_record`` line to
**stdout** (the repo's bench pipe convention); ``--trace FILE`` also
writes the merged Chrome trace for Perfetto.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

__all__ = ["main", "merge_dumps", "rebuild"]


def merge_dumps(dumps: List[Dict[str, Any]],
                last_s: Optional[float] = None) -> List[Dict[str, Any]]:
    """One deduplicated, time-ordered record stream from many dumps.

    Records are tagged ``_worker`` (which ring held them) before the
    merge; duplicates — the same event captured by two rings — collapse
    via the shared-clock identity ``(uid, event, t_ms, start_ms)`` for
    uid events and ``(kind/event/gauge, t_ms, worker fields)`` for the
    rest. ``last_s`` keeps only records within that many seconds of the
    newest record across ALL dumps (the "last N seconds" window)."""
    from apex_tpu.monitor.events import _dedupe_events

    records: List[Dict[str, Any]] = []
    for d in dumps:
        for r in d.get("records", []):
            rec = dict(r)
            rec.setdefault("_worker", d.get("worker"))
            records.append(rec)
    # non-uid records dedupe on their full identity minus the ring tag
    seen = set()
    uniq: List[Dict[str, Any]] = []
    for r in records:
        if r.get("kind") == "event" and "uid" in r:
            uniq.append(r)   # _dedupe_events handles these below
            continue
        key = tuple(sorted((k, repr(v)) for k, v in r.items()
                           if k != "_worker"))
        if key in seen:
            continue
        seen.add(key)
        uniq.append(r)
    records = _dedupe_events(uniq)
    records.sort(key=lambda r: (float(r.get("t_ms", r.get("ts", 0.0))
                                      or 0.0)))
    if last_s is not None and records:
        stamps = [float(r["t_ms"]) for r in records
                  if r.get("t_ms") is not None]
        if stamps:
            cutoff = max(stamps) - last_s * 1e3
            # explicit None check: t_ms == 0.0 is a REAL stamp (the log
            # epoch) and must be windowed out like any other old
            # record; only records with no clock stamp at all are kept
            records = [r for r in records
                       if r.get("t_ms") is None
                       or float(r["t_ms"]) >= cutoff]
    return records


def rebuild(dumps: List[Dict[str, Any]],
            last_s: Optional[float] = None,
            records: Optional[List[Dict[str, Any]]] = None
            ) -> Dict[str, Any]:
    """The merged postmortem record: window, per-worker dump accounting,
    per-trace request reconstruction (the ``view`` derivation over the
    merged stream), alert firings and deaths inside the window.
    ``records``: a pre-merged stream from :func:`merge_dumps` (same
    dumps, same window) so callers that also render the timeline run
    the merge once."""
    from apex_tpu.monitor.events import stitch_traces
    from apex_tpu.monitor.view import summarize

    if records is None:
        records = merge_dumps(dumps, last_s=last_s)
    events = [r for r in records if r.get("kind") == "event"]
    summary = summarize(records)
    stitch = stitch_traces(records)
    alerts = [r for r in events if r["event"] == "alert_fire"]
    deaths = [r for r in events if r["event"] == "worker_leave"]
    ts = [float(r["t_ms"]) for r in records if "t_ms" in r]
    out: Dict[str, Any] = {
        "n_dumps": len(dumps),
        "workers": sorted({d.get("worker") for d in dumps}),
        "dump_reasons": sorted({d.get("reason") for d in dumps}),
        "dropped_records": sum(int(d.get("dropped_records", 0))
                               for d in dumps),
        "window_ms": (round(max(ts) - min(ts), 3) if ts else 0.0),
        "n_records": len(records),
        "n_traces": len(stitch["traces"]),
        "trace_stitch_failures": stitch["stitch_failures"],
        "alerts_fired": [{k: r.get(k) for k in ("rule", "severity",
                                                "t_ms")}
                         for r in alerts],
        "worker_leaves": [{k: r.get(k) for k in ("worker", "reason",
                                                 "t_ms")}
                          for r in deaths],
        **summary,
    }
    return out


def _timeline_lines(records: List[Dict[str, Any]],
                    limit: int = 80) -> List[str]:
    lines = []
    shown = records[-limit:]
    if len(records) > len(shown):
        lines.append(f"  ... {len(records) - len(shown)} earlier records")
    for r in shown:
        t = r.get("t_ms", r.get("ts", ""))
        w = r.get("host", r.get("worker", r.get("_worker", "")))
        if r.get("kind") == "event":
            what = r["event"]
            who = r.get("uid", r.get("rule", ""))
        elif r.get("kind") == "gauge":
            what = f"gauge {r['gauge']}={r.get('value')}"
            who = ""
        else:
            what = f"step {r.get('step', '?')} {r.get('phase', '')}"
            who = ""
        lines.append(f"  {t:>10} ms  {str(w):<10} {what:<16} {who}")
    return lines


def main(argv=None) -> int:
    import argparse

    from apex_tpu.monitor.events import write_chrome_trace
    from apex_tpu.monitor.flight import load_dumps
    from apex_tpu.monitor.sink import json_record

    ap = argparse.ArgumentParser(
        description="rebuild the merged pre-failure timeline from "
                    "flight-recorder dumps")
    ap.add_argument("directory", help="directory holding flight-*.json")
    ap.add_argument("--last-s", type=float, default=None,
                    help="keep only the last N seconds before the newest "
                         "record (default: everything the rings held)")
    ap.add_argument("--trace", default=None,
                    help="also write the merged Chrome trace here")
    ap.add_argument("--timeline", type=int, default=40,
                    help="timeline rows to print (0: none)")
    args = ap.parse_args(argv)
    dumps = load_dumps(args.directory)
    if not dumps:
        print(f"no flight dumps under {args.directory}", file=sys.stderr)
        return 1
    records = merge_dumps(dumps, last_s=args.last_s)
    rec = rebuild(dumps, last_s=args.last_s, records=records)
    print(f"{rec['n_dumps']} dumps from {rec['workers']} "
          f"({rec['dump_reasons']}), {rec['n_records']} records over "
          f"{rec['window_ms']} ms, {rec['n_traces']} traces "
          f"({rec['trace_stitch_failures']} stitch failures)",
          file=sys.stderr)
    for a in rec["alerts_fired"]:
        print(f"  ALERT {a['rule']} ({a['severity']}) @ {a['t_ms']} ms",
              file=sys.stderr)
    for d in rec["worker_leaves"]:
        print(f"  LEAVE {d['worker']} ({d['reason']}) @ {d['t_ms']} ms",
              file=sys.stderr)
    if args.timeline:
        for line in _timeline_lines(records, args.timeline):
            print(line, file=sys.stderr)
    if args.trace:
        write_chrome_trace(args.trace, records)
        print(f"chrome trace -> {args.trace}", file=sys.stderr)
    print(json_record(metric="postmortem", directory=args.directory,
                      **rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

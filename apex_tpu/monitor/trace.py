"""Named-span tracing — phases visible in the trace viewer AND the HLO.

Reference: ``apex.pyprof.nvtx`` ranges / ad-hoc ``torch.cuda.nvtx`` in hot
paths — host-side markers a profiler joins with kernel launches.

TPU design: one :func:`span` plants BOTH kinds of marker at once:

* ``jax.named_scope`` — attaches the name to every op traced inside, so it
  rides the compiled HLO's op metadata and shows up as the layer path in
  ``apex_tpu.pyprof.op_table`` / ``measured_op_table`` (and the XLA trace
  viewer's per-op details). This is the marker that survives jit.
* ``jax.profiler.TraceAnnotation`` — a host-side range for eager/dispatch
  work, so un-jitted phases (data loading, checkpoint writes) show in the
  trace viewer's host rows too.

Canonical phase names are :data:`PHASES`
(``fwd``/``bwd``/``comm``/``opt``/``ckpt`` — the last is the host-side
checkpoint phase the resilience layer traces under) — using them makes
``monitor.report.phase_breakdown`` attribute step time per phase with no
configuration — but any string works.

:func:`step_annotation` wraps ``jax.profiler.StepTraceAnnotation`` so the
trace viewer groups device activity by train step (the MLPerf-style
step-time lane); use it host-side around each step call.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Iterator, Optional

import jax

# canonical train-step phases; monitor.report.phase_breakdown groups by the
# leading scope component, so spans named from this set roll up cleanly.
# "ckpt" is the host-side checkpoint phase (resilience.CheckpointManager's
# device_get + serialization) — it appears in trace-viewer host rows, not
# in the compiled step. "prefill"/"decode" are the serving phases the
# apex_tpu.serve engine traces its two jitted programs under; "transfer"
# is the disaggregated cluster's KV-block handoff between hosts
# (serve.cluster — pack/ship/unpack around the SimTransport or ICI hop).
# "scrape" is the fleet-observability tier's host-side phase: the
# FleetScraper pulling worker snapshots on the cluster clock (its cost
# is itself measured — scrape_ms — and gated by bench_observe.py).
PHASES = ("fwd", "bwd", "comm", "opt", "ckpt", "prefill", "decode",
          "transfer", "scrape")


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Named range: in-graph (``named_scope`` → HLO op metadata → pyprof
    layer paths) and host-side (``TraceAnnotation`` → trace-viewer host
    row). Nesting composes into ``outer/inner`` scope paths."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def span_function(fn: Callable = None, *, name: Optional[str] = None):
    """Decorator form of :func:`span` (ref ``nvtx/nvmarker.py`` function
    wrapping): the function body traces under ``name`` (default: its
    qualname)."""
    if fn is None:
        return functools.partial(span_function, name=name)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with span(name or fn.__qualname__):
            return fn(*args, **kwargs)

    return wrapped


def step_annotation(step: int, name: str = "train_step"):
    """Host-side step marker (``jax.profiler.StepTraceAnnotation``): device
    activity dispatched inside is grouped under step ``step`` in the trace
    viewer. Use around the step CALL (not inside the jitted body)::

        with monitor.step_annotation(i):
            state = train_step(state, batch)
    """
    return jax.profiler.StepTraceAnnotation(name, step_num=step)

"""Per-request lifecycle events on one monotonic clock — JSONL + Perfetto.

The tier-2 attribution question ("which phase of which request blew the
TTFT budget?") needs *events*, not step aggregates. This module is the
event half of the serve telemetry:

* :class:`EventLog` — stamps every event from ONE anchored monotonic clock
  (``time.perf_counter`` relative to the log's creation, in ms — wall
  clocks step; a latency pipeline must never subtract two of them) and
  streams each record through the existing
  :class:`~apex_tpu.monitor.sink.JsonlSink` (``kind: "event"`` /
  ``"gauge"`` records alongside the engine's step records). Memory is
  O(1) unless ``keep=True`` opts into in-process retention (tests, short
  runs); long runs read events back with ``read_jsonl``.
* the canonical request lifecycle is :data:`LIFECYCLE`:
  ``submitted → admitted → prefill_start → prefill_end → first_token →
  decode_chunk* → retired``, plus ``queue_depth`` / ``occupancy`` gauges.
* :func:`chrome_trace` — the same event records rendered as Chrome
  trace-event JSON (viewable in Perfetto / ``chrome://tracing``): one
  track per decode **slot** (what the hardware grid was doing) and one per
  **request** (where an individual request's time went: ``queued`` /
  ``prefill`` / ``decode`` spans + per-chunk slices), with gauges as
  counter tracks. :func:`write_chrome_trace` dumps it to a file.

The span set in the exported trace is a pure function of the event log —
``tests/test_serve.py`` pins that the trace matches the JSONL
request-for-request, so either artifact can be trusted alone.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "EventLog",
    "GAUGES",
    "LIFECYCLE",
    "SPAN_PAIRS",
    "chrome_trace",
    "dedupe_events",
    "request_spans",
    "stitch_traces",
    "write_chrome_trace",
]

# canonical request lifecycle, in order; decode_chunk repeats. The
# disaggregated-cluster path (serve.cluster) inserts a transfer span
# between prefill and decode — ``prefill_end → transfer_start →
# transfer_end → admitted`` — and ``shed`` is the router's terminal
# state for a request that was never admitted (load shedding: recorded,
# never an exception). The elastic tier adds migration: when a decode
# worker dies or drains, an in-flight request's blocks hop hosts
# (``migrate_start → migrate_end``) and its last unacked token is
# re-emitted (``replay``); ``worker_join`` / ``worker_leave`` are the
# membership events (no uid — they describe a host, not a request).
LIFECYCLE = ("submitted", "admitted", "prefill_start", "prefill_end",
             "first_token", "transfer_start", "transfer_end",
             "decode_chunk", "migrate_start", "migrate_end", "replay",
             "retired", "shed", "worker_join", "worker_leave",
             # the fleet-observability (tier 3) events: alert-engine
             # transitions (``rule=``/``severity=``, no uid — they
             # describe the fleet) and flight-recorder dumps
             # (``worker=``/``reason=``/``path=``)
             "alert_fire", "alert_resolve", "flight_dump")
GAUGES = ("queue_depth", "occupancy")


class EventLog:
    """Monotonic-clock event recorder. ``sink`` is a
    :class:`~apex_tpu.monitor.sink.JsonlSink` (or anything with a
    ``write(**fields)`` method); ``keep=True`` additionally retains records
    in ``self.records`` (unbounded — opt-in only)."""

    def __init__(self, sink=None, keep: bool = False,
                 clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._sink = sink
        self.records: Optional[List[Dict[str, Any]]] = [] if keep else None
        # per-uid default fields (trace id, tenant, current host) applied
        # to every emit for that uid — how the cluster threads ONE trace
        # id through producers (engine, workers, router) that never see
        # it; explicit emit fields always win
        self._bound: Dict[str, Dict[str, Any]] = {}
        # side observers of every record (the flight-recorder rings);
        # taps see the same dicts the sink does, in emit order
        self._taps: List[Callable[[Dict[str, Any]], None]] = []

    def now_ms(self) -> float:
        """Milliseconds since log creation, from the one monotonic clock
        every event in this log is stamped with."""
        return (self._clock() - self._t0) * 1e3

    # -- per-uid bound fields (distributed tracing) ------------------------
    def bind(self, uid: str, **fields: Any) -> None:
        """Attach default fields to every future event carrying ``uid``
        (``trace=`` minted at router submission, ``tenant=``, and the
        uid's CURRENT ``host=`` — rebound on migration). Explicit emit
        fields override; :meth:`unbind` at the terminal event keeps the
        table O(in-flight requests)."""
        self._bound.setdefault(uid, {}).update(fields)

    def unbind(self, uid: str) -> None:
        self._bound.pop(uid, None)

    def bound(self, uid: str) -> Dict[str, Any]:
        return dict(self._bound.get(uid, {}))

    def tap(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Register a record observer (flight rings, routers)."""
        self._taps.append(fn)

    def _write(self, rec: Dict[str, Any]) -> None:
        if self._sink is not None:
            self._sink.write(**rec)
        if self.records is not None:
            self.records.append(rec)
        for tap in self._taps:
            tap(rec)

    def emit(self, event: str, uid: Optional[str] = None,
             t_ms: Optional[float] = None, **fields: Any) -> float:
        """Record one lifecycle event; returns its timestamp (ms). Extra
        ``fields`` ride the record (``slot=``, ``n_tokens=``,
        ``start_ms=`` for span-shaped events)."""
        t = self.now_ms() if t_ms is None else float(t_ms)
        rec: Dict[str, Any] = {"kind": "event", "event": event,
                               "t_ms": round(t, 3)}
        if uid is not None:
            rec["uid"] = uid
        rec.update(fields)
        if uid is not None and uid in self._bound:
            for k, v in self._bound[uid].items():
                rec.setdefault(k, v)
        self._write(rec)
        return t

    def gauge(self, name: str, value: float,
              t_ms: Optional[float] = None) -> float:
        """Record one gauge sample (queue depth, occupancy, ...)."""
        t = self.now_ms() if t_ms is None else float(t_ms)
        self._write({"kind": "gauge", "gauge": name, "t_ms": round(t, 3),
                     "value": float(value)})
        return t


# ---------------------------------------------------------------------------
# Chrome trace-event rendering (Perfetto / chrome://tracing)

_PID_REQUESTS = 1
_PID_SLOTS = 2
_PID_HOSTS = 3   # host tracks (fleet tier) start here, one pid per host

# request-track spans derived from lifecycle event pairs: name -> (start
# event, end event). decode_chunk spans carry their own start_ms instead.
# transfer renders the cluster's KV-block hop between hosts — in Perfetto
# a disaggregated request visibly leaves its prefill host and lands on
# its decode host.
_SPAN_PAIRS = {
    "queued": ("submitted", "admitted"),
    "prefill": ("prefill_start", "prefill_end"),
    "transfer": ("transfer_start", "transfer_end"),
    "migrate": ("migrate_start", "migrate_end"),
    "decode": ("first_token", "retired"),
}


def _meta(pid: int, tid: int, name: str, kind: str) -> Dict[str, Any]:
    return {"ph": "M", "pid": pid, "tid": tid, "name": kind,
            "args": {"name": name}}


def _span(name: str, pid: int, tid: int, t0_ms: float, t1_ms: float,
          args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return {"ph": "X", "name": name, "pid": pid, "tid": tid,
            "ts": round(t0_ms * 1e3, 1),          # trace ts is µs
            "dur": round(max(0.0, t1_ms - t0_ms) * 1e3, 1),
            "cat": "serve", "args": args or {}}


def _dedupe_events(records: Iterable[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """Drop exact duplicates of uid-carrying events — the merged-logs
    artifact. Two workers' flight rings (or a worker log plus the
    cluster log) both hold the shared records of a request that hopped
    hosts; naively concatenating them replays the same ``decode_chunk``
    or ``admitted`` twice. Identity = (uid, event, t_ms, start_ms) on
    the one shared clock — distinct real events can never collide."""
    out: List[Dict[str, Any]] = []
    seen = set()
    for r in records:
        if "flight_worker" in r:
            # an in-log flight dump's record is a marked COPY of a live
            # record in the same stream — readers must never count both
            continue
        if r.get("kind") != "event":
            out.append(r)
            continue
        uid = r.get("uid")
        if uid is None:
            out.append(r)
            continue
        key = (uid, r["event"], r.get("t_ms"), r.get("start_ms"))
        if key in seen:
            continue
        seen.add(key)
        out.append(r)
    return out


# the public names tier-4 consumers (monitor.attrib, external tooling)
# build on: the span-pair table and the merged-log dedupe pass share one
# definition with the Chrome-trace renderer above
SPAN_PAIRS = _SPAN_PAIRS
dedupe_events = _dedupe_events


def request_spans(records: Iterable[Dict[str, Any]], *,
                  deduped: bool = False
                  ) -> Dict[str, List[Dict[str, Any]]]:
    """Per-request span list derived from an event log: the lifecycle
    pairs of :data:`_SPAN_PAIRS` plus one span per ``decode_chunk``
    event. This is the SAME derivation :func:`chrome_trace` renders,
    exposed so tests can pin trace == JSONL request-for-request.

    Reconstruction is per TRACE, not per (uid, log): records merged from
    several workers' logs are deduplicated first (a migrated request's
    events live in two logs that may share the cluster-global records),
    span pairs anchor on the FIRST occurrence of each side (the second
    ``admitted`` a migration emits never moves the queued span), and
    each ``decode_chunk`` renders exactly once however many dumps held
    it. Keys stay the request uid — uid and trace id are 1:1; the trace
    id rides the span records when present."""
    by_uid: Dict[str, Dict[str, float]] = {}
    spans: Dict[str, List[Dict[str, Any]]] = {}
    traces: Dict[str, str] = {}
    for r in (records if deduped else _dedupe_events(records)):
        if r.get("kind") != "event" or "uid" not in r:
            continue
        uid, ev, t = r["uid"], r["event"], float(r["t_ms"])
        if "trace" in r:
            traces.setdefault(uid, r["trace"])
        seen = by_uid.setdefault(uid, {})
        # the EARLIEST occurrence anchors (min by timestamp, not stream
        # position — merged logs derive the same spans in any order)
        seen[ev] = min(seen.get(ev, t), t)
        out = spans.setdefault(uid, [])
        if ev == "decode_chunk" and "start_ms" in r:
            chunk = {"name": "decode_chunk",
                     "t0_ms": float(r["start_ms"]), "t1_ms": t,
                     "n_tokens": r.get("n_tokens")}
            if "trace" in r:
                chunk["trace"] = r["trace"]
            out.append(chunk)
    for uid, seen in by_uid.items():
        out = spans.setdefault(uid, [])
        for name, (a, b) in _SPAN_PAIRS.items():
            if a in seen and b in seen:
                span = {"name": name, "t0_ms": seen[a], "t1_ms": seen[b]}
                if uid in traces:
                    span["trace"] = traces[uid]
                out.append(span)
    return spans


# cross-host span-pair kinds whose two sides may land in DIFFERENT
# workers' logs — the stitching targets. A trace that reached a terminal
# event but shows an unmatched side of one of these is a stitch failure.
_STITCH_PAIRS = ("transfer", "migrate")
_TERMINALS = ("retired", "shed")


def stitch_traces(records: Iterable[Dict[str, Any]], *,
                  deduped: bool = False) -> Dict[str, Any]:
    """Assemble per-TRACE cross-host structure from a (possibly merged)
    event stream: for every trace id (falling back to uid when no trace
    was minted), the per-host segments — [first event on that host, last
    event on that host] in first-touch order — and the causal verdict.

    ``stitch_failures`` counts traces that are structurally broken:

    * a terminal trace with a ``transfer_start``/``migrate_start`` whose
      matching end never appears anywhere in the stream (the two logs
      did not stitch), or
    * host segments that OVERLAP out of causal order on the shared
      clock (a request cannot be on two hosts at once — overlapping
      segments mean the logs disagree about the timeline).

    This is the acceptance currency of the chaos trace gate: a migrated
    request must reconstruct as ONE trace across ≥ 2 host segments with
    zero failures."""
    traces: Dict[str, Dict[str, Any]] = {}
    for r in (records if deduped else _dedupe_events(records)):
        if r.get("kind") != "event" or "uid" not in r:
            continue
        uid, ev, t = r["uid"], r["event"], float(r["t_ms"])
        key = r.get("trace", uid)
        tr = traces.setdefault(key, {
            "uid": uid, "trace": r.get("trace"),
            "segments": {}, "host_order": [],
            "pair_open": {k: 0 for k in _STITCH_PAIRS},
            "terminal": None, "events": 0})
        tr["events"] += 1
        host = r.get("host")
        if host is not None:
            seg = tr["segments"].get(host)
            if seg is None:
                tr["segments"][host] = [t, t]
                tr["host_order"].append(host)
            else:
                seg[0] = min(seg[0], t)
                seg[1] = max(seg[1], t)
        for kind in _STITCH_PAIRS:
            a, b = _SPAN_PAIRS[kind]
            if ev == a:
                # a transfer RETRY re-emits the start with attempt > 1;
                # only first attempts open a logical pair (retries share
                # the original's one end)
                if int(r.get("attempt", 1) or 1) <= 1:
                    tr["pair_open"][kind] += 1
            elif ev == b:
                tr["pair_open"][kind] -= 1
        if ev in _TERMINALS:
            tr["terminal"] = ev
    failures = 0
    out: Dict[str, Any] = {}
    for key, tr in traces.items():
        segs = [{"host": h, "t0_ms": tr["segments"][h][0],
                 "t1_ms": tr["segments"][h][1]}
                for h in tr["host_order"]]
        segs.sort(key=lambda s: (s["t0_ms"], s["t1_ms"]))
        ordered = all(segs[i + 1]["t0_ms"] >= segs[i]["t1_ms"] - 1e-6
                      for i in range(len(segs) - 1))
        unmatched = {k: n for k, n in tr["pair_open"].items() if n != 0}
        # a RETIRED trace must have every cross-host pair matched and
        # its segments causally ordered; a shed trace may legitimately
        # end mid-pair (transfer_failed died on the wire) but its
        # segments must still order
        failed = ((tr["terminal"] == "retired" and bool(unmatched))
                  or (tr["terminal"] is not None and not ordered))
        failures += failed
        out[key] = {"uid": tr["uid"], "trace": tr["trace"],
                    "hosts": [s["host"] for s in segs],
                    "segments": segs, "ordered": ordered,
                    "unmatched_pairs": unmatched,
                    "terminal": tr["terminal"], "failed": failed}
    return {"traces": out, "stitch_failures": failures}


def chrome_trace(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Render an event log (dicts from :class:`EventLog` / ``read_jsonl``)
    as a Chrome trace-event object: request tracks (one tid per uid, spans
    from :func:`request_spans`), slot tracks (one tid per slot, one span
    per residency ``admitted → retired`` named by the uid), gauge counter
    tracks.

    When events carry ``host=`` (the fleet/cluster path), one additional
    process appears PER HOST: each request renders one span per host it
    touched — named by its trace id, stamped with uid/trace args — so a
    request that hops hosts (disaggregated prefill→decode, chaos
    migration) is visibly ONE trace id across several host tracks, in
    causal order on the one shared clock. ``worker_join``/``worker_leave``
    and ``alert_fire`` render as instant markers. The stitch verdict
    (:func:`stitch_traces`) rides the returned object under ``"stitch"``
    (Perfetto ignores unknown top-level keys)."""
    records = list(records)
    events = [r for r in _dedupe_events(records)
              if r.get("kind") == "event"]
    gauges = [r for r in records if r.get("kind") == "gauge"
              and "flight_worker" not in r]

    trace: List[Dict[str, Any]] = [
        _meta(_PID_REQUESTS, 0, "requests", "process_name"),
        _meta(_PID_SLOTS, 0, "slots", "process_name"),
    ]

    # request tracks: stable tid per uid in first-seen order
    uid_tid: Dict[str, int] = {}
    for r in events:
        uid = r.get("uid")
        if uid is not None and uid not in uid_tid:
            uid_tid[uid] = len(uid_tid)
            trace.append(_meta(_PID_REQUESTS, uid_tid[uid], uid,
                               "thread_name"))
    for uid, spans in request_spans(events, deduped=True).items():
        for s in spans:
            args = {k: v for k, v in s.items()
                    if k not in ("name", "t0_ms", "t1_ms") and v is not None}
            trace.append(_span(s["name"], _PID_REQUESTS, uid_tid[uid],
                               s["t0_ms"], s["t1_ms"], args))

    # slot tracks: residency spans named by uid (admitted -> retired)
    admitted: Dict[str, Dict[str, Any]] = {}
    slot_tids = set()
    for r in events:
        uid = r.get("uid")
        if r["event"] == "admitted" and "slot" in r:
            admitted[uid] = r
        elif r["event"] == "retired" and uid in admitted:
            a = admitted.pop(uid)
            slot = int(a["slot"])
            slot_tids.add(slot)
            trace.append(_span(uid, _PID_SLOTS, slot, float(a["t_ms"]),
                               float(r["t_ms"])))
    for slot in sorted(slot_tids):
        trace.append(_meta(_PID_SLOTS, slot, f"slot {slot}", "thread_name"))

    # gauges as counter tracks
    for g in gauges:
        trace.append({"ph": "C", "name": g["gauge"], "pid": _PID_REQUESTS,
                      "tid": 0, "ts": round(float(g["t_ms"]) * 1e3, 1),
                      "args": {g["gauge"]: g["value"]}})

    # host tracks (fleet tier): one process per host named in the
    # stream, one span per (trace, host) segment — a migrated request is
    # ONE trace id across >= 2 host tracks, causally ordered
    stitch = stitch_traces(events, deduped=True)
    hosts: List[str] = []
    for r in events:
        # request events name their current host; membership events name
        # a REAL host via worker= — but other worker= carriers
        # (flight_dump's "cluster" ring, alert contexts) are not hosts
        # and must not mint phantom tracks
        h = r.get("host")
        if h is None and r["event"] in ("worker_join", "worker_leave"):
            h = r.get("worker")
        if h is not None and h not in hosts:
            hosts.append(h)
    if hosts:
        host_pid = {h: _PID_HOSTS + i for i, h in enumerate(sorted(hosts))}
        for h, pid in sorted(host_pid.items()):
            trace.append(_meta(pid, 0, f"host {h}", "process_name"))
        # stable per-host request lanes in first-seen order
        lanes: Dict[str, Dict[str, int]] = {h: {} for h in host_pid}
        for key, tr in stitch["traces"].items():
            for seg in tr["segments"]:
                lane = lanes[seg["host"]].setdefault(
                    key, len(lanes[seg["host"]]))
                trace.append(_span(
                    key, host_pid[seg["host"]], lane,
                    seg["t0_ms"], seg["t1_ms"],
                    {"uid": tr["uid"], "trace": tr["trace"]}))
        # membership churn + alert transitions as instant markers on the
        # host track (join/leave) or the fleet lane (alerts)
        for r in events:
            if r["event"] in ("worker_join", "worker_leave"):
                trace.append({
                    "ph": "i", "s": "p", "name": r["event"],
                    "pid": host_pid[r["worker"]], "tid": 0,
                    "ts": round(float(r["t_ms"]) * 1e3, 1),
                    "args": {k: v for k, v in r.items()
                             if k not in ("kind", "t_ms")}})
    for r in events:
        if r["event"] in ("alert_fire", "alert_resolve"):
            trace.append({
                "ph": "i", "s": "g", "name": f"{r['event']}:{r['rule']}",
                "pid": _PID_REQUESTS, "tid": 0,
                "ts": round(float(r["t_ms"]) * 1e3, 1),
                "args": {k: v for k, v in r.items()
                         if k not in ("kind", "t_ms")}})
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "stitch": {"stitch_failures": stitch["stitch_failures"]}}


def write_chrome_trace(path: str,
                       records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Dump :func:`chrome_trace` to ``path`` (open the file in Perfetto /
    ``chrome://tracing``); returns the trace object."""
    trace = chrome_trace(records)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace

"""Per-request lifecycle events on one monotonic clock — JSONL + Perfetto.

The tier-2 attribution question ("which phase of which request blew the
TTFT budget?") needs *events*, not step aggregates. This module is the
event half of the serve telemetry:

* :class:`EventLog` — stamps every event from ONE anchored monotonic clock
  (``time.perf_counter`` relative to the log's creation, in ms — wall
  clocks step; a latency pipeline must never subtract two of them) and
  streams each record through the existing
  :class:`~apex_tpu.monitor.sink.JsonlSink` (``kind: "event"`` /
  ``"gauge"`` records alongside the engine's step records). Memory is
  O(1) unless ``keep=True`` opts into in-process retention (tests, short
  runs); long runs read events back with ``read_jsonl``.
* the canonical request lifecycle is :data:`LIFECYCLE`:
  ``submitted → admitted → prefill_start → prefill_end → first_token →
  decode_chunk* → retired``, plus ``queue_depth`` / ``occupancy`` gauges.
* :func:`chrome_trace` — the same event records rendered as Chrome
  trace-event JSON (viewable in Perfetto / ``chrome://tracing``): one
  track per decode **slot** (what the hardware grid was doing) and one per
  **request** (where an individual request's time went: ``queued`` /
  ``prefill`` / ``decode`` spans + per-chunk slices), with gauges as
  counter tracks. :func:`write_chrome_trace` dumps it to a file.

The span set in the exported trace is a pure function of the event log —
``tests/test_serve.py`` pins that the trace matches the JSONL
request-for-request, so either artifact can be trusted alone.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

__all__ = [
    "EventLog",
    "GAUGES",
    "LIFECYCLE",
    "chrome_trace",
    "write_chrome_trace",
]

# canonical request lifecycle, in order; decode_chunk repeats. The
# disaggregated-cluster path (serve.cluster) inserts a transfer span
# between prefill and decode — ``prefill_end → transfer_start →
# transfer_end → admitted`` — and ``shed`` is the router's terminal
# state for a request that was never admitted (load shedding: recorded,
# never an exception). The elastic tier adds migration: when a decode
# worker dies or drains, an in-flight request's blocks hop hosts
# (``migrate_start → migrate_end``) and its last unacked token is
# re-emitted (``replay``); ``worker_join`` / ``worker_leave`` are the
# membership events (no uid — they describe a host, not a request).
LIFECYCLE = ("submitted", "admitted", "prefill_start", "prefill_end",
             "first_token", "transfer_start", "transfer_end",
             "decode_chunk", "migrate_start", "migrate_end", "replay",
             "retired", "shed", "worker_join", "worker_leave")
GAUGES = ("queue_depth", "occupancy")


class EventLog:
    """Monotonic-clock event recorder. ``sink`` is a
    :class:`~apex_tpu.monitor.sink.JsonlSink` (or anything with a
    ``write(**fields)`` method); ``keep=True`` additionally retains records
    in ``self.records`` (unbounded — opt-in only)."""

    def __init__(self, sink=None, keep: bool = False,
                 clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._sink = sink
        self.records: Optional[List[Dict[str, Any]]] = [] if keep else None

    def now_ms(self) -> float:
        """Milliseconds since log creation, from the one monotonic clock
        every event in this log is stamped with."""
        return (self._clock() - self._t0) * 1e3

    def _write(self, rec: Dict[str, Any]) -> None:
        if self._sink is not None:
            self._sink.write(**rec)
        if self.records is not None:
            self.records.append(rec)

    def emit(self, event: str, uid: Optional[str] = None,
             t_ms: Optional[float] = None, **fields: Any) -> float:
        """Record one lifecycle event; returns its timestamp (ms). Extra
        ``fields`` ride the record (``slot=``, ``n_tokens=``,
        ``start_ms=`` for span-shaped events)."""
        t = self.now_ms() if t_ms is None else float(t_ms)
        rec: Dict[str, Any] = {"kind": "event", "event": event,
                               "t_ms": round(t, 3)}
        if uid is not None:
            rec["uid"] = uid
        rec.update(fields)
        self._write(rec)
        return t

    def gauge(self, name: str, value: float,
              t_ms: Optional[float] = None) -> float:
        """Record one gauge sample (queue depth, occupancy, ...)."""
        t = self.now_ms() if t_ms is None else float(t_ms)
        self._write({"kind": "gauge", "gauge": name, "t_ms": round(t, 3),
                     "value": float(value)})
        return t


# ---------------------------------------------------------------------------
# Chrome trace-event rendering (Perfetto / chrome://tracing)

_PID_REQUESTS = 1
_PID_SLOTS = 2

# request-track spans derived from lifecycle event pairs: name -> (start
# event, end event). decode_chunk spans carry their own start_ms instead.
# transfer renders the cluster's KV-block hop between hosts — in Perfetto
# a disaggregated request visibly leaves its prefill host and lands on
# its decode host.
_SPAN_PAIRS = {
    "queued": ("submitted", "admitted"),
    "prefill": ("prefill_start", "prefill_end"),
    "transfer": ("transfer_start", "transfer_end"),
    "migrate": ("migrate_start", "migrate_end"),
    "decode": ("first_token", "retired"),
}


def _meta(pid: int, tid: int, name: str, kind: str) -> Dict[str, Any]:
    return {"ph": "M", "pid": pid, "tid": tid, "name": kind,
            "args": {"name": name}}


def _span(name: str, pid: int, tid: int, t0_ms: float, t1_ms: float,
          args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return {"ph": "X", "name": name, "pid": pid, "tid": tid,
            "ts": round(t0_ms * 1e3, 1),          # trace ts is µs
            "dur": round(max(0.0, t1_ms - t0_ms) * 1e3, 1),
            "cat": "serve", "args": args or {}}


def request_spans(records: Iterable[Dict[str, Any]]
                  ) -> Dict[str, List[Dict[str, Any]]]:
    """Per-uid span list derived from an event log: the lifecycle pairs of
    :data:`_SPAN_PAIRS` plus one span per ``decode_chunk`` event. This is
    the SAME derivation :func:`chrome_trace` renders, exposed so tests can
    pin trace == JSONL request-for-request."""
    by_uid: Dict[str, Dict[str, float]] = {}
    spans: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("kind") != "event" or "uid" not in r:
            continue
        uid, ev, t = r["uid"], r["event"], float(r["t_ms"])
        seen = by_uid.setdefault(uid, {})
        seen.setdefault(ev, t)  # first occurrence anchors the span
        out = spans.setdefault(uid, [])
        if ev == "decode_chunk" and "start_ms" in r:
            out.append({"name": "decode_chunk",
                        "t0_ms": float(r["start_ms"]), "t1_ms": t,
                        "n_tokens": r.get("n_tokens")})
    for uid, seen in by_uid.items():
        out = spans.setdefault(uid, [])
        for name, (a, b) in _SPAN_PAIRS.items():
            if a in seen and b in seen:
                out.append({"name": name, "t0_ms": seen[a],
                            "t1_ms": seen[b]})
    return spans


def chrome_trace(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Render an event log (dicts from :class:`EventLog` / ``read_jsonl``)
    as a Chrome trace-event object: request tracks (one tid per uid, spans
    from :func:`request_spans`), slot tracks (one tid per slot, one span
    per residency ``admitted → retired`` named by the uid), gauge counter
    tracks."""
    records = list(records)
    events = [r for r in records if r.get("kind") == "event"]
    gauges = [r for r in records if r.get("kind") == "gauge"]

    trace: List[Dict[str, Any]] = [
        _meta(_PID_REQUESTS, 0, "requests", "process_name"),
        _meta(_PID_SLOTS, 0, "slots", "process_name"),
    ]

    # request tracks: stable tid per uid in first-seen order
    uid_tid: Dict[str, int] = {}
    for r in events:
        uid = r.get("uid")
        if uid is not None and uid not in uid_tid:
            uid_tid[uid] = len(uid_tid)
            trace.append(_meta(_PID_REQUESTS, uid_tid[uid], uid,
                               "thread_name"))
    for uid, spans in request_spans(events).items():
        for s in spans:
            args = {k: v for k, v in s.items()
                    if k not in ("name", "t0_ms", "t1_ms") and v is not None}
            trace.append(_span(s["name"], _PID_REQUESTS, uid_tid[uid],
                               s["t0_ms"], s["t1_ms"], args))

    # slot tracks: residency spans named by uid (admitted -> retired)
    admitted: Dict[str, Dict[str, Any]] = {}
    slot_tids = set()
    for r in events:
        uid = r.get("uid")
        if r["event"] == "admitted" and "slot" in r:
            admitted[uid] = r
        elif r["event"] == "retired" and uid in admitted:
            a = admitted.pop(uid)
            slot = int(a["slot"])
            slot_tids.add(slot)
            trace.append(_span(uid, _PID_SLOTS, slot, float(a["t_ms"]),
                               float(r["t_ms"])))
    for slot in sorted(slot_tids):
        trace.append(_meta(_PID_SLOTS, slot, f"slot {slot}", "thread_name"))

    # gauges as counter tracks
    for g in gauges:
        trace.append({"ph": "C", "name": g["gauge"], "pid": _PID_REQUESTS,
                      "tid": 0, "ts": round(float(g["t_ms"]) * 1e3, 1),
                      "args": {g["gauge"]: g["value"]}})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Dump :func:`chrome_trace` to ``path`` (open the file in Perfetto /
    ``chrome://tracing``); returns the trace object."""
    trace = chrome_trace(records)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace

"""Per-request / per-tenant resource metering under a declarative
CostModel (monitor tier 4).

"Who pays for what": every retired request is charged ONCE — at final
retirement, by whichever engine retired it — for the modeled resources it
consumed, and the charges roll up per tenant:

* ``flops``           — modeled forward flops (the closed-form sum of
  ``serve.engine.decode_flops_per_token`` over the request's prefill
  positions and decode contexts — :func:`modeled_request_flops`),
* ``kv_block_s``      — KV-pool block-seconds of occupancy
  (blocks held × admitted→retired wall seconds),
* ``adapter_s``       — LoRA adapter residency-seconds pinned by the
  request's slot,
* ``adapter_load_ms`` — pool install time (charged at ``load_adapter``,
  to the ``_fleet`` pseudo-tenant when no tenant is attributable),
* ``wire_bytes``      — KV-transfer bytes the cluster moved for the
  request (handoffs and migrations).

Charging at retirement is what makes the fleet ledger double-count-proof
across migration and replay: the source engine of a migrated request
evicts without retiring (no charge), the destination retires once
(one charge covering the whole request), and replayed tokens appear in
the token count once however many times they decoded.

:class:`CostModel` is a declarative ``resource → weight`` map; ``cost
units = Σ weight_r × usage_r``. Tenancy is cardinality-bounded exactly
like the router's WFQ ledger and the MetricsRegistry: past
``max_tenants`` distinct ids, new tenants fold into the ``_overflow``
pseudo-tenant and ``overflow_charges_total`` counts every folded charge —
a tenant-id explosion degrades LOUDLY (visible counters, bounded memory),
never silently.

The per-worker view (``worker_cost_rate``) is the routing signal ROADMAP
item 5c consumes: each decode worker's accrued cost units per second,
advertised on the membership heartbeat next to its adapter residency and
quant mode, so an SLO-vs-cost router can prefer the cheapest worker that
still meets the deadline.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional

__all__ = [
    "DEFAULT_WEIGHTS",
    "OVERFLOW_TENANT",
    "CostModel",
    "Meter",
    "modeled_request_flops",
]

RESOURCES = ("flops", "kv_block_s", "adapter_s", "adapter_load_ms",
             "wire_bytes")
_COUNTS = ("tokens", "requests", "shed")

OVERFLOW_TENANT = "_overflow"

# default weights: one cost unit ≈ one Tflop of modeled compute; the
# other resources are scaled to be same-order for the pinned bench model
# (operators override with their own CostModel — the POINT is that the
# weights are declarative, not baked into call sites)
DEFAULT_WEIGHTS: Dict[str, float] = {
    "flops": 1e-12,
    "kv_block_s": 1e-2,
    "adapter_s": 1e-2,
    "adapter_load_ms": 1e-3,
    "wire_bytes": 1e-9,
}


def modeled_request_flops(n_params: int, num_layers: int, hidden: int,
                          prompt_len: int, n_generated: int,
                          cached_tokens: int = 0) -> float:
    """Modeled forward flops for one whole request: the closed-form sum
    of the serve engine's per-token model (``2N + 4·L·hidden·context``)
    over the prefill positions actually computed (``cached_tokens``
    skipped via the prefix cache are NOT billed — cache hits are the
    tenant's discount) and the decode contexts ``p .. p+g-2`` (the first
    generated token falls out of the prefill's last chunk)."""
    def span(a: int, b: int) -> float:
        n = max(0, b - a)
        return (n * 2.0 * n_params
                + 4.0 * num_layers * hidden * (a + b - 1) * n / 2.0)

    prefill = span(cached_tokens, prompt_len)
    decode = span(prompt_len, prompt_len + max(0, n_generated - 1))
    return prefill + decode


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Declarative resource → cost-unit weights. Unknown resources weigh
    zero (forward-compatible: an old model prices a new resource at 0
    rather than raising mid-serve)."""

    weights: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_WEIGHTS))

    def cost(self, usage: Mapping[str, Any]) -> float:
        return sum(w * float(usage.get(r, 0.0) or 0.0)
                   for r, w in self.weights.items())

    def to_dict(self) -> Dict[str, float]:
        return dict(self.weights)

    @classmethod
    def from_dict(cls, d: Mapping[str, float]) -> "CostModel":
        return cls(weights={k: float(v) for k, v in d.items()})


def _new_ledger() -> Dict[str, float]:
    led: Dict[str, float] = {r: 0.0 for r in RESOURCES}
    led.update({c: 0 for c in _COUNTS})
    return led


class Meter:
    """The shared fleet ledger. One instance per cluster (engines of all
    workers charge into it — one charge per request means Σ tenants ==
    fleet totals to the unit), or one per standalone engine."""

    def __init__(self, model: Optional[CostModel] = None,
                 max_tenants: int = 1024):
        if max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, got {max_tenants}")
        self.model = model or CostModel()
        self.max_tenants = max_tenants
        self._tenants: Dict[str, Dict[str, float]] = {}
        # per-worker accrual for the heartbeat-advertised cost rate:
        # bounded by fleet size, never by tenant count
        self._workers: Dict[str, Dict[str, float]] = {}
        self.overflow_charges_total = 0

    # -- charging ----------------------------------------------------------
    def _ledger(self, tenant: str) -> Dict[str, float]:
        led = self._tenants.get(tenant)
        if led is None:
            if (len(self._tenants) >= self.max_tenants
                    and tenant != OVERFLOW_TENANT):
                # cardinality bound: fold, count, stay loud
                self.overflow_charges_total += 1
                return self._ledger(OVERFLOW_TENANT)
            led = self._tenants[tenant] = _new_ledger()
        return led

    def charge(self, tenant: Optional[str], *, worker: Optional[str] = None,
               t_ms: Optional[float] = None, tokens: int = 0,
               requests: int = 0, shed: int = 0,
               **usage: float) -> float:
        """Fold one charge into the tenant's ledger; returns the cost in
        units. ``worker``/``t_ms`` additionally accrue the worker's cost
        rate (pass the one shared event clock's ms)."""
        for k in usage:
            if k not in RESOURCES:
                raise ValueError(
                    f"unknown resource {k!r} (known: {RESOURCES})")
        led = self._ledger(tenant or "default")
        for k, v in usage.items():
            led[k] += float(v)
        led["tokens"] += int(tokens)
        led["requests"] += int(requests)
        led["shed"] += int(shed)
        cost = self.model.cost(usage)
        if worker is not None:
            w = self._workers.setdefault(
                worker, {"cost": 0.0, "t0_ms": None, "t1_ms": None})
            w["cost"] += cost
            if t_ms is not None:
                if w["t0_ms"] is None:
                    w["t0_ms"] = float(t_ms)
                w["t1_ms"] = float(t_ms)
        return cost

    # -- rollups -----------------------------------------------------------
    def _roll(self, led: Mapping[str, float]) -> Dict[str, Any]:
        cost = self.model.cost(led)
        toks, reqs = int(led["tokens"]), int(led["requests"])
        out: Dict[str, Any] = {r: round(float(led[r]), 6)
                               for r in RESOURCES}
        out.update({c: int(led[c]) for c in _COUNTS})
        out["cost_units"] = round(cost, 6)
        out["cost_per_token"] = round(cost / toks, 9) if toks else None
        out["cost_per_request"] = round(cost / reqs, 9) if reqs else None
        return out

    def tenant_rollup(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant usage + cost (``cost_per_token`` /
        ``cost_per_request`` included — the regress-gated billing view)."""
        return {t: self._roll(led)
                for t, led in sorted(self._tenants.items())}

    def totals(self) -> Dict[str, Any]:
        """The whole-fleet ledger: by construction the exact field-wise
        sum of every tenant's rollup (one charge, one ledger — the
        no-double-count acceptance pin)."""
        tot = _new_ledger()
        for led in self._tenants.values():
            for k, v in led.items():
                tot[k] += v
        return self._roll(tot)

    def worker_cost_rate(self, worker: str,
                         t_ms: Optional[float] = None) -> float:
        """Accrued cost units per second for one worker (0.0 before its
        first charge) — the heartbeat advertisement."""
        w = self._workers.get(worker)
        if w is None or w["t0_ms"] is None:
            return 0.0
        t1 = float(t_ms) if t_ms is not None else w["t1_ms"]
        dt_s = max((t1 - w["t0_ms"]) / 1e3, 1e-9)
        return w["cost"] / dt_s

    def worker_rates(self, t_ms: Optional[float] = None
                     ) -> Dict[str, float]:
        return {name: round(self.worker_cost_rate(name, t_ms), 6)
                for name in sorted(self._workers)}

    # -- exposition --------------------------------------------------------
    def stats(self, completed: Optional[int] = None) -> Dict[str, Any]:
        """One JSON-serializable meter snapshot. ``completed`` (the
        engine/cluster retirement count) yields ``meter_coverage`` —
        metered requests / completed requests, the health of the plane
        itself (higher-better under regress)."""
        tot = self.totals()
        out: Dict[str, Any] = {
            "totals": tot,
            "tenants": self.tenant_rollup(),
            "n_tenants": len(self._tenants),
            "max_tenants": self.max_tenants,
            "overflow_charges_total": self.overflow_charges_total,
            "cost_per_token": tot["cost_per_token"],
            "cost_per_request": tot["cost_per_request"],
            "cost_model": self.model.to_dict(),
        }
        if completed is not None:
            out["meter_coverage"] = (
                round(min(1.0, tot["requests"] / completed), 4)
                if completed else None)
        return out

    def collect_registry(self, reg, t_ms: Optional[float] = None) -> None:
        """Fold the ledger into a MetricsRegistry (``tenant=`` labels).
        Cardinality is pre-bounded by ``max_tenants``, so this composes
        with the registry's own ``max_series`` bound instead of fighting
        it."""
        for tname, led in self._tenants.items():
            cost = self.model.cost(led)
            reg.counter("meter_cost_units_total", cost, tenant=tname)
            reg.counter("meter_tokens_total", int(led["tokens"]),
                        tenant=tname)
            reg.counter("meter_requests_total", int(led["requests"]),
                        tenant=tname)
        reg.counter("meter_overflow_charges_total",
                    self.overflow_charges_total)
        reg.gauge("meter_tenants", float(len(self._tenants)),
                  t_ms=0.0 if t_ms is None else t_ms)

"""Per-request latency attribution from the EventLog lifecycle (tier 4).

The fleet plane (tier 3) says *that* e2e or goodput regressed; this module
says *why*: every retired request's end-to-end time decomposes into five
disjoint components —

* ``queue``    — submitted until the first ``prefill_start`` (router +
  admission wait; falls back to the first ``admitted`` for logs that
  never prefilled locally),
* ``prefill``  — union of the request's ``prefill_start → prefill_end``
  intervals,
* ``transfer`` — union of the ``transfer_*`` and ``migrate_*`` intervals
  (the KV-block wire: the disaggregated handoff AND any chaos
  migration; a migrate window encloses its own transfer, so the union
  never double-counts),
* ``decode``   — the ``first_token → retired`` window minus its overlap
  with the transfer/migrate union (replayed tokens after a migration
  decode again — their time is decode time, the hop itself is not),
* ``stall``    — the residual, so the components ALWAYS sum to the
  event-derived e2e exactly; the pinned identity is therefore that
  ``stall`` stays non-negative (within clock-rounding tolerance) — a
  materially negative stall means components double-counted.

Derivation follows the ``request_spans`` discipline exactly: records are
deduplicated first (``_dedupe_events`` — merged worker logs replay shared
records), anchors are min-by-timestamp (max for the terminal ``retired``),
and retried ``transfer_start`` re-emissions (``attempt > 1``) never open a
second interval — so ANY concatenation order of the same logs attributes
identically, the same order-independence contract the chaos trace gate
pins.

Three consumers:

* :func:`attribute_requests` / :func:`attribution_summary` — batch
  attribution over a finished event stream (tests, ``monitor.view``,
  ``explain_regression``).
* :class:`AttributionAccumulator` — the streaming form: tap an
  :class:`~apex_tpu.monitor.events.EventLog`, keep O(in-flight) state,
  fold components into per-component :class:`Histogram`\\ s at each
  ``retired`` — what ``ServeCluster.stats()`` reports as
  ``{component}_component_ms_p50/p99`` + ``attrib_coverage``.
* :func:`explain_regression` — decompose a baseline-vs-new e2e delta into
  per-component deltas so a stage gate emits a *diagnosis* ("decode grew
  41 ms of the 44 ms regression"), not just a verdict.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from apex_tpu.monitor.events import _dedupe_events
from apex_tpu.monitor.hist import HistSpec, Histogram

__all__ = [
    "COMPONENTS",
    "AttributionAccumulator",
    "attribute_requests",
    "attribution_summary",
    "component_hists",
    "explain_regression",
]

COMPONENTS = ("queue", "prefill", "transfer", "decode", "stall")

# clock stamps round to 3 decimals (events.py), so per-request sums can
# miss the measured e2e by a few microseconds per event — anything past
# this is a real double-count, not rounding
DEFAULT_TOL_MS = 1.0

# interval-shaped event pairs (the _SPAN_PAIRS subset attribution needs);
# transfer and migrate fold into ONE "transfer" component via interval
# union — a migration's migrate window encloses its own wire transfer
_PAIR_EVENTS = {
    "prefill": ("prefill_start", "prefill_end"),
    "transfer": ("transfer_start", "transfer_end"),
    "migrate": ("migrate_start", "migrate_end"),
}


def _new_times() -> Dict[str, Any]:
    return {"submitted": None, "admitted": None, "first_token": None,
            "retired": None,
            "starts": {k: [] for k in _PAIR_EVENTS},
            "ends": {k: [] for k in _PAIR_EVENTS},
            "replayed_tokens": 0, "migrations": 0,
            "tenant": None, "trace": None}


def _feed(times: Dict[str, Any], ev: str, t: float,
          rec: Mapping[str, Any]) -> None:
    """Fold one deduplicated event into a uid's anchor state — pure
    min/max/append, so feeding order never matters."""
    if times["tenant"] is None and "tenant" in rec:
        times["tenant"] = rec["tenant"]
    if times["trace"] is None and "trace" in rec:
        times["trace"] = rec["trace"]
    if ev in ("submitted", "admitted", "first_token"):
        cur = times[ev]
        times[ev] = t if cur is None else min(cur, t)
        return
    if ev == "retired":
        cur = times["retired"]
        times["retired"] = t if cur is None else max(cur, t)
        return
    if ev == "replay":
        times["replayed_tokens"] += int(rec.get("n_tokens", 0) or 0)
        return
    for kind, (a, b) in _PAIR_EVENTS.items():
        if ev == a:
            # a transfer RETRY re-emits the start with attempt > 1; only
            # first attempts open an interval (the retry is covered by
            # the original's span — same rule as stitch_traces)
            if int(rec.get("attempt", 1) or 1) <= 1:
                times["starts"][kind].append(t)
                if kind == "migrate":
                    times["migrations"] += 1
            return
        if ev == b:
            times["ends"][kind].append(t)
            return


def _pair(starts: List[float], ends: List[float]
          ) -> List[Tuple[float, float]]:
    """FIFO-pair sorted starts with sorted ends into intervals (an
    unmatched trailing side — a truncated log — is dropped)."""
    return [(s, e) for s, e in zip(sorted(starts), sorted(ends)) if e > s]


def _union(intervals: List[Tuple[float, float]]
           ) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _clipped_len(merged: List[Tuple[float, float]],
                 lo: float, hi: float) -> float:
    return sum(max(0.0, min(b, hi) - max(a, lo)) for a, b in merged)


def _components(times: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The per-request decomposition; ``None`` until the request has both
    a ``submitted`` and a ``retired`` anchor (shed / in-flight requests
    are not attributable)."""
    t0, tf = times["submitted"], times["retired"]
    if t0 is None or tf is None:
        return None
    e2e = max(0.0, tf - t0)
    ps = times["starts"]["prefill"]
    anchor = min(ps) if ps else times["admitted"]
    queue = min(max(0.0, anchor - t0), e2e) if anchor is not None else 0.0
    prefill_u = _union(_pair(ps, times["ends"]["prefill"]))
    xfer_u = _union(_pair(times["starts"]["transfer"],
                          times["ends"]["transfer"])
                    + _pair(times["starts"]["migrate"],
                            times["ends"]["migrate"]))
    prefill = _clipped_len(prefill_u, t0, tf)
    transfer = _clipped_len(xfer_u, t0, tf)
    ft = times["first_token"]
    if ft is not None:
        decode = max(0.0, (tf - ft) - _clipped_len(xfer_u, ft, tf))
    else:
        decode = 0.0
    stall = e2e - (queue + prefill + transfer + decode)
    out: Dict[str, Any] = {
        "queue": round(queue, 3), "prefill": round(prefill, 3),
        "transfer": round(transfer, 3), "decode": round(decode, 3),
        "stall": round(stall, 3), "e2e_ms": round(e2e, 3),
        "migrated": times["migrations"] > 0,
        "replayed_tokens": times["replayed_tokens"],
    }
    if times["tenant"] is not None:
        out["tenant"] = times["tenant"]
    if times["trace"] is not None:
        out["trace"] = times["trace"]
    return out


def attribute_requests(records: Iterable[Mapping[str, Any]], *,
                       deduped: bool = False
                       ) -> Dict[str, Dict[str, Any]]:
    """uid -> component decomposition for every RETIRED request in the
    stream. Identity: the five :data:`COMPONENTS` sum to ``e2e_ms``
    exactly (stall is the residual); a well-formed log keeps
    ``stall >= -DEFAULT_TOL_MS``."""
    per_uid: Dict[str, Dict[str, Any]] = {}
    for r in (records if deduped else _dedupe_events(records)):
        if r.get("kind") != "event" or "uid" not in r:
            continue
        times = per_uid.setdefault(r["uid"], _new_times())
        _feed(times, r["event"], float(r["t_ms"]), r)
    out: Dict[str, Dict[str, Any]] = {}
    for uid, times in per_uid.items():
        c = _components(times)
        if c is not None:
            out[uid] = c
    return out


def component_hists(records: Iterable[Mapping[str, Any]], *,
                    spec: Optional[HistSpec] = None
                    ) -> Dict[str, Histogram]:
    """Per-component Histograms over a finished event stream (the batch
    twin of :class:`AttributionAccumulator`)."""
    hists = {c: Histogram(spec) for c in COMPONENTS}
    for comp in attribute_requests(records).values():
        for c in COMPONENTS:
            hists[c].add([max(0.0, comp[c])])
    return hists


def _summary_from(hists: Mapping[str, Histogram], n_retired: int,
                  n_attributed: int, tol_ms: float,
                  n_clean: int) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "n_retired": n_retired,
        "n_attributed": n_attributed,
        # coverage counts requests whose decomposition exists AND holds
        # the identity (stall within -tol): the regress-gated health of
        # the attribution plane itself
        "attrib_coverage": (round(n_clean / n_retired, 4)
                            if n_retired else None),
        "tol_ms": tol_ms,
    }
    for c in COMPONENTS:
        h = hists[c]
        if h.total == 0:
            continue
        out[f"{c}_component_ms_p50"] = round(h.quantile(0.5), 3)
        out[f"{c}_component_ms_p99"] = round(h.quantile(0.99), 3)
        mean = h.mean()
        if mean is not None:
            out[f"{c}_component_ms_mean"] = round(mean, 3)
    return out


def attribution_summary(records: Iterable[Mapping[str, Any]], *,
                        spec: Optional[HistSpec] = None,
                        tol_ms: float = DEFAULT_TOL_MS
                        ) -> Dict[str, Any]:
    """JSON-flat attribution aggregate over a finished event stream:
    ``{component}_component_ms_p50/p99/mean`` + ``attrib_coverage``
    (``monitor.regress`` gates both — component latencies lower-better,
    coverage higher-better)."""
    records = list(records)
    deduped = _dedupe_events(records)
    n_retired = len({r["uid"] for r in deduped
                     if r.get("kind") == "event" and "uid" in r
                     and r.get("event") == "retired"})
    attrib = attribute_requests(deduped, deduped=True)
    hists = {c: Histogram(spec) for c in COMPONENTS}
    n_clean = 0
    for comp in attrib.values():
        for c in COMPONENTS:
            hists[c].add([max(0.0, comp[c])])
        if comp["stall"] >= -tol_ms:
            n_clean += 1
    return _summary_from(hists, n_retired, len(attrib), tol_ms, n_clean)


class AttributionAccumulator:
    """Streaming attribution for a live :class:`EventLog`: register with
    ``events.tap(acc.tap)``; per-uid anchor state lives only while the
    request is in flight, and every ``retired`` folds the decomposition
    into per-component Histograms — O(in-flight) memory on week-long
    runs, the same contract as the engine's own histograms.

    The live tap sees each record exactly once (flight-recorder dump
    COPIES go through the sink, never the tap), so no dedupe pass is
    needed; the pairing rules are identical to the batch path."""

    def __init__(self, spec: Optional[HistSpec] = None,
                 tol_ms: float = DEFAULT_TOL_MS):
        self.hists: Dict[str, Histogram] = {
            c: Histogram(spec) for c in COMPONENTS}
        self.e2e = Histogram(spec)
        self.tol_ms = tol_ms
        self.n_retired = 0
        self.n_attributed = 0
        self.n_clean = 0
        self._open: Dict[str, Dict[str, Any]] = {}

    def tap(self, rec: Mapping[str, Any]) -> None:
        if rec.get("kind") != "event" or "uid" not in rec:
            return
        uid, ev = rec["uid"], rec["event"]
        if ev == "shed":
            # terminal without attribution — drop the open state
            self._open.pop(uid, None)
            return
        times = self._open.setdefault(uid, _new_times())
        _feed(times, ev, float(rec["t_ms"]), rec)
        if ev != "retired":
            return
        self.n_retired += 1
        comp = _components(self._open.pop(uid))
        if comp is None:
            return
        self.n_attributed += 1
        if comp["stall"] >= -self.tol_ms:
            self.n_clean += 1
        for c in COMPONENTS:
            self.hists[c].add([max(0.0, comp[c])])
        self.e2e.add([comp["e2e_ms"]])

    @property
    def in_flight(self) -> int:
        return len(self._open)

    def summary(self) -> Dict[str, Any]:
        return _summary_from(self.hists, self.n_retired,
                             self.n_attributed, self.tol_ms, self.n_clean)


def _component_means(attrib: Mapping[str, Mapping[str, Any]]
                     ) -> Dict[str, float]:
    n = len(attrib)
    out = {c: 0.0 for c in COMPONENTS}
    out["e2e_ms"] = 0.0
    if not n:
        return out
    for comp in attrib.values():
        for c in COMPONENTS:
            out[c] += comp[c]
        out["e2e_ms"] += comp["e2e_ms"]
    return {k: v / n for k, v in out.items()}


def explain_regression(baseline_records: Iterable[Mapping[str, Any]],
                       new_records: Iterable[Mapping[str, Any]], *,
                       top: int = 3) -> Dict[str, Any]:
    """Decompose an e2e regression between two event streams into
    per-component deltas. Means (not quantiles) because means are
    additive: the component deltas sum to the e2e delta exactly, so the
    diagnosis accounts for ALL of the regression. Returns the component
    ranking (worst first), the ``top`` regressed component names, and a
    one-word ``diagnosis`` — the component that grew the most (``None``
    when e2e did not regress)."""
    base = _component_means(attribute_requests(baseline_records))
    new = _component_means(attribute_requests(new_records))
    delta_e2e = new["e2e_ms"] - base["e2e_ms"]
    comps = []
    for c in COMPONENTS:
        d = new[c] - base[c]
        comps.append({
            "component": c,
            "baseline_ms": round(base[c], 3),
            "new_ms": round(new[c], 3),
            "delta_ms": round(d, 3),
            "share": (round(d / delta_e2e, 4) if abs(delta_e2e) > 1e-9
                      else None),
        })
    comps.sort(key=lambda e: -e["delta_ms"])
    regressed = [e["component"] for e in comps if e["delta_ms"] > 0.0]
    return {
        "metric": "e2e_ms",
        "baseline_mean_ms": round(base["e2e_ms"], 3),
        "new_mean_ms": round(new["e2e_ms"], 3),
        "delta_ms": round(delta_e2e, 3),
        "components": comps,
        "top_regressed": regressed[:top],
        "diagnosis": (comps[0]["component"]
                      if delta_e2e > 0 and comps[0]["delta_ms"] > 0
                      else None),
    }

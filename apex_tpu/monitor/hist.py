"""Fixed log-spaced-bucket streaming histograms — mergeable, constant
memory, bounded-relative-error quantiles.

The serving-telemetry problem (monitor tier 2): a week-long engine run
retires millions of requests, and "TTFT p99 under bursty load" must come
out of O(1) state, not a per-request list. The classic answer (HdrHistogram
/ DDSketch's log-spaced buckets) fits the monitor pipeline unusually well
because a fixed bucket ladder is exactly a fixed *name set*:

* **host-side** — :class:`Histogram` over a :class:`HistSpec`: ``add`` is
  one ``bincount``, ``merge`` adds count vectors (associative and
  commutative, so per-process / per-window histograms combine exactly),
  and :meth:`Histogram.quantile` returns the geometric midpoint of the
  rank's bucket — relative error ≤ ``spec.rel_error`` (= √growth − 1) for
  values inside ``[lo, hi)``, by construction, on ANY distribution;
* **in-graph** — :func:`bucket_indices` / :func:`hist_counts` compute the
  count vector with jnp ops, and :func:`accumulate_hist` folds it into the
  existing :class:`~apex_tpu.monitor.metrics.Metrics` pytree as one scalar
  counter per bucket (names ``{name}.h###`` — static for a fixed spec, so
  the treedef never changes and the jitted step retraces nothing, the same
  contract as every other monitor producer). :func:`hist_from_metrics`
  reassembles a host Histogram from a sink record.

Serialization rides the JSONL convention: :meth:`Histogram.to_dict` /
:meth:`Histogram.from_dict` round-trip through ``json`` so histograms live
inside bench records (``benchmarks/loadgen.py``'s goodput-under-SLO line)
and are diffable by ``monitor.regress``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DEFAULT_LATENCY_SPEC",
    "HistSpec",
    "Histogram",
    "accumulate_hist",
    "bucket_indices",
    "hist_counts",
    "hist_from_metrics",
    "hist_metric_names",
]


@dataclasses.dataclass(frozen=True)
class HistSpec:
    """Log-spaced bucket ladder: bucket 0 is the underflow ``(-inf, lo)``
    (zeros and negatives land here), buckets ``1..n`` cover
    ``[lo·g^(i-1), lo·g^i)``, and the last bucket is the overflow
    ``[~hi, inf)``. ``rel_error`` (= √growth − 1) bounds the quantile
    estimate's relative error for values inside the ladder."""

    lo: float = 0.01      # smallest resolvable value (ms scale: 10 µs)
    hi: float = 6.0e5     # largest (ms scale: 10 minutes)
    growth: float = 1.1   # bucket edge ratio -> ~4.9 % relative error

    def __post_init__(self):
        if not (self.lo > 0 and self.hi > self.lo):
            raise ValueError(f"need 0 < lo < hi, got ({self.lo}, {self.hi})")
        if not self.growth > 1.0:
            raise ValueError(f"growth must be > 1, got {self.growth}")

    @property
    def num_log_buckets(self) -> int:
        return int(math.ceil(math.log(self.hi / self.lo)
                             / math.log(self.growth)))

    @property
    def num_buckets(self) -> int:
        """underflow + log ladder + overflow."""
        return self.num_log_buckets + 2

    @property
    def rel_error(self) -> float:
        return math.sqrt(self.growth) - 1.0

    def edges(self) -> np.ndarray:
        """The ``num_log_buckets + 1`` finite edges (bucket i in 1..n spans
        ``[edges[i-1], edges[i])``)."""
        return self.lo * self.growth ** np.arange(self.num_log_buckets + 1)

    def bucket_of(self, values: np.ndarray) -> np.ndarray:
        """Host-side bucket index per value (vectorized)."""
        v = np.asarray(values, np.float64)
        out = np.zeros(v.shape, np.int64)
        pos = v >= self.lo
        idx = 1 + np.floor(np.log(np.where(pos, v, self.lo) / self.lo)
                           / math.log(self.growth)).astype(np.int64)
        np.copyto(out, np.clip(idx, 1, self.num_buckets - 1), where=pos)
        return out

    def estimate_of(self, bucket: int) -> float:
        """Representative value of a bucket: the geometric midpoint (the
        point minimizing worst-case relative error). Underflow reports
        ``lo``, overflow ``hi`` — callers holding exact min/max (the host
        Histogram does) clamp further."""
        if bucket <= 0:
            return self.lo
        if bucket >= self.num_buckets - 1:
            return self.hi
        return float(self.lo * self.growth ** (bucket - 1)
                     * math.sqrt(self.growth))

    def to_dict(self) -> Dict[str, float]:
        return {"lo": self.lo, "hi": self.hi, "growth": self.growth}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "HistSpec":
        return cls(lo=float(d["lo"]), hi=float(d["hi"]),
                   growth=float(d["growth"]))


# the serving-latency default: 10 µs .. 10 min at ~4.9 % relative error
DEFAULT_LATENCY_SPEC = HistSpec()


class Histogram:
    """Streaming histogram over a :class:`HistSpec`: constant memory
    (one int64 count vector + exact count/sum/min/max), mergeable, with
    nearest-rank quantile estimates whose relative error is bounded by
    ``spec.rel_error`` inside the ladder."""

    __slots__ = ("spec", "counts", "total", "sum", "min", "max")

    def __init__(self, spec: Optional[HistSpec] = None):
        self.spec = spec or DEFAULT_LATENCY_SPEC
        self.counts = np.zeros((self.spec.num_buckets,), np.int64)
        self.total = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- ingest ------------------------------------------------------------
    def add(self, values: Iterable[float]) -> "Histogram":
        """Fold values in (in place; returns self for chaining)."""
        v = np.atleast_1d(np.asarray(values, np.float64))
        if v.size == 0:
            return self
        self.counts += np.bincount(self.spec.bucket_of(v),
                                   minlength=self.spec.num_buckets)
        self.total += int(v.size)
        self.sum += float(v.sum())
        self.min = min(self.min, float(v.min()))
        self.max = max(self.max, float(v.max()))
        return self

    def add_counts(self, counts: np.ndarray) -> "Histogram":
        """Fold a raw count vector in (the in-graph ``hist_counts`` path —
        no exact sum/min/max available, so those stay whatever exact
        observations contributed)."""
        c = np.asarray(counts)
        if c.shape != self.counts.shape:
            raise ValueError(
                f"count vector shape {c.shape} != {self.counts.shape}")
        c = c.astype(np.int64)
        if (c < 0).any():
            raise ValueError("negative bucket counts")
        self.counts += c
        self.total += int(c.sum())
        return self

    # -- merge (associative + commutative) ---------------------------------
    def merge(self, other: "Histogram") -> "Histogram":
        """New histogram = self ⊎ other (specs must match)."""
        if self.spec != other.spec:
            raise ValueError(f"spec mismatch: {self.spec} vs {other.spec}")
        out = Histogram(self.spec)
        out.counts = self.counts + other.counts
        out.total = self.total + other.total
        out.sum = self.sum + other.sum
        out.min = min(self.min, other.min)
        out.max = max(self.max, other.max)
        return out

    def __add__(self, other: "Histogram") -> "Histogram":
        return self.merge(other)

    # -- readout -----------------------------------------------------------
    def mean(self) -> Optional[float]:
        # honest only when every observation arrived through add(); pure
        # add_counts histograms report the bucket-estimate mean instead
        if self.total == 0:
            return None
        if math.isfinite(self.min):
            return self.sum / self.total
        est = sum(int(c) * self.spec.estimate_of(i)
                  for i, c in enumerate(self.counts) if c)
        return est / self.total

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate (``q`` in [0, 1]); ``None`` when
        empty. Exact min/max clamp the under/overflow buckets when known."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.total == 0:
            return None
        # the extremes are tracked exactly — report them exactly
        if q == 0.0 and math.isfinite(self.min):
            return self.min
        if q == 1.0 and math.isfinite(self.max):
            return self.max
        rank = max(1, int(math.ceil(q * self.total)))  # 1-based
        cum = np.cumsum(self.counts)
        bucket = int(np.searchsorted(cum, rank))
        est = self.spec.estimate_of(bucket)
        if bucket == 0 and math.isfinite(self.min):
            return self.min
        if bucket == self.spec.num_buckets - 1 and math.isfinite(self.max):
            return self.max
        if math.isfinite(self.min):
            est = min(max(est, self.min), self.max)
        return est

    def quantiles(self, qs: Sequence[float]) -> List[Optional[float]]:
        return [self.quantile(q) for q in qs]

    # -- serialization (JSONL / bench-record friendly) ---------------------
    def to_dict(self) -> Dict[str, Any]:
        """JSON-serializable snapshot; sparse (bucket -> count) so ~200
        mostly-empty buckets don't bloat the record."""
        return {
            "spec": self.spec.to_dict(),
            "count": self.total,
            "sum": round(self.sum, 6),
            "min": self.min if math.isfinite(self.min) else None,
            "max": self.max if math.isfinite(self.max) else None,
            "buckets": {str(i): int(c)
                        for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Histogram":
        h = cls(HistSpec.from_dict(d["spec"]))
        for i, c in d["buckets"].items():
            h.counts[int(i)] = int(c)
        h.total = int(d["count"])
        h.sum = float(d.get("sum", 0.0))
        h.min = float(d["min"]) if d.get("min") is not None else math.inf
        h.max = float(d["max"]) if d.get("max") is not None else -math.inf
        return h

    def __repr__(self):
        return (f"Histogram(n={self.total}, p50={self.quantile(0.5)}, "
                f"p99={self.quantile(0.99)})")


# ---------------------------------------------------------------------------
# in-graph: count vectors on the Metrics pytree


def bucket_indices(values, spec: HistSpec):
    """Bucket index per value with jnp ops (jit-safe; ``spec`` is static)."""
    import jax.numpy as jnp

    v = jnp.asarray(values, jnp.float32)
    pos = v >= spec.lo
    idx = 1 + jnp.floor(
        jnp.log(jnp.where(pos, v, spec.lo) / spec.lo)
        / math.log(spec.growth)).astype(jnp.int32)
    return jnp.where(pos, jnp.clip(idx, 1, spec.num_buckets - 1), 0)


def hist_counts(values, spec: HistSpec, valid=None):
    """In-graph count vector (f32, length ``spec.num_buckets``) for a batch
    of values; ``valid`` (bool, same shape) masks entries out — the serve
    engine uses it for inactive slots."""
    import jax.numpy as jnp

    idx = bucket_indices(values, spec)
    w = (jnp.ones(idx.shape, jnp.float32) if valid is None
         else jnp.asarray(valid).astype(jnp.float32))
    return jnp.zeros((spec.num_buckets,), jnp.float32).at[idx].add(w)


def hist_metric_names(name: str, spec: HistSpec) -> Tuple[str, ...]:
    """The per-bucket Metrics scalar names — static for a fixed spec, so a
    step recording them has a stable treedef (pre-seed with these to carry
    a histogram through a donated step)."""
    return tuple(f"{name}.h{i:03d}" for i in range(spec.num_buckets))


def accumulate_hist(metrics, name: str, values, spec: HistSpec,
                    valid=None):
    """Fold a batch of in-graph values into ``metrics`` as per-bucket
    counters (``{name}.h###`` += bucket count). Same-name accumulation
    across steps composes exactly like ``Metrics.accumulate``; read back
    host-side with :func:`hist_from_metrics`.

    Precision contract: Metrics scalars are f32, so a carried bucket
    counter is exact only up to 2^24 (~16.7M) — past that, += 1 is a
    float no-op and the bucket silently saturates. Drain long-running
    counters to a host :class:`Histogram` (int64) well before any bucket
    approaches that — ``host = host.merge(hist_from_metrics(m.as_dict(),
    name, spec))`` then reset the carried names to zero. Per-window
    accumulation (the sink-record cadence) never nears the limit."""
    counts = hist_counts(values, spec, valid=valid)
    names = hist_metric_names(name, spec)
    return metrics.accumulate(**{n: counts[i] for i, n in enumerate(names)})


def hist_from_metrics(record: Mapping[str, Any], name: str,
                      spec: HistSpec) -> Histogram:
    """Reassemble a host Histogram from Metrics-as-dict / a sink record
    holding ``{name}.h###`` counters (missing buckets read as 0)."""
    h = Histogram(spec)
    counts = np.zeros((spec.num_buckets,), np.int64)
    for i, n in enumerate(hist_metric_names(name, spec)):
        c = record.get(n, 0.0)
        counts[i] = int(round(float(c)))
    return h.add_counts(counts)

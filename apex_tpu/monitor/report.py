"""Per-step MFU / bandwidth report — one join over three sources of truth.

The three observability fragments this unifies (each already exists, each
previously joined ad hoc by every consumer):

* ``apex_tpu.pyprof`` — MEASURED per-instruction time from the profiler
  trace (``measured_op_table``), the only source that answers "which op
  eats the step";
* ``apex_tpu.comm.accounting`` — bytes-on-wire priced from the compiled
  HLO's collectives (the EQuARX lesson: compression claims are validated
  on-wire, not in Python);
* analytic / XLA-cost-model FLOPs — the MFU denominator,
  cross-checked against ``compiled.cost_analysis()`` so it is never
  self-graded (``benchmarks/check_mfu_accounting.py``).

:func:`step_report` runs a jittable step under the profiler and returns one
flat dict (step time, MFU, wire bytes + modeled ICI bandwidth, per-phase
time via :func:`phase_breakdown` over ``monitor.span`` names, trace
coverage) ready for :func:`apex_tpu.monitor.sink.json_record`.
:func:`hlo_stats` / :func:`mfu_check` are the compile-only (no-trace)
subset for hosts that cannot run the profiler.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional

import jax

from apex_tpu.analyze.hlo import as_text
from apex_tpu.comm.accounting import collective_report


def gpt_analytic_flops_per_token(n_params: int, num_layers: int,
                                 hidden: int, seq: int) -> float:
    """Standard decoder MFU accounting: ``6·N`` per token (fwd+bwd matmuls)
    plus causal attention ``6·L·hidden·seq``. Remat recompute is NOT
    credited. Shared by ``bench.py`` and the HLO cross-check so the bench
    always divides by the constant the check validates."""
    return float(6 * n_params + 6 * num_layers * hidden * seq)


def pipeline_bubble_fraction(num_microbatches: int, pp: int) -> float:
    """Idle fraction of the 1F1B ring schedule: ``(pp-1)/(M+pp-1)`` of the
    ticks are fill/drain (``pipeline_ring`` runs ``M + pp - 1`` ticks for
    ``M`` real microbatches). The per-tick cost itself is measured via the
    schedule's ``pp_stage``/``pp_ring_shift`` spans."""
    if num_microbatches <= 0 or pp <= 0:
        raise ValueError("num_microbatches and pp must be positive")
    return (pp - 1) / (num_microbatches + pp - 1)


def hlo_stats(compiled, default_group_size: Optional[int] = None
              ) -> Dict[str, Any]:
    """Compile-time stats of a ``jax.stages.Compiled``: XLA cost-model
    flops/bytes plus the ring-model wire bytes of every collective."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    ca = dict(ca or {})
    # one .as_text() through the shared analyze.hlo normalization (the
    # same entry point accounting parses through), priced once
    rep = collective_report(as_text(compiled), default_group_size)
    # NaN (not 0.0) when the backend's cost model omits a key: a reader
    # must see "unavailable", never "measured zero"
    return {
        "hlo_flops": float(ca.get("flops", float("nan"))),
        "hlo_bytes_accessed": float(ca.get("bytes accessed", float("nan"))),
        "wire_bytes": rep.wire_bytes,
        "collective_counts": {k: v for k, v in rep.counts.items() if v},
    }


def mfu_check(fn: Callable, *args: Any, analytic_flops: float,
              **kwargs: Any) -> Dict[str, Any]:
    """Compile-only MFU-denominator validation: compare the analytic flops
    model against ``cost_analysis()`` on the exact compiled step (the
    ``check_mfu_accounting.py`` join). Returns the stats dict plus
    ``analytic_flops`` and ``hlo_over_analytic``."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    out = hlo_stats(compiled)
    out["analytic_flops"] = float(analytic_flops)
    out["hlo_over_analytic"] = (
        round(out["hlo_flops"] / analytic_flops, 4) if analytic_flops
        else float("nan"))
    return out


# AD/vectorization wrappers XLA's op paths accumulate around user scope
# names; peeled so e.g. transpose(jvp(fwd)) rolls up to the fwd phase
_WRAPPER_RE = re.compile(
    r"^(?:jvp|transpose|vmap|pmap|remat|checkpoint|custom_jvp|custom_vjp)"
    r"\((.*)\)$")


def _phase_of(scope: str) -> str:
    for part in scope.split("/"):
        if not part or (part.startswith("jit(") and part.endswith(")")):
            continue  # nested jit boundaries are plumbing, not phases
        while True:
            m = _WRAPPER_RE.match(part)
            if not m:
                break
            part = m.group(1)
        if part:
            return part
    return "<no-scope>"


def phase_breakdown(measured: Dict[str, Any]) -> Dict[str, float]:
    """ms/step per top-level span name, from a ``measured_op_table`` result.
    Scope paths come from ``monitor.span`` / ``jax.named_scope``; the first
    component that is a USER name is the phase (``fwd``/``bwd``/``comm``/
    ``opt`` or any name), with ``jit(...)`` boundaries skipped and
    ``jvp(...)``/``transpose(...)``-style AD wrappers peeled — a span
    traced under ``jax.grad`` (the pipeline ``pp_stage`` spans, a span
    inside the loss) still rolls its forward-replay AND transpose time up
    to the span's own name. Unscoped ops land in ``<no-scope>``."""
    phases: Dict[str, float] = {}
    for r in measured["rows"]:
        phase = _phase_of(r["scope"])
        phases[phase] = phases.get(phase, 0.0) + r["time_ms"]
    return dict(sorted(phases.items(), key=lambda kv: -kv[1]))


def step_report(
    fn: Callable,
    *args: Any,
    steps: int = 3,
    peak_flops: Optional[float] = None,
    analytic_flops_per_step: Optional[float] = None,
    depth: int = 2,
    default_group_size: Optional[int] = None,
    **kwargs: Any,
) -> Dict[str, Any]:
    """Measured per-step report of a jittable train step.

    Runs ``steps`` profiled executions (one compile, reused), joins the
    trace with the compiled HLO, and returns one flat JSON-ready dict::

        {backend, step_time_ms, flops_per_step, mfu, wire_bytes_per_step,
         wire_gbps, collective_counts, phase_ms, coverage_pct, rows}

    ``mfu`` uses ``analytic_flops_per_step`` when given (the honest
    accounting: remat recompute not credited), else the XLA cost-model
    flops. ``rows`` is the full per-op table
    (``pyprof.format_measured_table`` renders it) — pop it before sinking
    if you only want the summary line.
    """
    from apex_tpu.pyprof import measured_op_table

    measured = measured_op_table(
        fn, *args, steps=steps, depth=depth,
        peak_flops=peak_flops or 1e12, **kwargs)
    stats = hlo_stats(measured["compiled"], default_group_size)

    # wall clock, NOT the attributed-row sum: a partial trace join would
    # understate the step by 1/coverage and inflate MFU/bandwidth
    step_ms = measured.get("wall_ms_per_step") or \
        measured["total_ms_per_step"]
    step_s = step_ms / 1e3
    flops = (analytic_flops_per_step if analytic_flops_per_step is not None
             else stats["hlo_flops"])
    out: Dict[str, Any] = {
        "backend": jax.default_backend(),
        "step_time_ms": round(step_ms, 3),
        "attributed_ms": round(measured["total_ms_per_step"], 3),
        "flops_per_step": flops,
        "wire_bytes_per_step": round(stats["wire_bytes"]),
        "wire_gbps": round(stats["wire_bytes"] / step_s / 1e9, 3)
        if step_s else 0.0,
        "collective_counts": stats["collective_counts"],
        "phase_ms": {k: round(v, 3)
                     for k, v in phase_breakdown(measured).items()},
        "coverage_pct": round(measured["coverage_pct"], 1),
        "rows": measured["rows"],
        "unattributed": measured["unattributed"],
    }
    if peak_flops:
        out["mfu"] = round(flops / (step_s * peak_flops), 4) if step_s \
            else 0.0
    if analytic_flops_per_step is not None and stats["hlo_flops"]:
        out["hlo_over_analytic"] = round(
            stats["hlo_flops"] / analytic_flops_per_step, 4)
    return out


def format_step_report(rep: Dict[str, Any]) -> str:
    """Two human lines: the headline and the phase split (the per-op table
    is ``pyprof.format_measured_table``'s job)."""
    head = (f"{rep['step_time_ms']:.3f} ms/step on {rep['backend']}"
            f" | {rep['flops_per_step'] / 1e9:.1f} GFLOP/step")
    if "mfu" in rep:
        head += f" | MFU {100.0 * rep['mfu']:.1f}%"
    head += (f" | wire {rep['wire_bytes_per_step'] / 1e6:.2f} MB/step"
             f" ({rep['wire_gbps']:.2f} GB/s)")
    phases = " ".join(f"{k}={v:.3f}ms" for k, v in rep["phase_ms"].items())
    return head + f"\nphases: {phases} | trace coverage " \
                  f"{rep['coverage_pct']:.1f}%"

"""Cardinality-bounded metrics registry + exposition/aggregation plane.

Monitor tier 3's first piece. Tiers 1/2 left the repo with excellent
*instruments* (the ``Metrics`` pytree, streaming ``Histogram``\\ s, the
engine/router/membership counters) but no *naming plane*: every consumer
reads a different ad-hoc ``stats()`` dict, and nothing merges live state
across workers mid-run. This module is the naming plane:

* :class:`MetricsRegistry` — counters, gauges and histograms addressed by
  ``(name, sorted label set)``. The label space is **cardinality-bounded**
  (``max_series``): series past the bound fold into one
  ``{name}{overflow="true"}`` bucket and ``series_dropped_total`` counts
  them — a tenant-id explosion degrades one registry, never the host
  (the Prometheus operational lesson, enforced in-process).
* **exposition** — :meth:`MetricsRegistry.expose_text` renders the
  Prometheus text format (``# TYPE`` headers, ``name{label="v"} value``
  lines, cumulative ``_bucket``/``_sum``/``_count`` for histograms over
  the :class:`~apex_tpu.monitor.hist.HistSpec` edges), so any standard
  scraper can read a worker; :meth:`MetricsRegistry.snapshot` is the
  same state as one JSON-serializable dict (the in-repo wire format).
* **aggregation** — :func:`merge_snapshots` folds worker snapshots into
  one fleet view: counters sum, histograms merge (the
  :class:`~apex_tpu.monitor.hist.Histogram` associativity this was built
  for), gauges keep the freshest stamp. Because workers label their
  series (``worker="decode0"``, ``tenant="t1"``), the merged
  :class:`FleetView` holds per-worker, per-tenant AND rolled-up series
  at once — :meth:`FleetView.value` reads one, :meth:`FleetView.total`
  sums a name across labels.
* :class:`FleetScraper` — pulls every target's snapshot on the cluster
  clock, timing each pull (``scrape_ms``) and tracking **coverage** (the
  fraction of targets that answered — a dead worker is a scrape miss,
  which is itself a signal the alert engine consumes). The scraper is
  the cluster's live signal source: the
  :mod:`~apex_tpu.monitor.alerts` engine evaluates rules over its
  :class:`FleetView`, and the autoscaler acts on the firings.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, HistSpec, Histogram

__all__ = [
    "FleetScraper",
    "FleetView",
    "MetricsRegistry",
    "merge_snapshots",
]

_TYPES = ("counter", "gauge", "histogram")


def _label_key(labels: Mapping[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


@dataclasses.dataclass
class _Series:
    name: str
    kind: str                       # counter | gauge | histogram
    labels: Tuple[Tuple[str, str], ...]
    value: float = 0.0              # counter/gauge
    hist: Optional[Histogram] = None
    t_ms: float = 0.0               # last-update stamp (gauge freshness)


class MetricsRegistry:
    """One worker's named-series table. All mutators take ``**labels``;
    a series is ``(name, sorted labels)``. ``max_series`` bounds the
    table: past it, NEW label sets fold into the per-name overflow
    series (``overflow="true"``) and ``series_dropped_total`` counts the
    fold — bounded memory under label-cardinality attacks, loudly."""

    def __init__(self, max_series: int = 1024,
                 hist_spec: Optional[HistSpec] = None):
        if max_series < 1:
            raise ValueError(f"max_series must be >= 1, got {max_series}")
        self.max_series = int(max_series)
        self.hist_spec = hist_spec or DEFAULT_LATENCY_SPEC
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}
        self.series_dropped_total = 0

    # -- series resolution -------------------------------------------------
    def _get(self, name: str, kind: str,
             labels: Mapping[str, Any]) -> _Series:
        if kind not in _TYPES:
            raise ValueError(f"kind must be one of {_TYPES}, got {kind!r}")
        key = (name, _label_key(labels))
        s = self._series.get(key)
        if s is not None:
            if s.kind != kind:
                raise ValueError(
                    f"{name}: registered as {s.kind}, used as {kind}")
            return s
        if len(self._series) >= self.max_series:
            # cardinality bound: fold into the per-name overflow series
            # (which may itself need creating — allow it one slot past
            # the bound so the fold target always exists).
            # series_dropped_total counts folded WRITES; scrape-style
            # registries are rebuilt per scrape, so per-scrape it equals
            # the dropped-series count and never grows unboundedly
            self.series_dropped_total += 1
            okey = (name, (("overflow", "true"),))
            s = self._series.get(okey)
            if s is not None:
                if s.kind != kind:
                    # the overflow series enforces the same name/kind
                    # contract as the normal path
                    raise ValueError(
                        f"{name}: registered as {s.kind}, used as {kind}")
                return s
            key = okey
        s = _Series(name=name, kind=kind, labels=key[1],
                    hist=(Histogram(self.hist_spec)
                          if kind == "histogram" else None))
        self._series[key] = s
        return s

    # -- instruments -------------------------------------------------------
    def counter(self, name: str, inc: float = 1.0, **labels: Any) -> None:
        """Monotonic add (merge rule: sum)."""
        if inc < 0:
            raise ValueError(f"{name}: counters only go up, got {inc}")
        self._get(name, "counter", labels).value += float(inc)

    def gauge(self, name: str, value: float, t_ms: Optional[float] = None,
              **labels: Any) -> None:
        """Point-in-time set (merge rule: freshest ``t_ms`` wins)."""
        s = self._get(name, "gauge", labels)
        s.value = float(value)
        if t_ms is not None:
            s.t_ms = float(t_ms)

    def observe(self, name: str, values: Any, **labels: Any) -> None:
        """Fold observations into the series' streaming histogram."""
        s = self._get(name, "histogram", labels)
        assert s.hist is not None
        s.hist.add(values)

    def set_histogram(self, name: str, hist: Histogram,
                      **labels: Any) -> None:
        """Install a COPY-free snapshot reference of an existing
        histogram (the serve engine's hists are already streaming —
        re-ingesting them would double-count). Snapshot() serializes
        whatever the histogram holds at snapshot time."""
        s = self._get(name, "histogram", labels)
        s.hist = hist

    # -- readout -----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._series)

    def snapshot(self, t_ms: Optional[float] = None) -> Dict[str, Any]:
        """JSON-serializable state: the in-repo scrape wire format."""
        series = []
        for s in self._series.values():
            rec: Dict[str, Any] = {"name": s.name, "kind": s.kind,
                                   "labels": dict(s.labels)}
            if s.kind == "histogram":
                assert s.hist is not None
                rec["hist"] = s.hist.to_dict()
            else:
                rec["value"] = s.value
                if s.t_ms:
                    rec["t_ms"] = round(s.t_ms, 3)
            series.append(rec)
        return {"t_ms": (round(float(t_ms), 3) if t_ms is not None
                         else None),
                "series_dropped_total": self.series_dropped_total,
                "series": series}

    def expose_text(self) -> str:
        """Prometheus text exposition of the whole registry (one ``#
        TYPE`` header per name, histograms as cumulative ``_bucket``
        lines over the spec's finite edges plus ``_sum``/``_count``)."""
        by_name: Dict[str, List[_Series]] = {}
        for s in self._series.values():
            by_name.setdefault(s.name, []).append(s)
        lines: List[str] = []
        for name in sorted(by_name):
            group = by_name[name]
            lines.append(f"# TYPE {name} {group[0].kind}")
            for s in sorted(group, key=lambda s: s.labels):
                lbl = _fmt_labels(dict(s.labels))
                if s.kind != "histogram":
                    lines.append(f"{name}{lbl} {_fmt_value(s.value)}")
                    continue
                assert s.hist is not None
                cum = 0
                edges = s.hist.spec.edges()
                for i, c in enumerate(s.hist.counts):
                    cum += int(c)
                    le = ("+Inf" if i >= len(edges)
                          else _fmt_value(float(edges[i])))
                    lines.append(
                        f"{name}_bucket{_fmt_labels(dict(s.labels), le=le)}"
                        f" {cum}")
                lines.append(f"{name}_sum{lbl} {_fmt_value(s.hist.sum)}")
                lines.append(f"{name}_count{lbl} {s.hist.total}")
        return "\n".join(lines) + ("\n" if lines else "")


def _escape_label(v: str) -> str:
    """Prometheus text-format label escaping (backslash, quote,
    newline) — tenant ids are client-supplied, and one `"` in a label
    would invalidate the WHOLE scrape, not just its line."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, str], **extra: str) -> str:
    merged = dict(labels)
    merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


# ---------------------------------------------------------------------------
# Fleet aggregation


class FleetView:
    """A merged set of worker snapshots. Selectors:

    * :meth:`value` — one series by exact ``(name, labels)``;
    * :meth:`series` — every ``(labels, value)`` pair under a name;
    * :meth:`total` — counters/gauges under a name summed across label
      sets (the roll-up);
    * :meth:`hist` — the merged histogram under ``(name, labels)``.

    ``sources`` is the list of worker names that contributed (coverage
    accounting); a name the view has never seen reads as ``None`` —
    exactly what an absence alert rule matches on.
    """

    def __init__(self, t_ms: float, sources: List[str],
                 missed: List[str]):
        self.t_ms = float(t_ms)
        self.sources = list(sources)
        self.missed = list(missed)
        self._scalars: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            Tuple[float, float]] = {}  # (value, stamp)
        self._hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                          Histogram] = {}
        self.series_dropped_total = 0

    # -- construction (merge_snapshots fills these) ------------------------
    def _fold_scalar(self, name: str, labels: Mapping[str, str],
                     value: float, kind: str, t_ms: float) -> None:
        key = (name, _label_key(labels))
        cur = self._scalars.get(key)
        if cur is None:
            self._scalars[key] = (float(value), t_ms)
        elif kind == "counter":
            self._scalars[key] = (cur[0] + float(value), max(cur[1], t_ms))
        else:  # gauge: freshest stamp wins, ties keep the later snapshot
            if t_ms >= cur[1]:
                self._scalars[key] = (float(value), t_ms)

    def _fold_hist(self, name: str, labels: Mapping[str, str],
                   h: Histogram) -> None:
        key = (name, _label_key(labels))
        cur = self._hists.get(key)
        self._hists[key] = h if cur is None else cur.merge(h)

    # -- selectors ---------------------------------------------------------
    def value(self, name: str, **labels: Any) -> Optional[float]:
        v = self._scalars.get((name, _label_key(labels)))
        return v[0] if v is not None else None

    def series(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        return [(dict(k[1]), v[0]) for k, v in self._scalars.items()
                if k[0] == name]

    def total(self, name: str) -> Optional[float]:
        vals = [v[0] for k, v in self._scalars.items() if k[0] == name]
        return sum(vals) if vals else None

    def hist(self, name: str, **labels: Any) -> Optional[Histogram]:
        if labels:
            return self._hists.get((name, _label_key(labels)))
        merged: Optional[Histogram] = None
        for k, h in self._hists.items():
            if k[0] == name:
                merged = h if merged is None else merged.merge(h)
        return merged

    def names(self) -> List[str]:
        return sorted({k[0] for k in self._scalars}
                      | {k[0] for k in self._hists})

    def as_dict(self) -> Dict[str, Any]:
        """Flat JSON-serializable roll-up (scalar totals per name +
        hist quantiles) — the shape ``json_record``/regress consume."""
        out: Dict[str, Any] = {"sources": self.sources,
                               "missed": self.missed}
        for name in sorted({k[0] for k in self._scalars}):
            out[name] = self.total(name)
        for name in sorted({k[0] for k in self._hists}):
            h = self.hist(name)
            if h is not None and h.total:
                out[f"{name}_p50"] = round(h.quantile(0.5), 4)
                out[f"{name}_p99"] = round(h.quantile(0.99), 4)
        return out


def merge_snapshots(snapshots: Iterable[Tuple[str, Mapping[str, Any]]],
                    t_ms: float = 0.0,
                    missed: Optional[List[str]] = None) -> FleetView:
    """Fold ``(worker, snapshot)`` pairs into one :class:`FleetView`.
    Counters with identical ``(name, labels)`` sum, histograms merge
    (associative — order-independent by construction), gauges keep the
    freshest ``t_ms``. Workers normally label their series with their
    own name, so cross-worker collisions only happen where summing is
    the right semantics (the roll-up series)."""
    pairs = list(snapshots)
    view = FleetView(t_ms, sources=[w for w, _ in pairs],
                     missed=list(missed or []))
    for _, snap in pairs:
        view.series_dropped_total += int(
            snap.get("series_dropped_total", 0))
        stamp = float(snap.get("t_ms") or 0.0)
        for rec in snap.get("series", []):
            labels = rec.get("labels", {})
            if rec["kind"] == "histogram":
                view._fold_hist(rec["name"], labels,
                                Histogram.from_dict(rec["hist"]))
            else:
                view._fold_scalar(rec["name"], labels,
                                  float(rec["value"]), rec["kind"],
                                  float(rec.get("t_ms") or stamp))
    return view


# ---------------------------------------------------------------------------
# FleetScraper — pull worker snapshots on the cluster clock


class FleetScraper:
    """Scrapes a dynamic target set into one :class:`FleetView`.

    ``targets``: zero-arg callable returning the LIVE ``[(name,
    scrape_fn)]`` list (the cluster passes its alive-worker view, so the
    dispatch set and the scrape set stay one thing). A target whose
    ``scrape_fn`` raises (or returns None) is a MISS — it stays out of
    the view, drags ``scrape_coverage`` below 1.0, and its name lands in
    ``view.missed`` (what a heartbeat-absence rule reads). Each pull is
    wall-timed into the ``scrape_ms`` histogram — the observability
    plane measures itself, and ``bench_observe.py`` gates the cost."""

    def __init__(self, targets: Callable[[], List[Tuple[str, Callable]]],
                 clock: Optional[Callable[[], float]] = None):
        self._targets = targets
        self._clock = clock
        self.scrapes_total = 0
        self.scrape_misses_total = 0
        self.scrape_ms_hist = Histogram(DEFAULT_LATENCY_SPEC)
        self.last_view: Optional[FleetView] = None
        self.last_coverage: Optional[float] = None

    def scrape(self, t_ms: Optional[float] = None) -> FleetView:
        if t_ms is None:
            t_ms = self._clock() if self._clock is not None else 0.0
        got: List[Tuple[str, Mapping[str, Any]]] = []
        missed: List[str] = []
        t0 = time.perf_counter()
        for name, fn in self._targets():
            try:
                snap = fn()
            # a scrape must never take the scraper down: ANY failing
            # target is a miss (that is the coverage signal)
            except Exception:
                snap = None
            if snap is None:
                missed.append(name)
                self.scrape_misses_total += 1
            else:
                got.append((name, snap))
        self.scrape_ms_hist.add([(time.perf_counter() - t0) * 1e3])
        self.scrapes_total += 1
        view = merge_snapshots(got, t_ms=t_ms, missed=missed)
        n = len(got) + len(missed)
        self.last_coverage = (len(got) / n) if n else None
        self.last_view = view
        return view

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "scrapes_total": self.scrapes_total,
            "scrape_misses_total": self.scrape_misses_total,
            "scrape_coverage": self.last_coverage,
        }
        h = self.scrape_ms_hist
        if h.total:
            out["scrape_ms_p50"] = round(h.quantile(0.5), 4)
            out["scrape_ms_p99"] = round(h.quantile(0.99), 4)
        return out

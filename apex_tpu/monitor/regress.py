"""Baseline comparison for bench records — flag metric regressions.

The banked-artifact discipline (``BENCH_r0*.json``, ``SERVE_TPU.json``,
``tpu_watch.sh`` promotion rules) gives every bench a durable last-good
record; this module closes the loop by DIFFING a fresh record against the
banked one so a perf regression fails loudly at bench time instead of
surfacing rounds later in a human's spreadsheet:

* :func:`load_record` — reads a record file in any of the repo's shapes:
  one JSON object, a JSONL file (last parseable line wins — the sink
  convention), or the ``BENCH_r0*.json`` wrapper whose payload sits under
  ``"parsed"``.
* :func:`compare_records` — walks the two records' shared numeric fields
  (nested dicts flattened to dotted keys), classifies each as
  higher-better (throughput/goodput/MFU/occupancy) or lower-better
  (latency ``*_ms*``, violation counts) by name — unclassifiable keys are
  skipped, never guessed — and flags changes beyond ``tol`` in the bad
  direction. Returns a JSON-serializable report.
* CLI: ``python -m apex_tpu.monitor.regress BASELINE NEW [--tol 0.1]`` —
  table to stderr, one ``json_record`` line to stdout, exit 1 on
  regression (the ``tpu_watch.sh`` stage-10 gate; CPU-rehearsal records
  are refused by the caller before this ever runs).
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict, List, Mapping, Optional, Tuple

from apex_tpu.monitor.sink import json_record

__all__ = ["classify_metric", "compare_records", "flatten_record",
           "load_record", "main"]

# name fragments that decide polarity; first match wins, explicit rules
# override. Conservative on purpose: a key matching neither is SKIPPED.
_HIGHER = ("tokens_per_s", "goodput", "_rps", "mfu", "occupancy",
           "throughput", "hidden_fraction", "good_fraction",
           # serve throughput tier 2: a collapsing prefix-cache hit rate
           # or draft acceptance rate is a regression (stage-11 gate)
           "hit_rate", "acceptance_rate",
           # megakernel A/B: the fused-vs-per-op decode-step ratio is the
           # stage-12 headline — a shrinking speedup is a regression
           "speedup",
           # FSDP round: hidden ring bytes + the modeled HBM drop factor
           # (checked BEFORE _LOWER, so these never fall into the generic
           # *_bytes lower-is-better rules below)
           "hidden_bytes", "hbm_reduction",
           # disaggregated cluster (stage 15): admitted requests/s is the
           # router headline — already matched by "_rps", listed so the
           # gate's coverage is explicit next to its shed_rate dual
           "admitted_rps",
           # sub-8-bit round (stage 17): concurrent contexts a fixed KV
           # budget serves — the int4-KV headline (halving pool bytes
           # must double it; a drop is a capacity regression)
           "contexts_max",
           # elastic/chaos round (stage 18): the goodput the cluster
           # keeps while a worker dies mid-run, and the good-SLO
           # fraction of the surviving traffic (both already matched by
           # the generic goodput/good_fraction fragments — listed so
           # the chaos gate's coverage is explicit next to its
           # lower-is-better duals below)
           "goodput_under_chaos_rps", "survivor_good_fraction",
           # fleet observability round (stage 19): the fraction of
           # workers the FleetScraper reached (a scrape hole is a blind
           # spot) and the fleet-wide goodput roll-up (already matched
           # by the goodput fragment; listed for explicit coverage)
           "scrape_coverage", "fleet_goodput_rps",
           # per-tenant LoRA round (stage 20): registry hit rate
           # (already matched by the generic hit_rate fragment; listed
           # for explicit coverage) and the fraction of adapter-bound
           # handoffs the router landed adapter-warm — a falling warm
           # rate means the fleet-mix placement stopped working
           "adapter_hit_rate", "adapter_warm_dispatch_rate",
           # performance-forensics round (stage 21): the fraction of
           # retired requests the attribution plane decomposed with the
           # sum identity intact, and the fraction the meter charged —
           # a coverage hole is a blind billing/diagnosis spot
           "attrib_coverage", "meter_coverage",
           # megakernel tier-2 round (stage 23): the speculative-decode
           # draft acceptance rate at the fused verify step (already
           # matched by the generic acceptance_rate fragment; listed so
           # the verify A/B gate's coverage is explicit next to its
           # verify_step_ms dual in _LOWER)
           "spec_acceptance_rate")
_LOWER = ("_ms", "violation", "latency", "bubble", "exposed_bytes",
          # disaggregated cluster (stage 15): a rising shed fraction is a
          # capacity regression (transfer_ms falls under the generic
          # "_ms" rule; listed here for the same explicitness)
          "shed_rate", "transfer_ms",
          # FSDP round: the headline memory/wire accounting — growing
          # per-chip param HBM, peak HBM or FSDP bytes-on-wire is a
          # regression (hidden_fraction, the overlap headline, is in
          # _HIGHER; wire_bytes_fsdp only — the generic "wire_bytes"
          # fragment would also gate baseline-side columns like
          # bench_overlap's wire_bytes_off, where only the ratio matters)
          "hbm_params_bytes", "peak_hbm_bytes", "wire_bytes_fsdp",
          # analyze round (stage 16): the contract-checker record fields —
          # growing exposed collective traffic (exposed_bytes above),
          # f32↔bf16 convert round-trips, host syncs reachable from a
          # step, or new lint violations are all regressions
          "convert_churn", "host_syncs", "lint_violations",
          "fp32_dots", "donated_copied",
          # sub-8-bit round (stage 17): bits per cached KV element and
          # the int4 wire-byte column (scoped like wire_bytes_fsdp — the
          # generic "wire_bytes" fragment would gate baseline columns);
          # a rising fp8 cast-saturation fraction means the delayed
          # scales stopped tracking the dynamic range
          "kv_bits", "wire_bytes_int4", "fp8_overflow_rate",
          # elastic/chaos round (stage 18): more migrations, replayed
          # tokens, worker deaths, heartbeat misses or transfer retries
          # under the SAME deterministic chaos plan means the cluster
          # got less stable (a retry storm, flappier membership) — all
          # lower-is-better
          "migrations_total", "replayed_tokens", "worker_deaths",
          "heartbeat_misses", "transfer_retries",
          # fleet observability round (stage 19): more alert firings
          # under the same plan means a flappier fleet, scrape_ms is the
          # cost of the scrape itself (also caught by the generic "_ms"
          # rule; listed so the gate's coverage is explicit), and a
          # trace that stopped stitching across hosts is broken
          # observability, not a style issue
          "alerts_fired_total", "scrape_ms", "trace_stitch_failures",
          "series_dropped_total", "scrape_misses", "dropped_records",
          # per-tenant LoRA round (stage 20): time spent installing
          # adapters into pools (also caught by the generic "_ms" rule;
          # listed for explicit coverage) and LRU eviction churn — more
          # evictions under the same tenant mix means the pool is
          # thrashing
          "adapter_load_ms", "adapter_evictions",
          # performance-forensics round (stage 21): per-component
          # latency attribution (also caught by the generic "_ms" rule;
          # listed so the diagnosis fields' coverage is explicit), the
          # per-tenant billing headline rates, and the trend gate's own
          # drift score — a rising score means the longitudinal series
          # is walking away from its history
          "_component_ms", "cost_per_token", "cost_per_request",
          "drift_score",
          # elastic-training round (stage 22): reshard arithmetic time
          # (also caught by the generic "_ms" rule; listed so the elastic
          # gate's coverage is explicit), SDC disagreements and straggler
          # flags under the SAME deterministic chaos plan (more means the
          # sentinels got noisier or the fleet sicker), and step retries
          # (a retry storm is a regression even when every retry
          # eventually succeeds). elastic_resumes_total is deliberately
          # NOT listed: how many times a run resumed at a new topology is
          # the scheduler's business, informational either way
          "reshard_ms", "sdc_disagreements_total",
          "straggler_flags_total", "retries_total",
          # megakernel tier-2 round (stage 23): the fused-vs-unfused
          # decode/verify step latencies (also caught by the generic
          # "_ms" rule; listed so the verify A/B gate's coverage is
          # explicit — these are the headline quantiles the stage banks)
          "verify_step_ms", "decode_step_ms",
          # plan-sharded serving round (stage 24): per-layer weight
          # gather latency and the PP stage-idle fraction (both also
          # caught by the generic "_ms"/"bubble" rules; listed so the
          # serve-plan gate's coverage is explicit), and the modeled
          # model-residency bytes — a growing footprint for the same
          # checkpoint means the residency accounting (or the plan's
          # shard math) regressed; hbm_chip_bytes is the per-chip
          # residency the budget headline compares against
          "weight_gather_ms", "pp_bubble_fraction", "hbm_model_bytes",
          "hbm_chip_bytes")


def classify_metric(key: str,
                    rules: Optional[Mapping[str, str]] = None
                    ) -> Optional[str]:
    """'higher' | 'lower' | None (skip) for a flattened record key."""
    if rules:
        for pat, direction in rules.items():
            if pat in key:
                return direction
    low = key.lower()
    if any(t in low for t in _HIGHER):
        return "higher"
    if any(t in low for t in _LOWER):
        return "lower"
    return None


def flatten_record(rec: Mapping[str, Any], prefix: str = ""
                   ) -> Dict[str, float]:
    """Dotted-key flattening of a record's numeric fields (bools and
    non-numeric leaves dropped; histogram dumps skipped entirely — their
    count/sum/min would otherwise classify as '_ms' latencies through the
    dotted key and flag a fuller run as a regression; the quantile
    summaries are the comparable surface)."""
    out: Dict[str, float] = {}
    for k, v in rec.items():
        key = f"{prefix}{k}"
        if k in ("schema", "ts", "buckets", "spec", "config", "hists",
                 "provenance"):
            continue
        if isinstance(v, Mapping):
            if "buckets" in v and "spec" in v:
                continue  # an embedded Histogram.to_dict, wherever it sits
            out.update(flatten_record(v, prefix=f"{key}."))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)) and math.isfinite(v):
            out[key] = float(v)
    return out


def compare_records(baseline: Mapping[str, Any], new: Mapping[str, Any],
                    tol: float = 0.1,
                    rules: Optional[Mapping[str, str]] = None
                    ) -> Dict[str, Any]:
    """Diff two bench records. A key regresses when it moves beyond
    ``tol`` (relative) in its bad direction; a zero baseline regresses on
    ANY bad-direction move (violation counts: 0 → n must flag). Returns
    ``{ok, compared, regressions: [...], improvements: [...]}``."""
    fb, fn = flatten_record(baseline), flatten_record(new)
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    compared = 0
    for key in sorted(set(fb) & set(fn)):
        direction = classify_metric(key, rules)
        if direction is None:
            continue
        b, n = fb[key], fn[key]
        compared += 1
        if b == n:
            continue
        worse = n < b if direction == "higher" else n > b
        if b == 0.0:
            delta = math.inf if n > 0 else -math.inf
        else:
            delta = (n - b) / abs(b)
        entry = {"key": key, "baseline": b, "new": n,
                 "delta_pct": (round(delta * 100, 2)
                               if math.isfinite(delta) else None),
                 "direction": direction}
        if worse and (not math.isfinite(delta) or abs(delta) > tol):
            regressions.append(entry)
        elif not worse and (not math.isfinite(delta) or abs(delta) > tol):
            improvements.append(entry)
    return {"ok": not regressions, "compared": compared, "tol": tol,
            "regressions": regressions, "improvements": improvements}


def load_record(path: str) -> Dict[str, Any]:
    """Load a bench record: whole-file JSON, else JSONL (last parseable
    line). A ``BENCH_r0*.json``-style wrapper unwraps to its ``parsed``
    payload."""
    with open(path) as f:
        text = f.read()
    try:
        rec = json.loads(text)
    except json.JSONDecodeError:
        rec = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
        if rec is None:
            raise ValueError(f"{path}: no parseable JSON record")
    if isinstance(rec, dict) and isinstance(rec.get("parsed"), dict):
        rec = rec["parsed"]
    if not isinstance(rec, dict):
        raise ValueError(f"{path}: record is not a JSON object")
    return rec


def _format_rows(entries: List[Dict[str, Any]], label: str) -> List[str]:
    lines = []
    for e in entries:
        d = (f"{e['delta_pct']:+.1f}%" if e["delta_pct"] is not None
             else "from 0")
        lines.append(f"  {label} {e['key']}: {e['baseline']:g} -> "
                     f"{e['new']:g} ({d}, {e['direction']}-better)")
    return lines


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="flag metric regressions between two bench records")
    ap.add_argument("baseline")
    ap.add_argument("new")
    ap.add_argument("--tol", type=float, default=0.1,
                    help="relative tolerance before flagging (default 0.1)")
    args = ap.parse_args(argv)
    report = compare_records(load_record(args.baseline),
                             load_record(args.new), tol=args.tol)
    print(f"compared {report['compared']} metrics "
          f"(tol {args.tol:.0%}): "
          f"{len(report['regressions'])} regressions, "
          f"{len(report['improvements'])} improvements", file=sys.stderr)
    for line in _format_rows(report["regressions"], "REGRESSED"):
        print(line, file=sys.stderr)
    for line in _format_rows(report["improvements"], "improved"):
        print(line, file=sys.stderr)
    print(json_record(metric="regress_report", baseline=args.baseline,
                      new=args.new, **report), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

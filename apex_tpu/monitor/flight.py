"""Flight recorder — a bounded ring of recent telemetry, dumped on death.

The chaos harness (PR 13) exposed the tier-2 blind spot: when a worker
is killed its JSONL tail may still sit in the sink buffer, and the
cluster-level log says WHAT died but not what the dying worker saw in
its last seconds. This module is the black box:

* :class:`FlightRecorder` — a fixed-capacity in-memory ring of the most
  recent records (events, step records, gauges — anything
  ``write(**fields)``-shaped; it duck-types the
  :class:`~apex_tpu.monitor.sink.JsonlSink` protocol so it can sit
  anywhere a sink does, forwarding to an ``inner`` sink when given).
  O(capacity) memory forever; ``dropped_records`` counts what the ring
  forgot.
* **atomic dump** — :meth:`FlightRecorder.dump` publishes the ring as
  one JSON file with the ``resilience.checkpoint`` discipline: write to
  a ``.tmp.<pid>`` sibling, fsync, ``os.replace`` — a crash mid-dump
  leaves either nothing or a complete file, never a torn one (the same
  reason a torn checkpoint never binds). Dumps carry the worker name,
  the dump reason (``killed`` / ``stall`` / ``alert:<rule>`` / manual)
  and the shared-clock stamp, so ``postmortem`` can order them.
* the cluster arms one recorder per worker plus a cluster-scope ring,
  and dumps on chaos kill, StallWatchdog fire and page-severity alert
  escalation — ``python -m apex_tpu.monitor.postmortem DIR`` then
  rebuilds the merged pre-failure timeline from the dumps alone.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Mapping, Optional

__all__ = ["FlightRecorder", "load_dump", "load_dumps"]

DUMP_SCHEMA = 1
DUMP_PREFIX = "flight-"


class FlightRecorder:
    """Bounded ring of recent records; sink-protocol compatible.

    ``inner``: an optional downstream sink every record is forwarded to
    (the ring observes, it never swallows). ``worker`` names the ring in
    dumps; ``clock`` (ms) stamps dumps on the cluster's shared clock."""

    def __init__(self, capacity: int = 2048, worker: str = "worker",
                 inner: Any = None,
                 clock: Optional[Any] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.worker = worker
        self._inner = inner
        self._clock = clock
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.records_total = 0
        self.dumps_total = 0

    # -- sink protocol -----------------------------------------------------
    def write(self, step: Optional[int] = None, metrics: Any = None,
              **extra: Any) -> None:
        rec: Dict[str, Any] = {}
        if step is not None:
            rec["step"] = int(step)
        # stamp the shared clock: postmortem's merged timeline sorts by
        # t_ms, and a step record without one would sort to t=0 — the
        # head of a timeline it belongs at the tail of
        if self._clock is not None and "t_ms" not in extra:
            rec["t_ms"] = round(float(self._clock()), 3)
        if metrics is not None:
            # defer materialization: reading a Metrics pytree is a
            # device transfer, and the ring must stay off the step's
            # critical path — the object rides the ring and is read out
            # only if this record survives to a dump (the inner sink
            # makes its own read, exactly as without the ring)
            rec["_metrics"] = metrics
        rec.update(extra)
        self.record(rec)
        if self._inner is not None:
            self._inner.write(step=step, metrics=metrics, **extra)

    @staticmethod
    def _materialize(rec: Dict[str, Any]) -> Dict[str, Any]:
        m = rec.get("_metrics")
        if m is None:
            return dict(rec)
        out = {k: v for k, v in rec.items() if k != "_metrics"}
        vals = m.as_dict() if hasattr(m, "as_dict") else dict(m)
        for k, v in vals.items():
            out.setdefault(k, float(v) if hasattr(v, "__float__") else v)
        return out

    def flush(self) -> None:
        if self._inner is not None:
            self._inner.flush()

    def record(self, rec: Mapping[str, Any]) -> None:
        """Ring one already-shaped record (the EventLog tap path)."""
        self._ring.append(dict(rec))
        self.records_total += 1

    # -- readout -----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped_records(self) -> int:
        return self.records_total - len(self._ring)

    def records(self) -> List[Dict[str, Any]]:
        return [self._materialize(r) for r in self._ring]

    # -- the dump ----------------------------------------------------------
    def dump(self, directory: str, reason: str = "manual",
             t_ms: Optional[float] = None) -> str:
        """Atomically publish the ring into ``directory`` as
        ``flight-<worker>-<n>.json``; returns the path. Atomic the
        checkpoint way: a complete ``.tmp.<pid>`` sibling is fsynced,
        then ONE ``os.replace`` publishes — the postmortem reader never
        sees a torn dump. The ring is NOT cleared: a later escalation
        re-dumps the fuller window under the next index."""
        os.makedirs(directory, exist_ok=True)
        if t_ms is None:
            t_ms = self._clock() if self._clock is not None else 0.0
        self.dumps_total += 1
        payload = {
            "schema": DUMP_SCHEMA,
            "worker": self.worker,
            "reason": reason,
            "t_dump_ms": round(float(t_ms), 3),
            "wall_ts": round(time.time(), 3),
            "capacity": self.capacity,
            "records_total": self.records_total,
            "dropped_records": self.dropped_records,
            "records": self.records(),
        }
        final = os.path.join(
            directory, f"{DUMP_PREFIX}{self.worker}-{self.dumps_total}.json")
        tmp = f"{final}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        return final

    def dump_to_sink(self, sink: Any, reason: str = "manual",
                     t_ms: Optional[float] = None) -> int:
        """Stream the ring into a shared :class:`~apex_tpu.monitor.sink.
        JsonlSink` as ONE contiguous batch (``write_many`` — lock-scoped,
        so a concurrent step-record writer can neither interleave the
        batch nor split a record across a rotation boundary). The
        no-filesystem dump path: when a cluster has a durable log but no
        flight directory, the black box lands in the log itself, fenced
        by a header record. Returns the number of records written."""
        if t_ms is None:
            t_ms = self._clock() if self._clock is not None else 0.0
        self.dumps_total += 1
        # every dumped record is MARKED: most of a ring's contents were
        # already written live to the same log, and an unmarked copy
        # would double-count steps/gauges/events in every reader —
        # view/chrome_trace skip flight_worker-tagged records, humans
        # grep the fenced window
        records = [{**r, "flight_worker": self.worker}
                   for r in self.records()]
        header = {"kind": "flight_dump_header", "worker": self.worker,
                  "reason": reason, "t_dump_ms": round(float(t_ms), 3),
                  "n_records": len(records),
                  "dropped_records": self.dropped_records}
        sink.write_many([header] + records)
        return len(records)


def load_dump(path: str) -> Dict[str, Any]:
    """Read one flight dump (raises on schema mismatch — a reader from
    before the ring format would otherwise misparse silently)."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("schema") != DUMP_SCHEMA:
        raise ValueError(
            f"{path}: flight-dump schema {payload.get('schema')!r} != "
            f"{DUMP_SCHEMA}")
    return payload


def load_dumps(directory: str) -> List[Dict[str, Any]]:
    """Every complete ``flight-*.json`` under ``directory``, dump-time
    ordered. ``.tmp.*`` staging leftovers (a dumper died mid-write) are
    skipped — the atomic-publish contract means they are never valid."""
    out = []
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return []
    for name in names:
        if (not name.startswith(DUMP_PREFIX)
                or not name.endswith(".json")):
            continue
        out.append(load_dump(os.path.join(directory, name)))
    out.sort(key=lambda d: d["t_dump_ms"])
    return out

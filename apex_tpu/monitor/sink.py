"""Process-0-gated JSONL sink — the durable end of the telemetry pipe.

One record per train step, one JSON object per line, appended to a file.
The format choices are all crash-shaped:

* **versioned schema** — every record carries ``"schema": SCHEMA_VERSION``
  so a reader of mixed-age logs can dispatch; bench scripts share the same
  convention via :func:`json_record` (the one-JSON-line contract
  ``bench.py`` / ``benchmarks/bench_comm.py`` print).
* **buffered flush** — records buffer host-side and flush every
  ``buffer_steps`` (or on ``close``/``__exit__``), so the sink never adds a
  filesystem write to the step's critical path.
* **crash-safe append** — the file is opened in append mode and every flush
  writes whole ``\\n``-terminated lines; a crash can truncate at most the
  final line, which :func:`read_jsonl` skips, and a restarted job reopens
  the same path and appends (guarded by ``tests/test_monitor.py``).
* **process-0 gating** — under multi-process (``jax.distributed``) only
  process 0 writes; every other process's sink is a no-op, so the call
  sites stay SPMD-uniform.
* **exit flush** — buffered lines survive a normal interpreter exit and
  the resilience preemption path even when the caller forgot
  ``close()``/``with``: every enabled sink registers an ``atexit`` flush
  fallback (unregistered again on ``close`` so a well-behaved caller pays
  nothing at exit). Short runs and preempted runs keep their tail.
* **size-based rotation** — with ``rotate_bytes=N`` a flush that carries
  the file past N rolls it to ``<path>.1``, ``.2``, … (creation order:
  ``.1`` oldest — segments are immutable once rolled, no cascade renames)
  under the same lock; :func:`read_jsonl` iterates rotated segments in
  order transparently. Week-long serve runs stop producing one unbounded
  file; rotation only ever happens between whole records.

Human-readable mirror: with ``log_every=N`` the sink also logs a one-line
summary of every Nth record through the ``apex_tpu.monitor.metrics`` child
logger (``get_logger("apex_tpu.monitor").metrics`` — rank-prefixed like all
apex_tpu logs, see ``apex_tpu/_logging.py``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Mapping, Optional

SCHEMA_VERSION = 1

# process-wide provenance stamp (tier 4): when set, every json_record
# line carries it under "provenance" — the trend history is useless
# without knowing what changed between points. None (the default) keeps
# records byte-for-byte identical to the pre-provenance format.
_PROVENANCE: Optional[Dict[str, Any]] = None


def collect_provenance(extra: Optional[Mapping[str, Any]] = None
                       ) -> Dict[str, Any]:
    """Best-effort provenance for bench records: git sha, jax version,
    backend, hostname. Never raises, never initializes jax — the backend
    field appears only when the caller already imported jax (a bench),
    so tooling CLIs (trend append) don't grab a TPU just to stamp a
    line."""
    prov: Dict[str, Any] = {}
    try:
        import socket

        prov["hostname"] = socket.gethostname()
    except Exception:  # best-effort stamp: no hostname beats no record
        pass
    try:
        import subprocess

        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=repo,
                             capture_output=True, text=True, timeout=5)
        if out.returncode == 0 and out.stdout.strip():
            prov["git_sha"] = out.stdout.strip()
    except Exception:  # no git / not a checkout — stamp without a sha
        pass
    if "jax" in sys.modules:
        try:
            import jax

            prov["jax_version"] = jax.__version__
            prov["backend"] = jax.default_backend()
        except Exception:  # backend probe must never kill a bench record
            pass
    else:
        try:
            from importlib.metadata import version

            prov["jax_version"] = version("jax")
        except Exception:  # jax not installed — version stays unstamped
            pass
    if extra:
        prov.update(extra)
    return prov


def set_provenance(prov: Optional[Mapping[str, Any]]) -> None:
    """Install (or clear, with ``None``) the process-wide provenance
    stamp attached to every subsequent :func:`json_record` line."""
    global _PROVENANCE
    _PROVENANCE = dict(prov) if prov else None


def _is_process_zero() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # jax not initialized — single-process tooling
        return True


def json_record(**fields: Any) -> str:
    """Render one schema-stamped JSON line (no trailing newline) — the
    shared convention for sink records AND bench one-liners, so every
    emitter in the repo is parseable by the same reader. When a
    process-wide provenance stamp is set (:func:`set_provenance`), it
    rides under ``"provenance"`` (explicit fields win); records emitted
    without one are byte-for-byte the pre-provenance format."""
    rec: Dict[str, Any] = {"schema": SCHEMA_VERSION}
    rec.update(fields)
    if _PROVENANCE is not None and "provenance" not in rec:
        rec["provenance"] = _PROVENANCE
    return json.dumps(rec)


class JsonlSink:
    """Append-only JSONL metrics sink. Typical loop::

        sink = JsonlSink("metrics.jsonl", log_every=100)
        for step in range(n):
            state, metrics = train_step(state, batch)   # Metrics pytree out
            sink.write(step=step, metrics=metrics, **host_side_fields)
        sink.close()                                    # or `with` block

    ``metrics`` may be an :class:`apex_tpu.monitor.Metrics` (read out with
    one device transfer) or a plain dict of floats; ``extra`` fields must be
    JSON-serializable. ``fsync=True`` additionally fsyncs on every flush
    (true crash-safety at the cost of an IO stall per flush).
    """

    def __init__(
        self,
        path: str,
        buffer_steps: int = 16,
        process0_only: bool = True,
        fsync: bool = False,
        log_every: int = 0,
        rotate_bytes: Optional[int] = None,
    ):
        self.path = path
        self.buffer_steps = max(1, int(buffer_steps))
        self.fsync = fsync
        self.log_every = int(log_every)
        if rotate_bytes is not None and rotate_bytes <= 0:
            raise ValueError(
                f"rotate_bytes must be positive, got {rotate_bytes}")
        self.rotate_bytes = rotate_bytes
        self.enabled = _is_process_zero() if process0_only else True
        self._buf: List[str] = []
        self._file = None
        self._logger = None
        # write/flush are lock-guarded: background writers (the resilience
        # CheckpointManager's async worker, the stall watchdog) share one
        # sink with the train loop
        self._iolock = threading.Lock()
        self._atexit_registered = False
        if self.enabled:
            import atexit

            # fallback only: close() unregisters, so the common with-block
            # path never reaches it; a run killed by sys.exit/atexit (the
            # preemption save-and-exit path included) still flushes its tail
            atexit.register(self.close)
            self._atexit_registered = True

    # -- write path --------------------------------------------------------
    def write(self, step: Optional[int] = None, metrics: Any = None,
              **extra: Any) -> None:
        """Buffer one record ``{schema, ts, step, **metrics, **extra}``."""
        if not self.enabled:
            return
        fields: Dict[str, Any] = {"ts": round(time.time(), 3)}
        if step is not None:
            fields["step"] = int(step)
        if metrics is not None:
            vals = metrics.as_dict() if hasattr(metrics, "as_dict") \
                else dict(metrics)
            fields.update(vals)
        fields.update(extra)
        line = json_record(**fields)
        with self._iolock:
            self._buf.append(line)
            if len(self._buf) >= self.buffer_steps:
                self._flush_locked()
        if self.log_every and step is not None and step % self.log_every == 0:
            self._log_line(fields)

    def write_many(self, records: "List[Dict[str, Any]]") -> None:
        """Append a BATCH of records contiguously — one lock scope, one
        flush. The flight-recorder dump path needs this: a ring dumped
        record-by-record from another thread could interleave with the
        step loop's writes and have its records split across a rotation
        boundary mid-batch. Here the whole batch lands in one buffered
        flush, so every record is whole, the batch is contiguous in the
        stream, and rotation (which only ever runs AFTER a whole-line
        flush, under the same lock) can only happen between batches."""
        if not self.enabled or not records:
            return
        ts = round(time.time(), 3)
        lines = [json_record(**{"ts": ts, **r}) for r in records]
        with self._iolock:
            self._buf.extend(lines)
            self._flush_locked()

    def flush(self) -> None:
        """Write buffered records as whole lines and flush the OS buffer."""
        with self._iolock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        if self._file is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            # append-after-crash: a previous writer may have died mid-line;
            # terminate the partial record so new records start on a fresh
            # line (readers skip the malformed fragment)
            dangling = False
            if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
                with open(self.path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    dangling = rf.read(1) != b"\n"
            self._file = open(self.path, "a")
            if dangling:
                self._file.write("\n")
        self._file.write("".join(line + "\n" for line in self._buf))
        self._buf.clear()
        self._file.flush()
        if self.fsync:
            os.fsync(self._file.fileno())
        # size-based rotation: roll AFTER a whole-line flush so segments
        # always end on record boundaries; the next flush reopens path.
        # Roll to max(existing index)+1, NOT the first free slot — if an
        # operator deleted old segments to reclaim disk, reusing a freed
        # low index would file the NEWEST records under the oldest-read
        # name and scramble chronological iteration
        if (self.rotate_bytes is not None
                and self._file.tell() >= self.rotate_bytes):
            self._file.close()
            self._file = None
            indices = _segment_indices(self.path)
            k = (indices[-1] + 1) if indices else 1
            os.replace(self.path, f"{self.path}.{k}")

    def close(self) -> None:
        with self._iolock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None
        if self._atexit_registered:
            import atexit

            atexit.unregister(self.close)
            self._atexit_registered = False

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- human-readable mirror ---------------------------------------------
    def _log_line(self, fields: Dict[str, Any]) -> None:
        if self._logger is None:
            import logging

            from apex_tpu._logging import get_logger

            self._logger = get_logger("apex_tpu.monitor").metrics
            # log_every is an explicit opt-in: raise only THIS child to
            # INFO if the hierarchy's default (WARNING) would swallow the
            # lines the caller just asked for
            if not self._logger.isEnabledFor(logging.INFO):
                self._logger.setLevel(logging.INFO)
        parts = [f"step {fields.get('step', '?')}"]
        for k, v in fields.items():
            if k in ("schema", "ts", "step"):
                continue
            parts.append(f"{k}={v:.6g}" if isinstance(v, float) else
                         f"{k}={v}")
        self._logger.info(" ".join(parts))


def _segment_indices(path: str) -> List[int]:
    """Sorted numeric suffixes of a sink's rotated segments on disk
    (gap-tolerant: operators may delete old segments to reclaim space)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path) + "."
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return []
    return sorted(int(f[len(base):]) for f in names
                  if f.startswith(base) and f[len(base):].isdigit())


def rotated_segments(path: str) -> List[str]:
    """The on-disk segments of a possibly-rotated sink, oldest first:
    ``path.1``, ``path.2``, …, then ``path`` itself (segments are numbered
    in creation order, so sort-by-index is chronological even when old
    segments have been deleted)."""
    segs = [f"{path}.{k}" for k in _segment_indices(path)]
    if os.path.exists(path):
        segs.append(path)
    return segs


def read_jsonl(path: str, strict: bool = False,
               rotated: bool = True) -> Iterator[Dict[str, Any]]:
    """Yield records from a JSONL file, streaming (constant memory — the
    file is one line per train step of a possibly very long run). Malformed
    lines — the truncated final line of a crashed writer, or an interior
    fragment such a writer left behind before a restart terminated it — are
    skipped; pass ``strict=True`` to raise on any malformed INTERIOR line
    instead (a trailing partial line is always tolerated: it is the
    expected crash artifact, not corruption). A rotated sink's segments
    (``path.1``, ``.2``, …) are iterated in order before ``path`` unless
    ``rotated=False``."""
    paths = rotated_segments(path) if rotated else [path]
    if not paths:
        paths = [path]  # surface the FileNotFoundError the caller expects
    for p in paths:
        with open(p) as f:
            for raw in f:
                # a line still carrying its newline is complete wherever it
                # sits; only a newline-less final read is a crash tail
                interior = raw.endswith("\n")
                line = raw.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    if strict and interior:
                        raise

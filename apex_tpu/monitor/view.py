"""``python -m apex_tpu.monitor.view FILE.jsonl`` — latency/SLO summary.

The one-command read of a serve telemetry log (step records, lifecycle
events and gauges share one JSONL file — ``view`` partitions by the
``kind`` field). Human table to **stderr**, one machine-readable
``json_record`` line to **stdout** — the bench.py pipe convention, so
``tpu_watch.sh`` and humans read the same invocation.

Per-request latencies are reconstructed from the lifecycle events
(``submitted → admitted → first_token → retired``); pass SLO budgets
(``--ttft-budget`` / ``--tpot-budget`` / ``--queue-budget`` /
``--e2e-budget``, ms) to get goodput/violation accounting through
:class:`~apex_tpu.monitor.slo.SloTracker` on the same records. Rotated
sinks (``FILE.jsonl.1`` …) are read transparently via ``read_jsonl``.

Tier 4: logs whose lifecycle carries prefill/transfer anchors
additionally get the per-component **latency attribution** table
(queue/prefill/transfer/decode/stall p50/p99 via
:func:`~apex_tpu.monitor.attrib.attribution_summary`) and a per-tenant
rollup (requests / tokens / per-component time totals — "who consumed
the fleet's time" straight from the event stream, no meter required);
``--baseline OTHER.jsonl`` diffs the two logs through
:func:`~apex_tpu.monitor.attrib.explain_regression` and names the top-3
regressed components — the diagnosis, not just the verdict.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

__all__ = ["main", "summarize"]


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    rank = max(1, int(-(-q * len(s) // 1)))  # ceil, nearest-rank
    return round(s[min(rank, len(s)) - 1], 3)


def _request_latencies(events: List[Dict[str, Any]]
                       ) -> Dict[str, Dict[str, Optional[float]]]:
    """uid -> {ttft_ms, queue_ms, e2e_ms, tpot_ms, n_tokens} from the
    lifecycle events (dimensions missing when the log lacks the events).

    Reconstruction is per TRACE, not per (uid, log): merged multi-worker
    streams are deduplicated first, and a migrated request — which
    carries a SECOND ``admitted`` (on the destination host) plus
    ``replay``-re-emitted chunks — anchors on the FIRST ``submitted`` /
    ``admitted`` / ``first_token`` and the LAST ``retired``, so its
    queue wait, TTFT and e2e are the client-observed ones, not the
    resumption bookkeeping's. (Before this, the last ``admitted`` won
    and a migrated request double-counted its queue wait.)"""
    from apex_tpu.monitor.events import _dedupe_events

    # the EARLIEST occurrence anchors every event except the terminal
    # ones, where the LATEST is the real end of the request — min/max by
    # timestamp, not stream position, so merged logs read identically in
    # any concatenation order
    _LAST = ("retired", "shed")
    by_uid: Dict[str, Dict[str, Any]] = {}
    for r in _dedupe_events(events):
        uid = r.get("uid")
        if uid is None:
            continue
        evs = by_uid.setdefault(uid, {})
        cur = evs.get(r["event"])
        if cur is None:
            evs[r["event"]] = r
        elif r["event"] in _LAST:
            if float(r["t_ms"]) > float(cur["t_ms"]):
                evs[r["event"]] = r
        elif float(r["t_ms"]) < float(cur["t_ms"]):
            evs[r["event"]] = r
    out: Dict[str, Dict[str, Optional[float]]] = {}
    for uid, evs in by_uid.items():
        t = {k: float(v["t_ms"]) for k, v in evs.items()}
        lat: Dict[str, Optional[float]] = {
            "queue_ms": (t["admitted"] - t["submitted"]
                         if {"admitted", "submitted"} <= t.keys() else None),
            "ttft_ms": (t["first_token"] - t["submitted"]
                        if {"first_token", "submitted"} <= t.keys()
                        else None),
            "e2e_ms": (t["retired"] - t["submitted"]
                       if {"retired", "submitted"} <= t.keys() else None),
        }
        ret = evs.get("retired", {})
        n = ret.get("n_tokens")
        lat["n_tokens"] = n
        lat["tpot_ms"] = (
            (t["retired"] - t["first_token"]) / (n - 1)
            if n and n > 1 and {"retired", "first_token"} <= t.keys()
            else None)
        out[uid] = lat
    return out


def summarize(records: List[Dict[str, Any]],
              slo=None) -> Dict[str, Any]:
    """The view record: event/step/gauge counts, per-request latency
    quantiles, optional SLO accounting (``slo``: an
    :class:`~apex_tpu.monitor.slo.SloSpec`)."""
    from apex_tpu.monitor.events import _dedupe_events

    # in-log flight-dump copies are marked and never counted twice
    records = [r for r in records if "flight_worker" not in r]
    events = [r for r in _dedupe_events(records)
              if r.get("kind") == "event"]
    gauges = [r for r in records if r.get("kind") == "gauge"]
    steps = [r for r in records if "kind" not in r]
    lats = _request_latencies(events)
    rec: Dict[str, Any] = {
        "n_records": len(records), "n_events": len(events),
        "n_gauges": len(gauges), "n_steps": len(steps),
        "n_requests": len(lats),
        "n_retired": sum(1 for r in events if r["event"] == "retired"),
    }
    # fleet-tier events, when the log carries them
    for name, ev in (("n_migrations", "migrate_start"),
                     ("n_replays", "replay"),
                     ("n_alerts_fired", "alert_fire"),
                     ("n_flight_dumps", "flight_dump")):
        n = sum(1 for r in events if r["event"] == ev)
        if n:
            rec[name] = n
    for dim in ("ttft_ms", "queue_ms", "tpot_ms", "e2e_ms"):
        vals = [v[dim] for v in lats.values() if v.get(dim) is not None]
        if vals:
            rec[f"{dim}_p50"] = _pct(vals, 0.5)
            rec[f"{dim}_p99"] = _pct(vals, 0.99)
    step_ms = [r["step_ms"] for r in steps if "step_ms" in r]
    if step_ms:
        rec["decode_step_ms_p50"] = _pct(step_ms, 0.5)
        rec["decode_step_ms_p99"] = _pct(step_ms, 0.99)
    occ = [r["occupancy"] for r in steps if "occupancy" in r]
    if occ:
        rec["mean_occupancy"] = round(sum(occ) / len(occ), 4)
    # serve throughput-optimization telemetry (chunked prefill backlog,
    # speculative proposed/accepted per step, cumulative prefix-cache
    # counters — see serve.engine._emit_metrics)
    backlog = [r["prefill_backlog_tokens"] for r in steps
               if "prefill_backlog_tokens" in r]
    if backlog:
        rec["prefill_backlog_mean"] = round(
            sum(backlog) / len(backlog), 2)
        rec["prefill_backlog_max"] = max(backlog)
    proposed = sum(r.get("spec_proposed", 0) for r in steps)
    if proposed:
        accepted = sum(r.get("spec_accepted", 0) for r in steps)
        rec["spec_proposed"] = proposed
        rec["spec_accepted"] = accepted
        rec["spec_acceptance_rate"] = round(accepted / proposed, 4)
    cum = [r for r in steps if "prefix_blocks_needed_total" in r]
    if cum and cum[-1]["prefix_blocks_needed_total"]:
        last = cum[-1]
        rec["prefix_blocks_hit"] = last["prefix_blocks_hit_total"]
        rec["prefix_blocks_needed"] = last["prefix_blocks_needed_total"]
        rec["prefix_hit_rate"] = round(
            last["prefix_blocks_hit_total"]
            / last["prefix_blocks_needed_total"], 4)
        rec["prefill_flops_saved"] = last.get(
            "prefill_flops_saved_total")
    # tier-4 latency attribution: only when the log's lifecycle carries
    # the anchors the decomposition needs (an engine-only log without
    # prefill_start events yields nothing — the keys just stay absent)
    from apex_tpu.monitor.attrib import (
        COMPONENTS,
        attribute_requests,
        attribution_summary,
    )

    attrib = attribute_requests(events, deduped=True)
    if attrib:
        summ = attribution_summary(events)
        rec["attrib_coverage"] = summ["attrib_coverage"]
        for c in COMPONENTS:
            for q in ("p50", "p99"):
                k = f"{c}_component_ms_{q}"
                if summ.get(k) is not None:
                    rec[k] = summ[k]
        # per-tenant rollup: requests / tokens / per-component time
        # totals from the event stream alone ("who consumed the
        # fleet's time" — the meterless half of the billing view; the
        # priced half lives on cluster.stats()["meter"])
        tenants: Dict[str, Dict[str, Any]] = {}
        for uid, comp in attrib.items():
            tname = comp.get("tenant")
            if tname is None:
                continue
            led = tenants.setdefault(
                tname, {"requests": 0, "tokens": 0, "e2e_ms_total": 0.0,
                        **{f"{c}_ms_total": 0.0 for c in COMPONENTS}})
            led["requests"] += 1
            n = lats.get(uid, {}).get("n_tokens")
            led["tokens"] += int(n or 0)
            led["e2e_ms_total"] = round(
                led["e2e_ms_total"] + comp["e2e_ms"], 3)
            for c in COMPONENTS:
                led[f"{c}_ms_total"] = round(
                    led[f"{c}_ms_total"] + max(0.0, comp[c]), 3)
        if tenants:
            rec["tenants"] = dict(sorted(tenants.items()))
    if slo is not None and slo.budgets():
        from apex_tpu.monitor.slo import SloTracker

        tracker = SloTracker(slo)
        for v in lats.values():
            if v.get("ttft_ms") is None and v.get("e2e_ms") is None:
                continue  # never admitted/retired: nothing to account
            tracker.observe(ttft_ms=v.get("ttft_ms"),
                            tpot_ms=v.get("tpot_ms"),
                            queue_ms=v.get("queue_ms"),
                            e2e_ms=v.get("e2e_ms"))
        rep = tracker.report()
        rec["slo"] = slo.to_dict()
        rec["good"] = rep["good"]
        rec["good_fraction"] = rep["good_fraction"]
        rec["violations"] = rep["violations"]
    return rec


def _table(rec: Dict[str, Any]) -> List[str]:
    lines = [f"records: {rec['n_records']} "
             f"(events {rec['n_events']}, steps {rec['n_steps']}, "
             f"gauges {rec['n_gauges']}) | requests: {rec['n_requests']} "
             f"retired: {rec['n_retired']}"]
    rows = [(d, rec.get(f"{d}_p50"), rec.get(f"{d}_p99"))
            for d in ("ttft_ms", "queue_ms", "tpot_ms", "e2e_ms",
                      "decode_step_ms")]
    rows = [r for r in rows if r[1] is not None]
    if rows:
        lines.append(f"  {'metric':<16} {'p50':>10} {'p99':>10}")
        for name, p50, p99 in rows:
            lines.append(f"  {name:<16} {p50:>10.3f} {p99:>10.3f}")
    if rec.get("mean_occupancy") is not None:
        lines.append(f"  mean occupancy: {rec['mean_occupancy']}")
    if rec.get("prefix_hit_rate") is not None:
        lines.append(
            f"  prefix cache: {rec['prefix_blocks_hit']}"
            f"/{rec['prefix_blocks_needed']} blocks "
            f"({rec['prefix_hit_rate']}) "
            f"flops saved: {rec.get('prefill_flops_saved')}")
    if rec.get("spec_acceptance_rate") is not None:
        lines.append(
            f"  speculative: {rec['spec_accepted']}"
            f"/{rec['spec_proposed']} drafts accepted "
            f"({rec['spec_acceptance_rate']})")
    if rec.get("prefill_backlog_mean") is not None:
        lines.append(
            f"  prefill backlog: mean {rec['prefill_backlog_mean']} "
            f"max {rec['prefill_backlog_max']} tokens")
    if "violations" in rec:
        v = " ".join(f"{k}={n}" for k, n in rec["violations"].items())
        lines.append(f"  SLO: good {rec['good']}/{rec['n_retired']} "
                     f"({rec['good_fraction']}) violations: {v or 'none'}")
    comp_rows = [(c, rec.get(f"{c}_component_ms_p50"),
                  rec.get(f"{c}_component_ms_p99"))
                 for c in ("queue", "prefill", "transfer", "decode",
                           "stall")]
    comp_rows = [r for r in comp_rows if r[1] is not None]
    if comp_rows:
        lines.append(f"  attribution (coverage "
                     f"{rec.get('attrib_coverage')}):")
        lines.append(f"  {'component':<16} {'p50':>10} {'p99':>10}")
        for name, p50, p99 in comp_rows:
            lines.append(f"  {name:<16} {p50:>10.3f} {p99:>10.3f}")
    if rec.get("tenants"):
        lines.append(f"  {'tenant':<16} {'reqs':>6} {'tokens':>8} "
                     f"{'e2e_s':>8} {'decode_s':>9} {'queue_s':>8}")
        for tname, led in rec["tenants"].items():
            lines.append(
                f"  {tname:<16} {led['requests']:>6} {led['tokens']:>8} "
                f"{led['e2e_ms_total'] / 1e3:>8.2f} "
                f"{led['decode_ms_total'] / 1e3:>9.2f} "
                f"{led['queue_ms_total'] / 1e3:>8.2f}")
    if rec.get("explain") is not None:
        ex = rec["explain"]
        lines.append(
            f"  vs baseline: e2e {ex['baseline_mean_ms']} -> "
            f"{ex['new_mean_ms']} ms ({ex['delta_ms']:+.3f})")
        for e in ex["components"][:3]:
            share = (f" ({e['share'] * 100:.0f}% of the move)"
                     if e["share"] is not None else "")
            lines.append(
                f"    {e['component']:<10} {e['baseline_ms']} -> "
                f"{e['new_ms']} ms ({e['delta_ms']:+.3f}){share}")
        if ex["diagnosis"] is not None:
            lines.append(f"    diagnosis: {ex['diagnosis']} grew the most")
    return lines


def main(argv=None) -> int:
    import argparse

    from apex_tpu.monitor.sink import json_record, read_jsonl
    from apex_tpu.monitor.slo import SloSpec

    ap = argparse.ArgumentParser(
        description="summarize a monitor JSONL log (events + steps)")
    ap.add_argument("path")
    ap.add_argument("--ttft-budget", type=float, default=None)
    ap.add_argument("--tpot-budget", type=float, default=None)
    ap.add_argument("--queue-budget", type=float, default=None)
    ap.add_argument("--e2e-budget", type=float, default=None)
    ap.add_argument("--baseline", default=None, metavar="FILE.jsonl",
                    help="second event log to attribute an e2e move "
                         "against (explain_regression: top-3 regressed "
                         "components + diagnosis)")
    args = ap.parse_args(argv)
    slo = SloSpec(ttft_ms=args.ttft_budget, tpot_ms=args.tpot_budget,
                  queue_ms=args.queue_budget, e2e_ms=args.e2e_budget)
    records = list(read_jsonl(args.path))
    rec = summarize(records, slo=slo if slo.budgets() else None)
    if args.baseline is not None:
        from apex_tpu.monitor.attrib import explain_regression

        base_events = [r for r in read_jsonl(args.baseline)
                       if r.get("kind") == "event"
                       and "flight_worker" not in r]
        new_events = [r for r in records if r.get("kind") == "event"
                      and "flight_worker" not in r]
        rec["explain"] = explain_regression(base_events, new_events)
    for line in _table(rec):
        print(line, file=sys.stderr)
    print(json_record(metric="monitor_view", file=args.path, **rec),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""``python -m apex_tpu.monitor.view FILE.jsonl`` — latency/SLO summary.

The one-command read of a serve telemetry log (step records, lifecycle
events and gauges share one JSONL file — ``view`` partitions by the
``kind`` field). Human table to **stderr**, one machine-readable
``json_record`` line to **stdout** — the bench.py pipe convention, so
``tpu_watch.sh`` and humans read the same invocation.

Per-request latencies are reconstructed from the lifecycle events
(``submitted → admitted → first_token → retired``); pass SLO budgets
(``--ttft-budget`` / ``--tpot-budget`` / ``--queue-budget`` /
``--e2e-budget``, ms) to get goodput/violation accounting through
:class:`~apex_tpu.monitor.slo.SloTracker` on the same records. Rotated
sinks (``FILE.jsonl.1`` …) are read transparently via ``read_jsonl``.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

__all__ = ["main", "summarize"]


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    rank = max(1, int(-(-q * len(s) // 1)))  # ceil, nearest-rank
    return round(s[min(rank, len(s)) - 1], 3)


def _request_latencies(events: List[Dict[str, Any]]
                       ) -> Dict[str, Dict[str, Optional[float]]]:
    """uid -> {ttft_ms, queue_ms, e2e_ms, tpot_ms, n_tokens} from the
    lifecycle events (dimensions missing when the log lacks the events).

    Reconstruction is per TRACE, not per (uid, log): merged multi-worker
    streams are deduplicated first, and a migrated request — which
    carries a SECOND ``admitted`` (on the destination host) plus
    ``replay``-re-emitted chunks — anchors on the FIRST ``submitted`` /
    ``admitted`` / ``first_token`` and the LAST ``retired``, so its
    queue wait, TTFT and e2e are the client-observed ones, not the
    resumption bookkeeping's. (Before this, the last ``admitted`` won
    and a migrated request double-counted its queue wait.)"""
    from apex_tpu.monitor.events import _dedupe_events

    # the EARLIEST occurrence anchors every event except the terminal
    # ones, where the LATEST is the real end of the request — min/max by
    # timestamp, not stream position, so merged logs read identically in
    # any concatenation order
    _LAST = ("retired", "shed")
    by_uid: Dict[str, Dict[str, Any]] = {}
    for r in _dedupe_events(events):
        uid = r.get("uid")
        if uid is None:
            continue
        evs = by_uid.setdefault(uid, {})
        cur = evs.get(r["event"])
        if cur is None:
            evs[r["event"]] = r
        elif r["event"] in _LAST:
            if float(r["t_ms"]) > float(cur["t_ms"]):
                evs[r["event"]] = r
        elif float(r["t_ms"]) < float(cur["t_ms"]):
            evs[r["event"]] = r
    out: Dict[str, Dict[str, Optional[float]]] = {}
    for uid, evs in by_uid.items():
        t = {k: float(v["t_ms"]) for k, v in evs.items()}
        lat: Dict[str, Optional[float]] = {
            "queue_ms": (t["admitted"] - t["submitted"]
                         if {"admitted", "submitted"} <= t.keys() else None),
            "ttft_ms": (t["first_token"] - t["submitted"]
                        if {"first_token", "submitted"} <= t.keys()
                        else None),
            "e2e_ms": (t["retired"] - t["submitted"]
                       if {"retired", "submitted"} <= t.keys() else None),
        }
        ret = evs.get("retired", {})
        n = ret.get("n_tokens")
        lat["n_tokens"] = n
        lat["tpot_ms"] = (
            (t["retired"] - t["first_token"]) / (n - 1)
            if n and n > 1 and {"retired", "first_token"} <= t.keys()
            else None)
        out[uid] = lat
    return out


def summarize(records: List[Dict[str, Any]],
              slo=None) -> Dict[str, Any]:
    """The view record: event/step/gauge counts, per-request latency
    quantiles, optional SLO accounting (``slo``: an
    :class:`~apex_tpu.monitor.slo.SloSpec`)."""
    from apex_tpu.monitor.events import _dedupe_events

    # in-log flight-dump copies are marked and never counted twice
    records = [r for r in records if "flight_worker" not in r]
    events = [r for r in _dedupe_events(records)
              if r.get("kind") == "event"]
    gauges = [r for r in records if r.get("kind") == "gauge"]
    steps = [r for r in records if "kind" not in r]
    lats = _request_latencies(events)
    rec: Dict[str, Any] = {
        "n_records": len(records), "n_events": len(events),
        "n_gauges": len(gauges), "n_steps": len(steps),
        "n_requests": len(lats),
        "n_retired": sum(1 for r in events if r["event"] == "retired"),
    }
    # fleet-tier events, when the log carries them
    for name, ev in (("n_migrations", "migrate_start"),
                     ("n_replays", "replay"),
                     ("n_alerts_fired", "alert_fire"),
                     ("n_flight_dumps", "flight_dump")):
        n = sum(1 for r in events if r["event"] == ev)
        if n:
            rec[name] = n
    for dim in ("ttft_ms", "queue_ms", "tpot_ms", "e2e_ms"):
        vals = [v[dim] for v in lats.values() if v.get(dim) is not None]
        if vals:
            rec[f"{dim}_p50"] = _pct(vals, 0.5)
            rec[f"{dim}_p99"] = _pct(vals, 0.99)
    step_ms = [r["step_ms"] for r in steps if "step_ms" in r]
    if step_ms:
        rec["decode_step_ms_p50"] = _pct(step_ms, 0.5)
        rec["decode_step_ms_p99"] = _pct(step_ms, 0.99)
    occ = [r["occupancy"] for r in steps if "occupancy" in r]
    if occ:
        rec["mean_occupancy"] = round(sum(occ) / len(occ), 4)
    # serve throughput-optimization telemetry (chunked prefill backlog,
    # speculative proposed/accepted per step, cumulative prefix-cache
    # counters — see serve.engine._emit_metrics)
    backlog = [r["prefill_backlog_tokens"] for r in steps
               if "prefill_backlog_tokens" in r]
    if backlog:
        rec["prefill_backlog_mean"] = round(
            sum(backlog) / len(backlog), 2)
        rec["prefill_backlog_max"] = max(backlog)
    proposed = sum(r.get("spec_proposed", 0) for r in steps)
    if proposed:
        accepted = sum(r.get("spec_accepted", 0) for r in steps)
        rec["spec_proposed"] = proposed
        rec["spec_accepted"] = accepted
        rec["spec_acceptance_rate"] = round(accepted / proposed, 4)
    cum = [r for r in steps if "prefix_blocks_needed_total" in r]
    if cum and cum[-1]["prefix_blocks_needed_total"]:
        last = cum[-1]
        rec["prefix_blocks_hit"] = last["prefix_blocks_hit_total"]
        rec["prefix_blocks_needed"] = last["prefix_blocks_needed_total"]
        rec["prefix_hit_rate"] = round(
            last["prefix_blocks_hit_total"]
            / last["prefix_blocks_needed_total"], 4)
        rec["prefill_flops_saved"] = last.get(
            "prefill_flops_saved_total")
    if slo is not None and slo.budgets():
        from apex_tpu.monitor.slo import SloTracker

        tracker = SloTracker(slo)
        for v in lats.values():
            if v.get("ttft_ms") is None and v.get("e2e_ms") is None:
                continue  # never admitted/retired: nothing to account
            tracker.observe(ttft_ms=v.get("ttft_ms"),
                            tpot_ms=v.get("tpot_ms"),
                            queue_ms=v.get("queue_ms"),
                            e2e_ms=v.get("e2e_ms"))
        rep = tracker.report()
        rec["slo"] = slo.to_dict()
        rec["good"] = rep["good"]
        rec["good_fraction"] = rep["good_fraction"]
        rec["violations"] = rep["violations"]
    return rec


def _table(rec: Dict[str, Any]) -> List[str]:
    lines = [f"records: {rec['n_records']} "
             f"(events {rec['n_events']}, steps {rec['n_steps']}, "
             f"gauges {rec['n_gauges']}) | requests: {rec['n_requests']} "
             f"retired: {rec['n_retired']}"]
    rows = [(d, rec.get(f"{d}_p50"), rec.get(f"{d}_p99"))
            for d in ("ttft_ms", "queue_ms", "tpot_ms", "e2e_ms",
                      "decode_step_ms")]
    rows = [r for r in rows if r[1] is not None]
    if rows:
        lines.append(f"  {'metric':<16} {'p50':>10} {'p99':>10}")
        for name, p50, p99 in rows:
            lines.append(f"  {name:<16} {p50:>10.3f} {p99:>10.3f}")
    if rec.get("mean_occupancy") is not None:
        lines.append(f"  mean occupancy: {rec['mean_occupancy']}")
    if rec.get("prefix_hit_rate") is not None:
        lines.append(
            f"  prefix cache: {rec['prefix_blocks_hit']}"
            f"/{rec['prefix_blocks_needed']} blocks "
            f"({rec['prefix_hit_rate']}) "
            f"flops saved: {rec.get('prefill_flops_saved')}")
    if rec.get("spec_acceptance_rate") is not None:
        lines.append(
            f"  speculative: {rec['spec_accepted']}"
            f"/{rec['spec_proposed']} drafts accepted "
            f"({rec['spec_acceptance_rate']})")
    if rec.get("prefill_backlog_mean") is not None:
        lines.append(
            f"  prefill backlog: mean {rec['prefill_backlog_mean']} "
            f"max {rec['prefill_backlog_max']} tokens")
    if "violations" in rec:
        v = " ".join(f"{k}={n}" for k, n in rec["violations"].items())
        lines.append(f"  SLO: good {rec['good']}/{rec['n_retired']} "
                     f"({rec['good_fraction']}) violations: {v or 'none'}")
    return lines


def main(argv=None) -> int:
    import argparse

    from apex_tpu.monitor.sink import json_record, read_jsonl
    from apex_tpu.monitor.slo import SloSpec

    ap = argparse.ArgumentParser(
        description="summarize a monitor JSONL log (events + steps)")
    ap.add_argument("path")
    ap.add_argument("--ttft-budget", type=float, default=None)
    ap.add_argument("--tpot-budget", type=float, default=None)
    ap.add_argument("--queue-budget", type=float, default=None)
    ap.add_argument("--e2e-budget", type=float, default=None)
    args = ap.parse_args(argv)
    slo = SloSpec(ttft_ms=args.ttft_budget, tpot_ms=args.tpot_budget,
                  queue_ms=args.queue_budget, e2e_ms=args.e2e_budget)
    records = list(read_jsonl(args.path))
    rec = summarize(records, slo=slo if slo.budgets() else None)
    for line in _table(rec):
        print(line, file=sys.stderr)
    print(json_record(metric="monitor_view", file=args.path, **rec),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""In-graph metric collection — a functional pytree of named scalars.

Reference context: the reference stack logs training scalars host-side
(print statements in ``examples/``, ``apex/pyprof`` for kernel time), every
read a device sync. MLPerf-on-TPU-pods work (arxiv 1909.09756) attributes
most scaling wins to per-step telemetry that does NOT perturb the step.

TPU design: :class:`Metrics` is a tiny pytree — an ordered mapping from
metric name to an f32 scalar — threaded through the jitted train step
exactly like the loss-scaler state (:class:`apex_tpu.amp.LossScalerState`):

* **in-graph** — every value is computed inside the step (global norms fuse
  into the sweeps that already touch the gradients), so collection costs no
  extra device round-trip;
* **donation-safe** — a Metrics carried in and returned out has a fixed
  treedef (names are the aux data, sorted), so ``donate_argnums`` works and
  the step's buffers alias as before;
* **zero extra compilations** — the name set is static per train-step
  specialization; recording the same names every step retraces nothing
  (guarded by ``tests/test_monitor.py``'s compile-count gate).

Host-side readout is one ``jax.device_get`` of the whole pytree
(:meth:`Metrics.as_dict`), typically handed to
:class:`apex_tpu.monitor.JsonlSink`.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


def _scalar(v) -> jnp.ndarray:
    """Coerce a metric value to an f32 scalar (bool flags become 0.0/1.0 so
    the pytree is homogeneous — one dtype, one treedef, donation-friendly)."""
    a = jnp.asarray(v)
    if a.ndim != 0:
        raise ValueError(
            f"metrics are scalars; got shape {a.shape} — reduce first "
            "(e.g. global_norm)")
    return a.astype(jnp.float32)


@jax.tree_util.register_pytree_node_class
class Metrics:
    """Immutable named-scalar pytree. Names are treedef aux data (sorted, so
    insertion order never splits the jit cache); values are f32 scalar
    leaves. All update methods return a NEW Metrics."""

    __slots__ = ("_values",)

    def __init__(self, values: Optional[Mapping[str, Any]] = None):
        vals = {k: _scalar(v) for k, v in dict(values or {}).items()}
        object.__setattr__(self, "_values", dict(sorted(vals.items())))

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        keys = tuple(self._values.keys())
        return tuple(self._values[k] for k in keys), keys

    @classmethod
    def tree_unflatten(cls, keys, leaves):
        obj = object.__new__(cls)
        # bypass _scalar: leaves may be tracers/placeholders mid-transform
        object.__setattr__(obj, "_values", dict(zip(keys, leaves)))
        return obj

    # -- functional updates ------------------------------------------------
    def record(self, **entries) -> "Metrics":
        """New Metrics with ``entries`` added (overwriting same names)."""
        merged = dict(self._values)
        merged.update({k: _scalar(v) for k, v in entries.items()})
        return Metrics(merged)

    def accumulate(self, **entries) -> "Metrics":
        """New Metrics with ``entries`` ADDED to existing values (counters:
        overflow totals, cumulative comm bytes). Missing names start at 0."""
        merged = dict(self._values)
        for k, v in entries.items():
            merged[k] = merged.get(k, jnp.float32(0.0)) + _scalar(v)
        return Metrics(merged)

    def merge(self, other: "Metrics") -> "Metrics":
        """New Metrics with ``other``'s entries (other wins on collision)."""
        merged = dict(self._values)
        merged.update(other._values)
        return Metrics(merged)

    # -- access ------------------------------------------------------------
    def __getitem__(self, name: str) -> jnp.ndarray:
        return self._values[name]

    def __contains__(self, name: str) -> bool:
        return name in self._values

    def __iter__(self) -> Iterator[str]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def names(self) -> Tuple[str, ...]:
        return tuple(self._values.keys())

    def as_dict(self) -> Dict[str, float]:
        """Host-side readout: ONE device transfer for all values."""
        host = jax.device_get(self._values)
        return {k: float(v) for k, v in host.items()}

    def __repr__(self):
        return f"Metrics({list(self._values.keys())})"


def global_norm(tree: Pytree) -> jnp.ndarray:
    """Global L2 norm over every leaf of a pytree, f32. XLA fuses the
    squared-sums into whatever sweep already reads the leaves (the same
    fusion ``amp_C.multi_tensor_l2norm`` hand-wrote), so recording a grad
    norm alongside the unscale/update sweep is free."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def train_metrics(
    metrics: Optional[Metrics] = None,
    *,
    loss: Optional[jnp.ndarray] = None,
    grads: Optional[Pytree] = None,
    params: Optional[Pytree] = None,
    updates: Optional[Pytree] = None,
) -> Metrics:
    """Record the standard per-step scalars: ``loss`` plus global norms of
    whatever pytrees are given (``grad_norm``, ``param_norm``,
    ``update_norm``). Call inside the jitted step; compose with
    :meth:`apex_tpu.amp.LossScaler.metrics` for scale/overflow and
    :meth:`apex_tpu.parallel.DistributedDataParallel.average_gradients`
    (``metrics=``) for comm bytes."""
    m = metrics if metrics is not None else Metrics()
    entries: Dict[str, Any] = {}
    if loss is not None:
        entries["loss"] = loss
    if grads is not None:
        entries["grad_norm"] = global_norm(grads)
    if params is not None:
        entries["param_norm"] = global_norm(params)
    if updates is not None:
        entries["update_norm"] = global_norm(updates)
    return m.record(**entries)

"""Unified in-graph training telemetry (L-monitor).

Not in the reference: NVIDIA Apex observes training through three
disconnected holes — ``pyprof`` NVTX/kernel joins, per-example ``print``
logging, and whatever the trainer scripts hand-roll. This subsystem is the
one layer that answers "what was the loss, grad norm, loss scale, comm
volume, and MFU at step N" from a running job, with zero perturbation of
the step:

* :mod:`~apex_tpu.monitor.metrics` — :class:`Metrics`, a named-scalar
  pytree threaded through the jitted train step like the loss-scaler state
  (in-graph, donation-safe, zero extra compilations), plus
  :func:`global_norm` / :func:`train_metrics` collectors. Producers wired
  in: ``amp.LossScaler.metrics`` (scale + overflow/skip counters),
  ``parallel.DistributedDataParallel.average_gradients(metrics=...)``
  (per-bucket wire bytes + compression ratio),
  ``contrib.optimizers.DistributedFused{Adam,LAMB}.step(metrics=...)``
  (shard norms).
* :mod:`~apex_tpu.monitor.trace` — :func:`span` named ranges
  (``jax.named_scope`` + host ``TraceAnnotation``: one marker, visible in
  the trace viewer and as pyprof layer paths) and :func:`step_annotation`
  step grouping. The pipeline schedules emit ``pp_stage`` /
  ``pp_ring_shift`` spans for bubble attribution.
* :mod:`~apex_tpu.monitor.sink` — :class:`JsonlSink`, the process-0-gated,
  versioned, buffered, crash-safe JSONL writer; :func:`json_record` is the
  shared one-JSON-line convention every bench prints.
* :mod:`~apex_tpu.monitor.report` — :func:`step_report`, the measured-time
  × HLO-flops × bytes-on-wire join (MFU, ICI bandwidth, per-phase ms);
  :func:`mfu_check` / :func:`hlo_stats` compile-only variants
  (``benchmarks/profile_step.py`` and ``check_mfu_accounting.py`` are thin
  wrappers over these).

Tier 2 (the serving side — request-level attribution, not step averages):

* :mod:`~apex_tpu.monitor.hist` — :class:`Histogram` /:class:`HistSpec`:
  fixed log-spaced-bucket streaming histograms (mergeable, constant
  memory, quantiles within ``rel_error``), host-side or as per-bucket
  counters on the :class:`Metrics` pytree (:func:`accumulate_hist`);
* :mod:`~apex_tpu.monitor.events` — :class:`EventLog` request-lifecycle
  recording on one monotonic clock (``submitted → … → retired`` + queue/
  occupancy gauges), JSONL via the sink and Chrome trace-event JSON via
  :func:`chrome_trace` (one Perfetto track per slot and per request);
* :mod:`~apex_tpu.monitor.slo` — :class:`SloSpec` declarative latency
  budgets → :class:`SloTracker` goodput/violation accounting over rolling
  windows;
* :mod:`~apex_tpu.monitor.regress` — :func:`compare_records` baseline
  diffing of bench records (the ``tpu_watch.sh`` stage-10 gate);
* :mod:`~apex_tpu.monitor.view` — ``python -m apex_tpu.monitor.view``
  latency/SLO summary CLI over any monitor JSONL file.

Tier 3 (the fleet side — live cross-host signal, not per-worker logs):

* **distributed tracing** — :meth:`EventLog.bind` threads a trace id
  (minted at router submission) plus the request's current host through
  every producer's events; :func:`request_spans` reconstructs per
  trace across merged multi-worker logs, :func:`stitch_traces` verifies
  the cross-host structure, and :func:`chrome_trace` renders one
  Perfetto track per HOST — a request that hops hosts or migrates under
  chaos is visibly one trace id in causal order;
* :mod:`~apex_tpu.monitor.registry` — :class:`MetricsRegistry`
  cardinality-bounded named series (counters/gauges/histograms) with
  Prometheus text exposition, snapshot/merge aggregation (histogram
  merge is associative — this is what it was built for), and the
  :class:`FleetScraper` pulling worker snapshots on the cluster clock
  into one :class:`~apex_tpu.monitor.registry.FleetView` (per-worker,
  per-tenant and rolled-up series; scrape_ms/coverage self-measured);
* :mod:`~apex_tpu.monitor.alerts` — declarative threshold / absence /
  rate rules evaluated over scraped series; firings are first-class
  ``alert_fire``/``alert_resolve`` events that drive the cluster's
  autoscaler and land in the JSONL stream;
* :mod:`~apex_tpu.monitor.flight` — :class:`FlightRecorder` bounded
  in-memory rings of recent records, dumped atomically (the
  ``resilience.checkpoint`` tmp+replace discipline) on chaos kill /
  watchdog fire / alert escalation;
* :mod:`~apex_tpu.monitor.postmortem` — ``python -m
  apex_tpu.monitor.postmortem DIR`` rebuilds the merged pre-failure
  timeline from flight dumps alone.

Tier 4 (performance forensics — why, who pays, and since when):

* :mod:`~apex_tpu.monitor.attrib` — per-request latency attribution
  derived purely from the EventLog lifecycle: every retired request's
  e2e decomposes into queue/prefill/transfer/decode/stall components
  that SUM to the measured e2e (migration/replay-safe, concatenation-
  order-independent); :class:`AttributionAccumulator` streams it into
  per-component histograms on ``engine.stats()``/``cluster.stats()``,
  and :func:`explain_regression` turns a stage-gate verdict into a
  diagnosis;
* :mod:`~apex_tpu.monitor.meter` — per-tenant resource metering
  (modeled flops, KV block-seconds, adapter residency, wire bytes)
  rolled up under a declarative :class:`CostModel` with
  ``cost_per_token``/``cost_per_request`` surfaced in stats, per-worker
  cost rates advertised on the membership heartbeat, and loud
  cardinality-bounded overflow accounting;
* :mod:`~apex_tpu.monitor.trend` — append-only per-stage history of
  banked watcher records (provenance-stamped via
  :func:`sink.set_provenance`) with robust median+MAD / Theil–Sen
  drift detection; ``python -m apex_tpu.monitor.trend check`` exits 1
  on drift — the longitudinal gate next to the pairwise regress gate.
"""

from apex_tpu.monitor.alerts import (  # noqa: F401
    AbsenceRule,
    AlertEngine,
    AlertRule,
    Condition,
    RateRule,
)
from apex_tpu.monitor.attrib import (  # noqa: F401
    COMPONENTS,
    AttributionAccumulator,
    attribute_requests,
    attribution_summary,
    explain_regression,
)
from apex_tpu.monitor.events import (  # noqa: F401
    EventLog,
    chrome_trace,
    dedupe_events,
    request_spans,
    stitch_traces,
    write_chrome_trace,
)
from apex_tpu.monitor.meter import (  # noqa: F401
    CostModel,
    Meter,
    modeled_request_flops,
)
from apex_tpu.monitor.flight import (  # noqa: F401
    FlightRecorder,
)
from apex_tpu.monitor.registry import (  # noqa: F401
    FleetScraper,
    FleetView,
    MetricsRegistry,
    merge_snapshots,
)
from apex_tpu.monitor.hist import (  # noqa: F401
    DEFAULT_LATENCY_SPEC,
    HistSpec,
    Histogram,
    accumulate_hist,
    hist_counts,
    hist_from_metrics,
    hist_metric_names,
)

from apex_tpu.monitor.metrics import (  # noqa: F401
    Metrics,
    global_norm,
    train_metrics,
)
from apex_tpu.monitor.report import (  # noqa: F401
    format_step_report,
    gpt_analytic_flops_per_token,
    hlo_stats,
    mfu_check,
    phase_breakdown,
    pipeline_bubble_fraction,
    step_report,
)
from apex_tpu.monitor.sink import (  # noqa: F401
    SCHEMA_VERSION,
    JsonlSink,
    collect_provenance,
    json_record,
    read_jsonl,
    rotated_segments,
    set_provenance,
)
from apex_tpu.monitor.slo import (  # noqa: F401
    SloSpec,
    SloTracker,
)
from apex_tpu.monitor.trace import (  # noqa: F401
    PHASES,
    span,
    span_function,
    step_annotation,
)


def __getattr__(name):
    # regress and trend double as `python -m apex_tpu.monitor.<mod>`;
    # importing them eagerly here would make runpy warn about the
    # pre-imported module every CLI run, so their package-level names
    # resolve lazily
    if name in ("compare_records", "load_record"):
        from apex_tpu.monitor import regress

        return getattr(regress, name)
    if name in ("append_history", "detect_trends", "load_history"):
        from apex_tpu.monitor import trend

        return getattr(trend, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "AbsenceRule",
    "AlertEngine",
    "AlertRule",
    "AttributionAccumulator",
    "COMPONENTS",
    "Condition",
    "CostModel",
    "DEFAULT_LATENCY_SPEC",
    "EventLog",
    "FleetScraper",
    "FleetView",
    "FlightRecorder",
    "HistSpec",
    "Histogram",
    "JsonlSink",
    "Meter",
    "Metrics",
    "MetricsRegistry",
    "PHASES",
    "RateRule",
    "SCHEMA_VERSION",
    "SloSpec",
    "SloTracker",
    "accumulate_hist",
    "append_history",
    "attribute_requests",
    "attribution_summary",
    "chrome_trace",
    "collect_provenance",
    "compare_records",
    "dedupe_events",
    "detect_trends",
    "explain_regression",
    "load_history",
    "merge_snapshots",
    "modeled_request_flops",
    "format_step_report",
    "global_norm",
    "gpt_analytic_flops_per_token",
    "hist_counts",
    "hist_from_metrics",
    "hist_metric_names",
    "hlo_stats",
    "json_record",
    "load_record",
    "mfu_check",
    "phase_breakdown",
    "pipeline_bubble_fraction",
    "read_jsonl",
    "request_spans",
    "rotated_segments",
    "set_provenance",
    "span",
    "stitch_traces",
    "span_function",
    "step_annotation",
    "step_report",
    "train_metrics",
    "write_chrome_trace",
]

"""Unified in-graph training telemetry (L-monitor).

Not in the reference: NVIDIA Apex observes training through three
disconnected holes — ``pyprof`` NVTX/kernel joins, per-example ``print``
logging, and whatever the trainer scripts hand-roll. This subsystem is the
one layer that answers "what was the loss, grad norm, loss scale, comm
volume, and MFU at step N" from a running job, with zero perturbation of
the step:

* :mod:`~apex_tpu.monitor.metrics` — :class:`Metrics`, a named-scalar
  pytree threaded through the jitted train step like the loss-scaler state
  (in-graph, donation-safe, zero extra compilations), plus
  :func:`global_norm` / :func:`train_metrics` collectors. Producers wired
  in: ``amp.LossScaler.metrics`` (scale + overflow/skip counters),
  ``parallel.DistributedDataParallel.average_gradients(metrics=...)``
  (per-bucket wire bytes + compression ratio),
  ``contrib.optimizers.DistributedFused{Adam,LAMB}.step(metrics=...)``
  (shard norms).
* :mod:`~apex_tpu.monitor.trace` — :func:`span` named ranges
  (``jax.named_scope`` + host ``TraceAnnotation``: one marker, visible in
  the trace viewer and as pyprof layer paths) and :func:`step_annotation`
  step grouping. The pipeline schedules emit ``pp_stage`` /
  ``pp_ring_shift`` spans for bubble attribution.
* :mod:`~apex_tpu.monitor.sink` — :class:`JsonlSink`, the process-0-gated,
  versioned, buffered, crash-safe JSONL writer; :func:`json_record` is the
  shared one-JSON-line convention every bench prints.
* :mod:`~apex_tpu.monitor.report` — :func:`step_report`, the measured-time
  × HLO-flops × bytes-on-wire join (MFU, ICI bandwidth, per-phase ms);
  :func:`mfu_check` / :func:`hlo_stats` compile-only variants
  (``benchmarks/profile_step.py`` and ``check_mfu_accounting.py`` are thin
  wrappers over these).
"""

from apex_tpu.monitor.metrics import (  # noqa: F401
    Metrics,
    global_norm,
    train_metrics,
)
from apex_tpu.monitor.report import (  # noqa: F401
    format_step_report,
    gpt_analytic_flops_per_token,
    hlo_stats,
    mfu_check,
    phase_breakdown,
    pipeline_bubble_fraction,
    step_report,
)
from apex_tpu.monitor.sink import (  # noqa: F401
    SCHEMA_VERSION,
    JsonlSink,
    json_record,
    read_jsonl,
)
from apex_tpu.monitor.trace import (  # noqa: F401
    PHASES,
    span,
    span_function,
    step_annotation,
)

__all__ = [
    "JsonlSink",
    "Metrics",
    "PHASES",
    "SCHEMA_VERSION",
    "format_step_report",
    "global_norm",
    "gpt_analytic_flops_per_token",
    "hlo_stats",
    "json_record",
    "mfu_check",
    "phase_breakdown",
    "pipeline_bubble_fraction",
    "read_jsonl",
    "span",
    "span_function",
    "step_annotation",
    "step_report",
    "train_metrics",
]

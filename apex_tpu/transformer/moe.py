"""Mixture-of-Experts layer with expert parallelism (EP) — TPU-native.

The reference has no MoE/expert parallelism (SURVEY §2.3 marks EP "not
present"); this module is the north-star extension that completes the
parallelism checklist alongside ring/Ulysses sequence parallelism. The
design follows the GShard/Switch capacity-factor formulation, built the
TPU way:

* **Static shapes everywhere.** Token→expert assignment uses a fixed
  per-expert capacity ``C``; overflowing tokens are dropped from the expert
  path (their output is the zero vector, so the surrounding residual
  connection passes them through unchanged). No dynamic shapes, no host
  round-trips — the whole layer is one traced program.
* **EP rides the data-parallel axis.** Experts are sharded over ``ep``
  (default: the ``dp`` mesh axis — the standard ep ⊆ dp layout): each rank
  holds ``E / ep`` experts and routes its local tokens to *global* experts
  with one ``lax.all_to_all`` each way. On TPU the all-to-all maps onto the
  ICI torus natively.
* **TP composes inside the expert.** Expert FFN weights carry the usual
  Megatron column/row split on the hidden dim; the TP collectives are the
  same copy/reduce pair as ``tensor_parallel.layers`` (identity-fwd/psum-bwd
  on entry, psum-fwd/identity-bwd on exit).

Routing math (fp32, regardless of model dtype): top-k gates, normalized
over the selected k (GShard top-2 convention), position-in-expert by
priority cumsum (all ranks' top-1 choices outrank top-2), load-balance
auxiliary loss ``E · Σ_e f_e · p̄_e`` (Switch eq. 4) and router z-loss.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import DP_AXIS, TP_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
)

Pytree = Any


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Static MoE hyper-parameters (one dataclass, SURVEY §5 config style)."""

    num_experts: int
    hidden: int
    ffn_hidden: int
    top_k: int = 2
    # capacity per expert = ceil(top_k * tokens / num_experts) * factor
    capacity_factor: float = 1.25
    # weight of the load-balance aux loss in `moe_mlp`'s returned aux dict
    lb_loss_weight: float = 1e-2
    z_loss_weight: float = 1e-3
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.top_k > self.num_experts:
            raise ValueError(
                f"top_k ({self.top_k}) cannot exceed num_experts "
                f"({self.num_experts})")

    def capacity(self, tokens_per_rank: int) -> int:
        per = self.top_k * tokens_per_rank / self.num_experts
        cap = int(per * self.capacity_factor) + 1
        # keep the lane dim friendly: round up to 8 (sublane) when roomy
        return max(8, -(-cap // 8) * 8) if cap > 8 else max(1, cap)


def init_moe_params(rng, cfg: MoEConfig, ep: int = 1, tp: int = 1) -> Pytree:
    """Global-shape parameter pytree. Expert weights lead with the GLOBAL
    expert dim [E]; :func:`moe_param_specs` shards it over ``ep`` and the
    ffn dim over ``tp``."""
    if cfg.num_experts % ep:
        raise ValueError(
            f"num_experts ({cfg.num_experts}) not divisible by ep ({ep})")
    if cfg.ffn_hidden % tp:
        raise ValueError(
            f"ffn_hidden ({cfg.ffn_hidden}) not divisible by tp ({tp})")
    kr, k1, k2 = jax.random.split(rng, 3)
    e, h, f = cfg.num_experts, cfg.hidden, cfg.ffn_hidden
    dt = cfg.dtype
    return {
        # router stays fp32: its output feeds softmax/top-k decisions
        "router": jax.random.normal(kr, (h, e), jnp.float32) * 0.02,
        "fc1_kernel": (jax.random.normal(k1, (e, h, f)) * 0.02).astype(dt),
        "fc1_bias": jnp.zeros((e, f), dt),
        "fc2_kernel": (jax.random.normal(k2, (e, f, h)) * 0.02).astype(dt),
        "fc2_bias": jnp.zeros((e, h), dt),
    }


def moe_param_specs(ep_axis: Optional[str] = DP_AXIS) -> Pytree:
    """PartitionSpecs for :func:`init_moe_params`: experts over ``ep_axis``,
    expert FFN dim over tp (Megatron column/row split)."""
    from jax.sharding import PartitionSpec as P

    return {
        "router": P(),
        "fc1_kernel": P(ep_axis, None, TP_AXIS),
        "fc1_bias": P(ep_axis, TP_AXIS),
        "fc2_kernel": P(ep_axis, TP_AXIS, None),
        "fc2_bias": P(ep_axis, None),
    }


# ---------------------------------------------------------------------------
# routing


def _route(logits32, top_k: int, capacity: int):
    """Token-choice top-k routing with per-expert capacity.

    ``logits32``: (T, E) fp32. Returns ``(dispatch, combine, aux)`` where
    ``dispatch`` is a boolean (T, E, C) assignment, ``combine`` the fp32
    gate-weighted version, and ``aux`` carries the load stats.
    """
    t, e = logits32.shape
    probs = jax.nn.softmax(logits32, axis=-1)
    gate, idx = lax.top_k(probs, top_k)  # (T, k)
    # GShard: renormalize the selected gates over the k choices
    gate = gate / jnp.maximum(jnp.sum(gate, axis=-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (T, k, E)
    # priority: every token's slot-0 choice outranks any slot-1 choice —
    # order the cumsum (k, T, E) so rank-0 rows come first
    sel_kt = onehot.transpose(1, 0, 2).reshape(top_k * t, e)
    pos_kt = jnp.cumsum(sel_kt, axis=0) - sel_kt  # 0-based slot in expert
    pos = pos_kt.reshape(top_k, t, e).transpose(1, 0, 2)  # (T, k, E)
    keep = onehot * (pos < capacity)
    slot = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)  # (T, k) slot id

    # (T, k, E, C) -> reduce k: a token occupies ≤1 slot per expert
    slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)  # (T, k, C)
    dispatch = jnp.einsum("tke,tkc->tec", keep, slot_oh)
    combine = jnp.einsum("tke,tkc,tk->tec", keep, slot_oh, gate)

    # Switch aux loss: E * sum_e (fraction routed to e) * (mean prob of e).
    # "routed" counts the top-1 assignment before capacity (standard form).
    frac = jnp.mean(onehot[:, 0, :], axis=0)
    lb_loss = e * jnp.sum(frac * jnp.mean(probs, axis=0))
    z = jax.nn.logsumexp(logits32, axis=-1)
    z_loss = jnp.mean(z * z)
    kept = jnp.sum(keep) / jnp.maximum(jnp.sum(onehot), 1.0)
    aux = {"lb_loss": lb_loss, "z_loss": z_loss, "fraction_kept": kept}
    return dispatch, combine, aux


# ---------------------------------------------------------------------------
# expert compute (local experts, TP-sharded FFN)


def _expert_ffn(p, x):
    """``x``: (E_local, N, h) TP-replicated -> (E_local, N, h). Megatron
    split on the ffn dim: fc1 column-parallel, gelu, fc2 row-parallel."""
    x = copy_to_tensor_model_parallel_region(x)
    # input-dtype einsum: keeps backward cotangents bf16 (see
    # tensor_parallel/layers.py) — fp32 MXU accumulation either way
    y = jnp.einsum("enh,ehf->enf", x,
                   p["fc1_kernel"].astype(x.dtype))
    y = y + p["fc1_bias"][:, None, :]
    y = jax.nn.gelu(y, approximate=True)
    y = jnp.einsum("enf,efh->enh", y,
                   p["fc2_kernel"].astype(x.dtype))
    y = reduce_from_tensor_model_parallel_region(y)
    return y + p["fc2_bias"][:, None, :]


# ---------------------------------------------------------------------------
# the layer


def moe_mlp(params, x, cfg: MoEConfig, ep_axis: Optional[str] = DP_AXIS,
            seq_shard_axis: Optional[str] = None) -> Tuple[jax.Array, dict]:
    """MoE FFN over ``x`` (..., h). Call inside a mesh program; tokens are
    this rank's local shard, experts are sharded over ``ep_axis`` (pass
    ``None`` for a single-rank/no-EP layer). Returns ``(out, aux)``;
    ``aux['loss']`` is the weighted router auxiliary loss (psum-mean it over
    the data axis alongside the main loss).

    ``seq_shard_axis`` enables the sequence-sharded dispatch for callers
    whose tokens are sharded over that axis (Megatron-SP regions, sharded
    over tp): each rank routes only its LOCAL tokens with a per-shard
    capacity ``C/axis_size``, the kept expert slots — not the raw sequence
    — are all-gathered along the capacity dim (the expert FFN's TP split
    needs replicated inputs for its row-parallel psum), and each rank
    combines only its own slot block back out. Versus gathering the full
    sequence first, router/dispatch/combine einsum FLOPs drop by the axis
    size, the all_to_all bytes are unchanged, and the output STAYS
    sequence-sharded (the SP activation saving is kept). Semantics note:
    capacity is enforced per sequence shard, so under skewed load the drop
    pattern differs from the full-sequence path; with ample capacity the
    outputs are bitwise the gathered path's (tested).
    """
    lead = x.shape[:-1]
    h = x.shape[-1]
    xf = x.reshape(-1, h)
    t = xf.shape[0]
    e = cfg.num_experts
    cap = cfg.capacity(t)

    logits = jnp.dot(xf.astype(jnp.float32), params["router"])
    dispatch, combine, aux = _route(logits, cfg.top_k, cap)

    # (T, h) -> (E, C, h): zero rows where a slot is unfilled
    exp_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xf)

    if seq_shard_axis is not None:
        # kept slots from every sequence shard, stacked on the capacity dim
        exp_in = lax.all_gather(exp_in, seq_shard_axis, axis=1, tiled=True)

    if ep_axis is not None:
        ep = lax.axis_size(ep_axis)
    else:
        ep = 1
    if ep > 1:
        e_local = e // ep
        # exchange: split global experts over ranks, gather every rank's
        # contribution for the local experts along the token dim
        exp_in = lax.all_to_all(exp_in, ep_axis, split_axis=0, concat_axis=1,
                                tiled=True)  # (E/ep, ep*C, h)
        exp_out = _expert_ffn(_local_experts(params, ep_axis, e_local),
                              exp_in)
        exp_out = lax.all_to_all(exp_out, ep_axis, split_axis=1,
                                 concat_axis=0, tiled=True)  # (E, C, h)
    else:
        exp_out = _expert_ffn(params, exp_in)

    if seq_shard_axis is not None:
        # this rank's slot block back out of the gathered capacity dim
        exp_out = lax.dynamic_slice_in_dim(
            exp_out, lax.axis_index(seq_shard_axis) * cap, cap, axis=1)

    out = jnp.einsum("tec,ech->th", combine.astype(x.dtype), exp_out)
    aux = dict(aux)
    aux["loss"] = (cfg.lb_loss_weight * aux["lb_loss"]
                   + cfg.z_loss_weight * aux["z_loss"])
    return out.reshape(*lead, h), aux


def _local_experts(params, ep_axis: str, e_local: int) -> Pytree:
    """Slice this rank's expert shard out of params that arrived replicated
    (inside shard_map the spec normally delivers them pre-sliced; this
    handles the replicated-params case, e.g. pure-pjit callers)."""
    fc1 = params["fc1_kernel"]
    if fc1.shape[0] == e_local:
        return params  # already the local shard (shard_map + specs)
    start = lax.axis_index(ep_axis) * e_local
    return {
        k: lax.dynamic_slice_in_dim(params[k], start, e_local, 0)
        for k in ("fc1_kernel", "fc1_bias", "fc2_kernel", "fc2_bias")
    }

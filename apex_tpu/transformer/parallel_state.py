"""Model-parallel state: the mesh-backed analogue of Megatron process groups.

Reference: ``apex/transformer/parallel_state.py`` — builds DP/TP/PP/embedding
process groups and exposes ~30 rank/size accessors. Here the state is a single
global ``jax.sharding.Mesh`` (built by :func:`initialize_model_parallel`) plus
virtual-pipeline bookkeeping. Two kinds of accessor exist:

* **Host-level sizes** (``get_*_world_size``) read the mesh shape and work
  anywhere.
* **Rank accessors** (``get_*_rank``) return ``lax.axis_index(axis)`` — a
  traced value — and are therefore only valid *inside* a mesh program
  (``shard_map`` / ``pjit`` body). This is the honest TPU translation: under
  SPMD one program runs on every device, so "my rank" is a device-varying
  value, not a Python int. (The reference can return a Python int because each
  NCCL rank is its own process.)

Virtual pipeline (interleaved schedule) rank/size are host-level Python ints,
as in the reference (``parallel_state.py:297-320``), because they index model
*chunks* held by the current stage, not devices.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh

from apex_tpu.parallel.mesh import (
    AXIS_ORDER,
    DP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    build_mesh,
    model_parallel_axes,
)

_MESH: Optional[Mesh] = None
_VIRTUAL_PP_SIZE: Optional[int] = None
_VIRTUAL_PP_RANK: Optional[int] = None
_PP_SPLIT_RANK: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    sequence_parallel_size_: int = 1,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    devices=None,
) -> Mesh:
    """Build and install the global mesh (ref parallel_state.py:57-185).

    ``pipeline_model_parallel_split_rank_`` (ref parallel_state.py:61,113)
    records the encoder/decoder boundary for ``ModelType.encoder_and_decoder``
    models. Here it is bookkeeping for API parity only: the TPU enc-dec
    schedule runs encoder and decoder as two full-ring phases, so every stage
    holds one chunk of each and no device partition exists to balance (see
    ``schedules/fwd_bwd_enc_dec.py``).
    """
    global _MESH, _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK, _PP_SPLIT_RANK
    if pipeline_model_parallel_split_rank_ is not None and not (
        0 < pipeline_model_parallel_split_rank_ < pipeline_model_parallel_size_
    ):
        # upper bound strict: split == pp would leave zero decoder stages
        raise ValueError(
            f"pipeline_model_parallel_split_rank_="
            f"{pipeline_model_parallel_split_rank_} outside "
            f"(0, pp={pipeline_model_parallel_size_})"
        )
    _MESH = build_mesh(
        tp=tensor_model_parallel_size_,
        pp=pipeline_model_parallel_size_,
        sp=sequence_parallel_size_,
        devices=devices,
    )
    _VIRTUAL_PP_SIZE = virtual_pipeline_model_parallel_size_
    _VIRTUAL_PP_RANK = 0 if virtual_pipeline_model_parallel_size_ else None
    _PP_SPLIT_RANK = pipeline_model_parallel_split_rank_
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError(
            "model parallel mesh is not initialized; call "
            "initialize_model_parallel() first"
        )
    return _MESH


def destroy_model_parallel() -> None:
    """Ref parallel_state.py:440-465."""
    global _MESH, _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK, _PP_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PP_SIZE = None
    _VIRTUAL_PP_RANK = None
    _PP_SPLIT_RANK = None


def get_mesh_axes_str() -> str:
    if _MESH is None:
        return "uninitialized"
    return "x".join(f"{a}={_MESH.shape[a]}" for a in AXIS_ORDER)


# ---------------------------------------------------------------------------
# World sizes (host-level)

def get_tensor_model_parallel_world_size() -> int:
    return get_mesh().shape[TP_AXIS]


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh().shape[PP_AXIS]


def get_sequence_parallel_world_size() -> int:
    return get_mesh().shape[SP_AXIS]


def get_data_parallel_world_size() -> int:
    return get_mesh().shape[DP_AXIS]


def get_model_parallel_world_size() -> int:
    m = get_mesh()
    out = 1
    for a in model_parallel_axes(m):
        out *= m.shape[a]
    return out


# ---------------------------------------------------------------------------
# Ranks (traced values; valid inside mesh programs only)

def get_tensor_model_parallel_rank():
    return lax.axis_index(TP_AXIS)


def get_pipeline_model_parallel_rank():
    return lax.axis_index(PP_AXIS)


def get_sequence_parallel_rank():
    return lax.axis_index(SP_AXIS)


def get_data_parallel_rank():
    return lax.axis_index(DP_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced boolean (ref parallel_state.py:322-338). With virtual pipeline,
    the first *virtual* chunk on the first stage is the model's first layer.

    .. warning:: The virtual-pipeline rank is read at **trace time** (it is
       host-level Python state, as in the reference). Functions that branch on
       it must be re-traced after ``set_virtual_pipeline_model_parallel_rank``
       — the interleaved schedule builder does this by constructing one traced
       program per model chunk; do not bake this call into a single jit cache
       entry reused across chunks."""
    first = get_pipeline_model_parallel_rank() == 0
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        return first if _VIRTUAL_PP_RANK == 0 else (first & False)
    return first


def is_pipeline_last_stage(ignore_virtual: bool = False):
    last = (
        get_pipeline_model_parallel_rank()
        == get_pipeline_model_parallel_world_size() - 1
    )
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        if _VIRTUAL_PP_RANK != _VIRTUAL_PP_SIZE - 1:
            return last & False
    return last


# ---------------------------------------------------------------------------
# Encoder/decoder split bookkeeping (ref parallel_state.py:251-286,345-354).
# The split rank is a host-level int; the before/after predicates are traced
# booleans like is_pipeline_first_stage, valid inside mesh programs only.


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PP_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: int) -> None:
    global _PP_SPLIT_RANK
    _PP_SPLIT_RANK = rank


def is_pipeline_stage_before_split(rank=None):
    """True if this stage executes encoder blocks for an enc-dec model
    (ref parallel_state.py:251-263). Always True when pp == 1 or no split
    rank is set, as in the reference."""
    if get_pipeline_model_parallel_world_size() == 1 or _PP_SPLIT_RANK is None:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    return rank < _PP_SPLIT_RANK


def is_pipeline_stage_after_split(rank=None):
    """True if this stage executes decoder blocks for an enc-dec model
    (ref parallel_state.py:266-278)."""
    if get_pipeline_model_parallel_world_size() == 1 or _PP_SPLIT_RANK is None:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    return rank >= _PP_SPLIT_RANK


def is_pipeline_stage_at_split():
    """True on the last encoder stage (the next stage runs decoder blocks;
    ref parallel_state.py:281-286). Host-level ``False`` when pp == 1 or no
    split rank is set — those cases have no enc/dec boundary, and reading a
    traced rank for them would make the predicate unusable outside mesh
    programs where its siblings still work."""
    if get_pipeline_model_parallel_world_size() == 1 or _PP_SPLIT_RANK is None:
        return False
    rank = get_pipeline_model_parallel_rank()
    return is_pipeline_stage_before_split(rank) & is_pipeline_stage_after_split(
        rank + 1
    )


# ---------------------------------------------------------------------------
# Virtual pipeline bookkeeping (host-level ints, ref parallel_state.py:297-320)

def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PP_SIZE


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PP_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    global _VIRTUAL_PP_RANK
    _VIRTUAL_PP_RANK = rank


# ---------------------------------------------------------------------------
# Axis-name exports (the "process group" handles; ref get_*_group())

def get_tensor_model_parallel_axis() -> str:
    return TP_AXIS


def get_pipeline_model_parallel_axis() -> str:
    return PP_AXIS


def get_sequence_parallel_axis() -> str:
    return SP_AXIS


def get_data_parallel_axis() -> str:
    return DP_AXIS


def get_model_parallel_axes():
    return model_parallel_axes(get_mesh())


def get_rank_info() -> str:
    """Human-readable identity for logging (ref parallel_state.py:186-204)."""
    if _MESH is None:
        return "mesh uninitialized"
    return get_mesh_axes_str()

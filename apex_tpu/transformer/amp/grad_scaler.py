"""Model-parallel-aware gradient scaler.

Reference: ``apex/transformer/amp/grad_scaler.py:8-106`` — a
``torch.cuda.amp.GradScaler`` subclass whose ``_maybe_opt_step`` and
``update`` all-reduce (MAX) the per-rank ``found_inf`` flag over the
model-parallel group, so a TP/PP shard that overflows makes *every* rank skip
the step in lockstep.

TPU re-design: a thin policy over :class:`apex_tpu.amp.LossScaler` that bakes
the cross-axis agreement in. Under SPMD the flag disagreement can only arise
from genuinely different shard values (each rank checks its own param
shards), so the ``pmax`` here plays exactly the reference's role. Pure
functional: state in, state out, usable inside a jitted train step.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaler, LossScalerState


class GradScaler(LossScaler):
    """``LossScaler`` whose overflow decision is agreed across model-parallel
    mesh axes (ref grad_scaler.py:25-60).

    ``axis_names``: the model-parallel axes to reduce over; defaults to
    every non-dp axis of the installed mesh at call time.
    """

    def __init__(
        self,
        init_scale: float = 2.0 ** 16,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 2000,
        axis_names: Optional[Sequence[str]] = None,
        **kw: Any,
    ) -> None:
        # always dynamic, like torch.cuda.amp.GradScaler
        super().__init__(
            "dynamic",
            init_scale=float(init_scale),
            scale_factor=growth_factor,
            scale_window=growth_interval,
            backoff_factor=backoff_factor,
            **kw,
        )
        self.axis_names = tuple(axis_names) if axis_names is not None else None

    def _mp_axes(self) -> Sequence[str]:
        if self.axis_names is not None:
            return self.axis_names
        from apex_tpu.transformer import parallel_state

        return parallel_state.get_model_parallel_axes()

    def sync_found_inf(self, found_inf: jnp.ndarray) -> jnp.ndarray:
        """MAX-allreduce of the flag over the MP axes (ref :25-46). Must run
        inside the mesh program."""
        out = found_inf
        for a in self._mp_axes():
            out = lax.pmax(out, a)
        return out

    def update_scale(
        self, state: LossScalerState, found_inf: jnp.ndarray, *, synced: bool = True
    ) -> Tuple[LossScalerState, jnp.ndarray]:
        """Ref ``update`` (:61-106). ``synced=False`` additionally runs
        :meth:`sync_found_inf` first (then must be called inside the mesh
        program)."""
        if not synced:
            found_inf = self.sync_found_inf(found_inf)
        return super().update_scale(state, found_inf)

"""Model-parallel AMP (ref ``apex/transformer/amp/``)."""

from apex_tpu.transformer.amp.grad_scaler import GradScaler  # noqa: F401

"""Megatron pretraining batch samplers (ref ``apex/transformer/_data``)."""

from apex_tpu.transformer._data._batchsampler import (  # noqa: F401
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]

"""DP-rank-aware pretraining batch samplers.

Reference: ``apex/transformer/_data/_batchsampler.py:38,102`` — samplers that
(1) resume from ``consumed_samples``, (2) slice the global minibatch so each
data-parallel rank reads only its shard, (3) support changing the local
minibatch size mid-run (batch-size ramp-up). Pure Python index generators —
no torch dependency in the first place; they plug into any data source
(e.g. grain / tf.data / numpy arrays indexed per step).
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np


class MegatronPretrainingSampler:
    """Sequential sampler (ref :38-100): walk ``consumed_samples →
    total_samples`` accumulating a global minibatch of
    ``local_minibatch_size × data_parallel_size`` indices and yield this
    rank's contiguous slice."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        local_minibatch_size: int,
        data_parallel_rank: int,
        data_parallel_size: int,
        drop_last: bool = True,
    ):
        if total_samples <= 0:
            raise RuntimeError(
                f"total_samples must be positive, got {total_samples}")
        if consumed_samples >= total_samples:
            raise RuntimeError(
                f"already consumed {consumed_samples} of {total_samples} "
                f"samples — nothing left to iterate")
        if local_minibatch_size <= 0:
            raise RuntimeError(
                f"local_minibatch_size must be positive, got "
                f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise RuntimeError(
                f"data_parallel_size must be positive, got "
                f"{data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise RuntimeError(
                f"data_parallel_rank {data_parallel_rank} out of range for "
                f"data_parallel_size {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.drop_last = drop_last

    def __len__(self) -> int:
        return self.total_samples

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new_size: int) -> None:
        self._local_minibatch_size = new_size

    def get_start_end_idx(self) -> tuple:
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    def __iter__(self) -> Iterator[List[int]]:
        batch: List[int] = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == (self.local_minibatch_size
                              * self.data_parallel_size):
                start, end = self.get_start_end_idx()
                yield batch[start:end]
                batch = []
        if batch and not self.drop_last:
            start, end = self.get_start_end_idx()
            yield batch[start:end]


class MegatronPretrainingRandomSampler:
    """Shuffling sampler (ref :102-177): deterministic per-epoch permutation
    seeded by the epoch index, resumable mid-epoch from ``consumed_samples``;
    each rank permutes only its own bucket of the sample space."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        local_minibatch_size: int,
        data_parallel_rank: int,
        data_parallel_size: int,
    ) -> None:
        if total_samples <= 0:
            raise ValueError(
                f"total_samples must be positive, got {total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(
                f"local_minibatch_size must be positive, got "
                f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError(
                f"data_parallel_size must be positive, got "
                f"{data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                f"data_parallel_rank {data_parallel_rank} out of range for "
                f"data_parallel_size {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.last_batch_size = (
            self.total_samples
            % (self._local_minibatch_size * data_parallel_size))

    def __len__(self) -> int:
        return self.total_samples

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new_size: int) -> None:
        self._local_minibatch_size = new_size

    def __iter__(self) -> Iterator[List[int]]:
        active_total = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total
        current_epoch_samples = self.consumed_samples % active_total
        assert current_epoch_samples % (self.local_minibatch_size
                                        * self.data_parallel_size) == 0

        # per-rank bucket of the (shuffled) sample space
        bucket_size = (active_total // self.data_parallel_size)
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = np.random.RandomState(seed=self.epoch)
        random_idx = rng.permutation(bucket_size) + start_idx
        idx_range = random_idx[bucket_offset:].tolist()

        batch: List[int] = []
        for idx in idx_range:
            batch.append(int(idx))
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (self.local_minibatch_size
                                          * self.data_parallel_size)
                yield batch
                batch = []

"""Fused functional ops for the transformer stack (ref
``apex/transformer/functional/__init__.py``)."""

from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    AttnMaskType,
    FusedScaleMaskSoftmax,
)

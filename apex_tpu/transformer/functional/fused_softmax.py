"""FusedScaleMaskSoftmax — the kernel-selection module.

Reference: ``apex/transformer/functional/fused_softmax.py:95-199`` — picks the
causal CUDA kernel (``scaled_upper_triang_masked_softmax_cuda``) or the
padding-mask kernel (``scaled_masked_softmax_cuda``) when the shape/dtype
constraints hold (fp16/bf16, 16 < sk ≤ 2048, ...), else falls back to an
unfused torch softmax with optional fp32 upcast.

TPU re-design: both "kernels" are the custom-VJP functions in
``apex_tpu.ops.softmax`` (XLA fuses scale→mask→softmax into one loop; the
custom VJP reproduces the reference's backward-from-saved-output memory
trade), valid at any sequence length — so ``is_kernel_available`` only
gates on the input-in-half-precision rule that changes *numerics* in the
reference, not on shape limits.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)


from apex_tpu.transformer.enums import AttnMaskType  # noqa: F401,E402


class FusedScaleMaskSoftmax:
    """Ref fused_softmax.py:95-199. Callable module:
    ``softmax(input, mask) -> probs`` over ``(b, np, sq, sk)`` scores.

    ``mask_func`` is the fallback-path mask application (the reference applies
    ``mask_func(input, mask)`` before the unfused softmax, :172-186).
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ) -> None:
        if input_in_fp16 and input_in_bf16:
            raise ValueError("both fp16 and bf16 flags cannot be active")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if scale is not None and not softmax_in_fp32:
            raise ValueError("softmax should be in fp32 when scaled")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """The reference's gate (:126-160) minus the CUDA shape limits."""
        return self.scaled_masked_softmax_fusion and self.input_in_float16

    def __call__(self, input: jnp.ndarray, mask=None) -> jnp.ndarray:
        b, np_, sq, sk = input.shape
        if self.is_kernel_available(mask, b, np_, sq, sk):
            return self.forward_fused_softmax(input, mask)
        return self.forward_torch_softmax(input, mask)

    def forward_fused_softmax(self, input, mask):
        """Ref :162-171."""
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            if input.shape[2] != input.shape[3]:
                raise ValueError("causal mask is only for self attention")
            b, np_, sq, sk = input.shape
            out = scaled_upper_triang_masked_softmax(
                input.reshape(b * np_, sq, sk), scale
            )
            return out.reshape(b, np_, sq, sk)
        return scaled_masked_softmax(input, mask, scale)

    def forward_torch_softmax(self, input, mask):
        """The unfused fallback (ref :172-193): optional fp32 upcast, mask
        via ``mask_func``, plain softmax, downcast."""
        orig_dtype = input.dtype
        if self.input_in_float16 and self.softmax_in_fp32:
            input = input.astype(jnp.float32)
        if self.scale is not None:
            input = input * self.scale
        if mask is not None:
            if self.mask_func is not None:
                input = self.mask_func(input, mask)
            else:
                input = jnp.where(mask, -10000.0, input)
        probs = jax.nn.softmax(input, axis=-1)
        if self.input_in_float16 and self.softmax_in_fp32:
            probs = probs.astype(orig_dtype)
        return probs

"""Preallocated activation storage — API parity for the reference's
MemoryBuffer/RingMemBuffer.

Reference: ``apex/transformer/tensor_parallel/memory.py:34-140`` — a
preallocated flat CUDA tensor handed out as zero-copy views so checkpointed
activations don't churn the caching allocator.

TPU re-design: XLA owns allocation; buffer reuse comes from donation
(``jax.jit(..., donate_argnums)``) and the fact that a jitted step has a
static memory plan — there is no allocator churn to fight. These classes keep
the reference's shape-accounting semantics (allocate typed views out of one
budget, error on overflow) so code written against the reference API ports,
but the "views" are ordinary arrays.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp


class MemoryBuffer:
    """Ref memory.py:34-118: fixed element budget, ``get(shape)`` carves a
    typed view, ``reset()`` rewinds."""

    def __init__(self, name: str, numel: int, dtype, track_usage: bool = False):
        self.name = name
        self.numel = numel
        self.dtype = dtype
        self.track_usage = track_usage
        self.in_use_numel = 0
        self.max_used = 0

    def reset(self):
        self.in_use_numel = 0

    def is_in_use(self) -> bool:
        return self.in_use_numel > 0

    def numel_in_use(self) -> int:
        return self.in_use_numel

    def get(self, shape):
        n = 1
        for s in shape:
            n *= s
        if self.in_use_numel + n > self.numel:
            raise RuntimeError(
                f"MemoryBuffer {self.name!r} overflow: requested {n} elements, "
                f"{self.numel - self.in_use_numel} free of {self.numel}"
            )
        self.in_use_numel += n
        if self.track_usage:
            self.max_used = max(self.max_used, self.in_use_numel)
        return jnp.zeros(shape, self.dtype)

    def print_average_usage(self):
        from apex_tpu._logging import get_logger

        get_logger(__name__).info(
            "MemoryBuffer %s: peak %d / %d elements", self.name, self.max_used,
            self.numel,
        )


class RingMemBuffer:
    """Ref memory.py:121-140: a rotating ring of MemoryBuffers."""

    def __init__(self, name: str, num_buffers: int, numel: int, dtype,
                 track_usage: bool = False):
        self.num_buffers = num_buffers
        self.buffers: List[MemoryBuffer] = [
            MemoryBuffer(f"{name} {i}", numel, dtype, track_usage)
            for i in range(num_buffers)
        ]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        if buf.is_in_use():
            raise RuntimeError("buffer is already in use")
        return buf

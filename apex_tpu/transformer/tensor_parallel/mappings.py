"""The four tensor-parallel collective mappings, as differentiable functions.

Reference: ``apex/transformer/tensor_parallel/mappings.py:23-157`` — four
``torch.autograd.Function``s pairing a forward collective with its transpose
in backward:

====================  =============================  =======================
mapping               forward                        backward
====================  =============================  =======================
copy_to_...           identity                       all-reduce
reduce_from_...       all-reduce                     identity
scatter_to_...        split last dim (keep my slice) all-gather (concat)
gather_from_...       all-gather (concat last dim)   split (keep my slice)
====================  =============================  =======================

TPU re-design: in a ``shard_map`` body JAX tracks which values vary across
each mesh axis (the VMA system) and *derives* the transpose collectives, so
three of the four mappings are raw primitives whose autodiff rules already
match the reference's backward table:

* copy      = ``pcast(to='varying')`` — identity whose transpose is ``psum``
  (the reference's bwd all-reduce, ``mappings.py:77-92``); crucially the psum
  is inserted exactly once, where a hand-written custom-VJP psum would
  double-count against shard_map's own invariant-input reduction.
* reduce    = ``lax.psum`` — its transpose is the identity cast (:95-107).
* scatter   = ``axis_index``-based slice — its transpose (scatter-add + the
  invariant-input psum) reassembles the full gradient = the reference's bwd
  all-gather (:110-121).
* gather    = ``lax.all_gather(tiled)`` — this one DOES need a custom VJP:
  the built-in transpose is ``psum_scatter``, which double-counts when the
  downstream loss is computed redundantly per TP rank (the Megatron pattern:
  every rank holds the gathered activations and computes the same loss). The
  reference's bwd is *split, not reduce-scatter* (:124-135) for exactly this
  reason.

These functions therefore require ``check_vma=True`` (the shard_map default)
— with ``check_vma=False`` JAX cannot insert the copy/scatter transposes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.parallel.mesh import axis_size as _axis_size
from apex_tpu.transformer.tensor_parallel.utils import divide


def _is_varying(x, axis_name: str) -> bool:
    try:
        return axis_name in jax.typeof(x).vma
    except (AttributeError, TypeError):
        return True  # no vma tracking (check_vma=False) — treat as varying


def _pvary(x, axis_name: str):
    """Mark x as device-varying over axis (identity value-wise); transpose is
    psum. No-op if already varying."""
    if _is_varying(x, axis_name):
        return x
    return lax.pcast(x, axis_name, to="varying")


def _split(x, axis_name: str):
    """Keep this rank's slice of the last dim (ref mappings.py:36-52)."""
    world = _axis_size(axis_name)
    chunk = divide(x.shape[-1], world)
    rank = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=x.ndim - 1)


def pvary_like(w, ref):
    """Mark ``w`` varying over every mesh axis ``ref`` varies on (identity
    value-wise; transpose = psum over those axes). Required before feeding a
    replicated parameter together with sharded activations into a
    ``custom_vjp`` op: the opaque vjp rule hides the linearity, so
    shard_map's automatic invariant-input reduction cannot fire — this makes
    the reduction explicit at the pvary transpose, over exactly the axes the
    cotangent (which inherits the activations' vma) will carry."""
    try:
        want = set(jax.typeof(ref).vma)
        have = set(jax.typeof(w).vma)
    except (AttributeError, TypeError):
        return w
    missing = tuple(sorted(want - have))
    if missing:
        w = lax.pcast(w, missing, to="varying")
    return w


def copy_to_tensor_model_parallel_region(x, axis_name: str = TP_AXIS):
    """Identity fwd / all-reduce bwd (ref _CopyToModelParallelRegion,
    mappings.py:77-92). Feeds activations into a column-parallel matmul."""
    return _pvary(x, axis_name)


def reduce_from_tensor_model_parallel_region(x, axis_name: str = TP_AXIS):
    """All-reduce fwd / identity bwd (ref _ReduceFromModelParallelRegion,
    mappings.py:95-107). Collects partial sums out of a row-parallel matmul."""
    return lax.psum(_pvary(x, axis_name), axis_name)


def scatter_to_tensor_model_parallel_region(x, axis_name: str = TP_AXIS):
    """Split-last-dim fwd / all-gather bwd (ref _ScatterToModelParallelRegion,
    mappings.py:110-121)."""
    return _split(_pvary(x, axis_name), axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name: str = TP_AXIS):
    """All-gather-concat fwd / split bwd (ref _GatherFromModelParallelRegion,
    mappings.py:124-135)."""
    return lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _gather_fwd(x, axis_name):
    return gather_from_tensor_model_parallel_region(x, axis_name), None


def _gather_bwd(axis_name, _res, g):
    return (_split(g, axis_name),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ---------------------------------------------------------------------------
# Megatron-style sequence-parallel region boundaries (Korthikanti et al.,
# "Reducing Activation Recomputation"; NOT in the reference snapshot — its
# only SP artifact is activation-shard checkpointing, random.py:244-263).
# Activations in the LN/dropout/residual regions are sharded along the
# SEQUENCE dim over the same tp ranks; entering a TP block all-gathers the
# sequence ("g"), leaving one reduce-scatters it ("ḡ") — the psum a plain
# row-parallel exit would do, split across ranks. Unlike the replicated
# copy/gather mappings above, the input here is genuinely rank-varying, so
# JAX AD's built-in transposes (all_gather ⇄ psum_scatter) are exactly the
# Megatron backward pair and no custom_vjp is needed.


def gather_from_sequence_parallel_region(x, axis_name: str = TP_AXIS,
                                         seq_axis: int = 1):
    """Sequence all-gather entering a column-parallel block (fwd ``g``:
    all_gather; bwd: reduce-scatter). ``x``: the local (b, s/tp, h) shard."""
    return lax.all_gather(
        _pvary(x, axis_name), axis_name, axis=seq_axis, tiled=True)


def reduce_scatter_to_sequence_parallel_region(x, axis_name: str = TP_AXIS,
                                               seq_axis: int = 1):
    """Sequence reduce-scatter leaving a row-parallel block (fwd ``ḡ``:
    psum_scatter; bwd: all_gather). Returns the local (b, s/tp, h) shard."""
    return lax.psum_scatter(
        _pvary(x, axis_name), axis_name, scatter_dimension=seq_axis,
        tiled=True)


def scatter_to_sequence_parallel_region(x, axis_name: str = TP_AXIS,
                                        seq_axis: int = 1):
    """Rank-indexed sequence slice of an axis-invariant (fully reduced)
    tensor — the no-reduction exit from a region where every rank computed
    the full sequence (e.g. the MoE block under Megatron-SP). Backward is
    exact by transposition: slicing an invariant tensor at the rank index
    transposes to a psum of zero-padded shard cotangents, so every rank
    recovers the FULL per-token cotangent. Use
    :func:`reduce_scatter_to_sequence_parallel_region` instead when the
    input still carries per-rank partial sums."""
    world = _axis_size(axis_name)
    chunk = divide(x.shape[seq_axis], world)
    rank = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=seq_axis)

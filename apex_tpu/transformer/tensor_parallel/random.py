"""RNG policy + Megatron-style activation checkpointing, functional JAX.

Reference: ``apex/transformer/tensor_parallel/random.py`` — two subsystems:

1. ``CudaRNGStatesTracker`` (:113-193): named RNG streams so dropout inside
   tensor-parallel regions draws *different* randomness per TP rank (seed +
   2718 + tp_rank) while dropout outside draws the *same* randomness across
   the TP group (plain seed), identically across DP replicas of a position.
2. ``CheckpointFunction`` (:224-294): activation checkpointing that re-runs
   the forward under the restored RNG states, optionally sharding the one
   saved hidden state across TP ranks (``distribute_saved_activations``).

TPU re-design: JAX RNG is already functional — a key is a value, not device
state — so the tracker collapses to **key derivation policy**:
``model_parallel_key`` folds ``axis_index(tp)`` into the key (distinct per TP
rank), ``data_parallel_key`` does not (identical across the TP group). The
stateful ``fork()`` choreography (save/restore device RNG state) has no
analogue and nothing to get wrong. A thin ``RngStatesTracker`` keeps the
reference's named-stream API for porting convenience.

Checkpointing maps to ``jax.checkpoint``: recompute-in-backward with
deterministic RNG is automatic (keys are inputs, replayed exactly), and
``distribute_saved_activations`` maps to a save policy — under GSPMD the
saved residuals inherit the activations' sharding, so the TP-sharded-save
behavior comes from sharding, not from a manual MemoryBuffer.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import DP_AXIS, PP_AXIS, TP_AXIS

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"

# Matches the reference's seed-offset convention (random.py:203-207):
# "2718 is just for fun and any POSITIVE value will work."
_MODEL_PARALLEL_SEED_OFFSET = 2718


def model_parallel_key(key, axis_name: str = TP_AXIS):
    """A key distinct per TP rank, identical across DP replicas — for dropout
    inside tensor-parallel regions (ref random.py:195-221 'tensor-model-
    parallel state'). Valid inside a mesh program."""
    return jax.random.fold_in(
        jax.random.fold_in(key, _MODEL_PARALLEL_SEED_OFFSET),
        lax.axis_index(axis_name),
    )


def data_parallel_key(key):
    """The 'default state': same across the TP group (ref :205-210). JAX keys
    are replicated across the mesh unless folded, so this is the identity —
    named for call-site clarity."""
    return key


def attention_dropout_seed(key, axis_name: str = TP_AXIS):
    """int32 seed for the flash kernels' counter-based attention dropout:
    the TP-folded stream (attention probabilities live on TP-sharded
    heads, so ranks must drop independent entries) reduced to the scalar
    the kernels take. The ONE policy shared by the dense and ring-SP
    attention paths in the GPT/T5 fixtures — the ring's global-position
    hash decorrelates sp shards itself, so sp deliberately does not enter
    (callers must pass an sp-invariant key)."""
    return jax.random.bits(model_parallel_key(key, axis_name),
                           dtype=jnp.uint32).astype(jnp.int32)


def pipeline_stage_key(key, axis_name: str = PP_AXIS):
    """Distinct per pipeline stage — used to decorrelate dropout across
    stages when one traced program serves every stage."""
    return jax.random.fold_in(key, lax.axis_index(axis_name))


class RngStatesTracker:
    """Named key streams with the reference tracker's API surface
    (ref random.py:113-193). Each named stream holds a base key; ``fork``
    yields a fresh subkey each use (the functional analogue of "the state
    advances while forked")."""

    def __init__(self):
        self._keys: Dict[str, jax.Array] = {}
        self._counters: Dict[str, int] = {}
        self._seeds = set()

    def reset(self):
        self._keys = {}
        self._counters = {}
        self._seeds = set()

    def get_states(self):
        """Snapshot of (key, counter) per stream — restoring it replays the
        exact same subkey sequence (the point of the reference's
        ``get_states``/``set_states``, random.py:150-161)."""
        return {
            name: (key, self._counters[name]) for name, key in self._keys.items()
        }

    def set_states(self, states):
        self._keys = {}
        self._counters = {}
        for name, entry in states.items():
            # accept bare keys for backward compatibility (counter restarts)
            key, counter = entry if isinstance(entry, tuple) else (entry, 0)
            self._keys[name] = key
            self._counters[name] = counter

    def add(self, name: str, seed_or_key):
        if name in self._keys:
            raise RuntimeError(f"rng state {name!r} already exists")
        if isinstance(seed_or_key, int):
            if seed_or_key in self._seeds:
                raise RuntimeError(f"seed {seed_or_key} already exists")
            self._seeds.add(seed_or_key)
            key = jax.random.key(seed_or_key)
        else:
            key = seed_or_key
        self._keys[name] = key
        self._counters[name] = 0

    def key(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Next subkey from the named stream."""
        if name not in self._keys:
            raise RuntimeError(f"rng state {name!r} is not added")
        k = jax.random.fold_in(self._keys[name], self._counters[name])
        self._counters[name] += 1
        return k

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Context-manager parity shim: yields the subkey (ref fork():163-183
        swaps device state; here the key is handed to the caller)."""
        yield self.key(name)


_RNG_STATE_TRACKER = RngStatesTracker()


def get_rng_tracker() -> RngStatesTracker:
    """Ref ``get_cuda_rng_tracker`` (random.py:187-189)."""
    return _RNG_STATE_TRACKER


# Alias keeping the reference's import name greppable.
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed: int) -> Dict[str, jax.Array]:
    """Ref ``model_parallel_cuda_manual_seed`` (random.py:195-221): installs
    the default (data-parallel) stream and the model-parallel stream. The
    tracker stores the model-parallel stream with the 2718 offset already
    folded in; what remains device-dependent is the rank fold, which must
    happen inside the mesh program — so fold ``lax.axis_index(tp)`` into the
    key the tracker hands out (NOT :func:`model_parallel_key`, which folds
    the offset again and is meant for raw base keys).
    """
    tracker = get_rng_tracker()
    tracker.reset()
    base = jax.random.key(seed)
    tracker.add("default", base)
    tracker.add(
        _MODEL_PARALLEL_RNG_TRACKER_NAME,
        jax.random.fold_in(base, _MODEL_PARALLEL_SEED_OFFSET),
    )
    return tracker.get_states()


model_parallel_cuda_manual_seed = model_parallel_seed


# ---------------------------------------------------------------------------
# Activation checkpointing (ref CheckpointFunction, random.py:224-294)

#: Save policies, in the vocabulary of the reference's memory knobs:
#: - "nothing": recompute everything (the reference's behavior — only the
#:   *input* is saved, random.py:239-246)
#: - "dots": save MXU outputs, recompute elementwise (usually the TPU sweet
#:   spot — recomputing matmuls wastes MXU cycles)
#: - "everything": no rematerialization (checkpointing off)
CHECKPOINT_POLICIES = {
    "nothing": None,  # jax.checkpoint default: save nothing saveable
    "dots": "dots_with_no_batch_dims_saveable",
    "everything": "everything_saveable",
}


def checkpoint(function: Callable, *args, policy: str = "nothing", **kwargs):
    """Checkpoint ``function(*args)``: run forward without saving
    intermediates; re-run it during backward (ref random.py:291-294).

    RNG correctness is structural: any dropout key is an explicit argument
    and is replayed identically in the recompute — the property the reference
    needs the whole tracker save/restore dance for (:247-253, :268-283).
    ``distribute_saved_activations`` (:239-246) is subsumed by sharding: saved
    residuals inherit the (TP-sharded) activation sharding under GSPMD.
    """
    return checkpoint_wrapper(function, policy=policy)(*args, **kwargs)


def checkpoint_wrapper(function: Callable, policy: str = "nothing") -> Callable:
    if policy not in CHECKPOINT_POLICIES:
        raise ValueError(f"policy must be one of {sorted(CHECKPOINT_POLICIES)}")
    name = CHECKPOINT_POLICIES[policy]
    if name is None:
        return jax.checkpoint(function)
    return jax.checkpoint(function, policy=getattr(jax.checkpoint_policies, name))

"""Tensor parallelism: Megatron-style sharded layers, collective mappings,
vocab-parallel cross-entropy, RNG policy, activation checkpointing.

Reference: ``apex/transformer/tensor_parallel/__init__.py`` export list.
"""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    column_parallel_linear,
    row_parallel_linear,
    set_tensor_model_parallel_attributes,
    sharded_init,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.memory import (  # noqa: F401
    MemoryBuffer,
    RingMemBuffer,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RngStatesTracker,
    checkpoint,
    checkpoint_wrapper,
    data_parallel_key,
    get_cuda_rng_tracker,
    get_rng_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_key,
    model_parallel_seed,
    pipeline_stage_key,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    VocabUtility,
    divide,
    split_tensor_along_last_dim,
)

__all__ = [
    "ColumnParallelLinear",
    "MemoryBuffer",
    "RingMemBuffer",
    "RngStatesTracker",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "VocabUtility",
    "broadcast_data",
    "checkpoint",
    "checkpoint_wrapper",
    "column_parallel_linear",
    "copy_to_tensor_model_parallel_region",
    "data_parallel_key",
    "divide",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "get_cuda_rng_tracker",
    "get_rng_tracker",
    "model_parallel_cuda_manual_seed",
    "model_parallel_key",
    "model_parallel_seed",
    "pipeline_stage_key",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "row_parallel_linear",
    "scatter_to_tensor_model_parallel_region",
    "set_tensor_model_parallel_attributes",
    "sharded_init",
    "split_tensor_along_last_dim",
    "vocab_parallel_cross_entropy",
    "vocab_parallel_embedding",
]

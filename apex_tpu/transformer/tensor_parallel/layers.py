"""Megatron-style tensor-parallel layers: vocab-parallel embedding,
column-parallel and row-parallel linear.

Reference: ``apex/transformer/tensor_parallel/layers.py`` —
``VocabParallelEmbedding`` (:138), ``ColumnParallelLinear`` (:321),
``RowParallelLinear`` (:464), plus the autograd functions
``LinearWithGradAccumulationAndAsyncAllreduce(In16Bit)`` (:217,:269) whose
point is (a) overlapping the input-grad TP all-reduce with the weight-grad
GEMM and (b) accumulating dW straight into an fp32 ``main_grad`` buffer
(``fused_weight_gradient_mlp_cuda``).

TPU re-design:

* Layers are flax modules whose parameters are the **local shard** — the
  natural ``shard_map`` formulation: one program per device, weights of shape
  ``(in, out/tp)`` (column) / ``(in/tp, out)`` (row). (JAX kernels are
  ``(in, out)``; the reference stores the torch-transposed ``(out, in)``.)
* The backward collectives come from the :mod:`mappings` custom-VJP functions.
  Comm/compute overlap: for *independent* ops XLA's latency-hiding scheduler
  reorders the psum against the dW dot on its own — but it cannot overlap a
  **dependent** collective→matmul chain (the SP entry all-gather feeding the
  GEMM, the GEMM feeding the exit reduce-scatter/psum). ``overlap_comm=True``
  switches those sites to :mod:`apex_tpu.comm.overlap`'s decomposed
  collective matmuls — ppermute rings interleaved with partial GEMMs, the
  reference's "async allreduce" capability (:217-269) generalized — with
  custom VJPs so backward overlaps too.
* Gradient-accumulation fusion into fp32 main_grad is
  :mod:`apex_tpu.optimizers.grad_accumulation` — ``accumulate_gradients``
  scans microbatches adding model-dtype dW into an fp32 accumulator; XLA
  fuses the cast+add into the dW GEMM epilogue.
* Weight init is **TP-invariant**: the full (master) weight is initialized
  from a replicated RNG and each rank keeps its slice — the semantics of the
  reference's ``_initialize_affine_weight_cpu`` (:89-120) master-weight path,
  so checkpoints and tests are independent of the TP degree.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.parallel.mesh import axis_size as _axis_size
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    pvary_like,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility, divide


# ---------------------------------------------------------------------------
# TP parameter attributes (ref layers.py:55-87). In JAX the "attribute" worth
# keeping is the partition spec; these helpers build flax metadata boxes that
# GSPMD-style code can read with nn.get_partition_spec.

def set_tensor_model_parallel_attributes(
    init_fn: Callable, is_parallel: bool, dim: int, stride: int = 1, ndim: int = 2
):
    """Wrap an initializer with TP partition metadata (ref :55-66). Under
    shard_map the metadata is advisory; under pjit it becomes the sharding."""
    if not is_parallel:
        return init_fn
    names = [None] * ndim
    names[dim] = TP_AXIS
    return nn.with_partitioning(init_fn, tuple(names))


def param_is_tensor_parallel(meta) -> bool:
    """Ref ``param_is_not_tensor_parallel_duplicate`` (:67-76), inverted."""
    return isinstance(meta, nn.Partitioned) or getattr(meta, "names", None)


# ---------------------------------------------------------------------------
# TP-invariant init: initialize the full master weight, keep this rank's slice
# (ref _initialize_affine_weight_cpu, layers.py:89-120).

def sharded_init(
    base_init: Callable, full_shape, partition_dim: int, axis_name: str = TP_AXIS
) -> Callable:
    """Initializer producing this rank's slice of a master weight initialized
    at full shape. Must run inside a mesh program so ``axis_index`` resolves.
    """

    def init(key, shard_shape, dtype=jnp.float32):
        master = base_init(key, tuple(full_shape), dtype)
        rank = lax.axis_index(axis_name)
        chunk = shard_shape[partition_dim]
        return lax.dynamic_slice_in_dim(master, rank * chunk, chunk, partition_dim)

    return init


# ---------------------------------------------------------------------------
# Functional cores


def vocab_parallel_embedding(ids, weight, axis_name: str = TP_AXIS,
                             sequence_parallel: bool = False):
    """Lookup into a vocab-sharded embedding table (ref forward :191-215).

    ``weight``: (vocab/tp, hidden) local shard. Out-of-range ids contribute a
    zero row; psum assembles each token's row from its owner rank. With
    ``sequence_parallel`` the psum is a reduce-scatter along seq (Megatron-SP
    embedding exit) and the result is the (b, s/tp, hidden) shard.
    """
    per_partition = weight.shape[0]
    rank = lax.axis_index(axis_name)
    start, end = VocabUtility.vocab_range_from_per_partition_vocab_size(
        per_partition, rank, _axis_size(axis_name)
    )
    mask = (ids < start) | (ids >= end)
    local_ids = jnp.where(mask, 0, ids - start)
    out = jnp.take(weight, local_ids, axis=0)
    out = jnp.where(mask[..., None], jnp.zeros((), out.dtype), out)
    if sequence_parallel:
        return reduce_scatter_to_sequence_parallel_region(out, axis_name)
    return reduce_from_tensor_model_parallel_region(out, axis_name)


def column_parallel_linear(
    x,
    kernel,
    bias=None,
    *,
    gather_output: bool = True,
    axis_name: str = TP_AXIS,
    sequence_parallel: bool = False,
    overlap_comm: bool = False,
):
    """Y_i = X @ A_i (+ b_i); A sharded on the output dim (ref forward
    :443-463). ``kernel``: (in, out/tp). With ``sequence_parallel`` the
    input is the sequence-local shard (b, s/tp, h) and is all-gathered
    along seq on entry (Megatron-SP ``g``; reduce-scatter in backward).
    ``overlap_comm`` decomposes that entry gather into the
    :func:`~apex_tpu.comm.overlap.all_gather_matmul` ppermute ring so the
    hops hide behind partial GEMMs, forward and backward (no-op without
    ``sequence_parallel`` — the plain entry is a collective-free copy)."""
    if sequence_parallel and overlap_comm:
        from apex_tpu.comm.overlap import all_gather_matmul

        k = pvary_like(kernel.astype(x.dtype), x)
        y = all_gather_matmul(x, k, axis_name=axis_name, gather_axis=1)
    else:
        if sequence_parallel:
            x = gather_from_sequence_parallel_region(x, axis_name)
        else:
            x = copy_to_tensor_model_parallel_region(x, axis_name)
        # dot in the input dtype: the MXU accumulates bf16 x bf16 in fp32
        # regardless, so the result equals the explicit preferred-fp32 +
        # round-to-bf16 form — but a bf16 OUTPUT keeps the backward's
        # cotangents bf16, so dX/dW also ride the fast MXU path instead
        # of fp32 dots (~4x slower); with fp32 params nothing changes
        y = jnp.dot(x, kernel.astype(x.dtype))
    if bias is not None:
        y = y + bias
    if gather_output:
        y = gather_from_tensor_model_parallel_region(y, axis_name)
    return y


def row_parallel_linear(
    x,
    kernel,
    bias=None,
    *,
    input_is_parallel: bool = False,
    axis_name: str = TP_AXIS,
    sequence_parallel: bool = False,
    overlap_comm: bool = False,
):
    """Y = sum_i X_i @ A_i (+ b); A sharded on the input dim (ref forward
    :560-576). ``kernel``: (in/tp, out); bias added once, after the reduce.
    With ``sequence_parallel`` the partial sums are reduce-scattered along
    seq (Megatron-SP ``ḡ``) and the result is the (b, s/tp, out) shard.
    ``overlap_comm`` decomposes the exit collective
    (:func:`~apex_tpu.comm.overlap.matmul_reduce_scatter` under SP,
    :func:`~apex_tpu.comm.overlap.matmul_all_reduce` otherwise) into a
    ppermute ring of partial GEMMs; needs the seq dim divisible by the
    axis size, and the non-SP result comes back TYPE-varying (equal
    values) rather than axis-invariant — the monolithic value either way,
    up to fp addition reorder in the ring sum."""
    if not input_is_parallel:
        x = scatter_to_tensor_model_parallel_region(x, axis_name)
    if overlap_comm:
        from apex_tpu.comm.overlap import (
            matmul_all_reduce,
            matmul_reduce_scatter,
        )

        k = pvary_like(kernel.astype(x.dtype), x)
        if sequence_parallel:
            y = matmul_reduce_scatter(x, k, axis_name=axis_name,
                                      scatter_axis=1)
        else:
            y = matmul_all_reduce(x, k, axis_name=axis_name, scatter_axis=1)
    else:
        # dot in the input dtype: the MXU accumulates bf16 x bf16 in fp32
        # regardless, so the result equals the explicit preferred-fp32 +
        # round-to-bf16 form — but a bf16 OUTPUT keeps the backward's
        # cotangents bf16, so dX/dW also ride the fast MXU path instead
        # of fp32 dots (~4x slower); with fp32 params nothing changes
        y = jnp.dot(x, kernel.astype(x.dtype))
        if sequence_parallel:
            y = reduce_scatter_to_sequence_parallel_region(y, axis_name)
        else:
            y = reduce_from_tensor_model_parallel_region(y, axis_name)
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# Modules


def _tp_world() -> int:
    from apex_tpu.transformer import parallel_state

    return parallel_state.get_tensor_model_parallel_world_size()


class VocabParallelEmbedding(nn.Module):
    """Ref layers.py:138-215. Params are the local (vocab/tp, hidden) shard;
    call inside a mesh program."""

    num_embeddings: int
    embedding_dim: int
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    params_dtype: jnp.dtype = jnp.float32
    axis_name: str = TP_AXIS

    @nn.compact
    def __call__(self, ids):
        per_partition = divide(self.num_embeddings, _tp_world())
        weight = self.param(
            "weight",
            sharded_init(
                self.init_method,
                (self.num_embeddings, self.embedding_dim),
                partition_dim=0,
                axis_name=self.axis_name,
            ),
            (per_partition, self.embedding_dim),
            self.params_dtype,
        )
        return vocab_parallel_embedding(ids, weight, self.axis_name)


class ColumnParallelLinear(nn.Module):
    """Ref layers.py:321-463. Returns ``(output, output_bias)`` exactly like
    the reference (``output_bias`` is the unapplied bias iff skip_bias_add)."""

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = True
    init_method: Callable = nn.initializers.xavier_normal()
    skip_bias_add: bool = False
    params_dtype: jnp.dtype = jnp.float32
    axis_name: str = TP_AXIS
    sequence_parallel: bool = False
    # decompose the SP entry all-gather into the comm.overlap ppermute
    # ring (the reference's sequence_parallel_enabled + async-comm knobs)
    overlap_comm: bool = False

    @nn.compact
    def __call__(self, x):
        out_per_partition = divide(self.output_size, _tp_world())
        kernel = self.param(
            "kernel",
            sharded_init(
                self.init_method,
                (self.input_size, self.output_size),
                partition_dim=1,
                axis_name=self.axis_name,
            ),
            (self.input_size, out_per_partition),
            self.params_dtype,
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (out_per_partition,),
                       self.params_dtype)
            if self.use_bias
            else None
        )
        y = column_parallel_linear(
            x,
            kernel,
            None if self.skip_bias_add else bias,
            gather_output=self.gather_output,
            axis_name=self.axis_name,
            sequence_parallel=self.sequence_parallel,
            overlap_comm=self.overlap_comm,
        )
        return y, (bias if self.skip_bias_add else None)


class RowParallelLinear(nn.Module):
    """Ref layers.py:464-576. Returns ``(output, output_bias)``."""

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = False
    init_method: Callable = nn.initializers.xavier_normal()
    skip_bias_add: bool = False
    params_dtype: jnp.dtype = jnp.float32
    axis_name: str = TP_AXIS
    sequence_parallel: bool = False
    # decompose the exit reduce-scatter/psum into the comm.overlap rings
    overlap_comm: bool = False

    @nn.compact
    def __call__(self, x):
        in_per_partition = divide(self.input_size, _tp_world())
        kernel = self.param(
            "kernel",
            sharded_init(
                self.init_method,
                (self.input_size, self.output_size),
                partition_dim=0,
                axis_name=self.axis_name,
            ),
            (in_per_partition, self.output_size),
            self.params_dtype,
        )
        # Bias is NOT sharded; initialized zero (ref :540-548) and added after
        # the reduce so it is applied exactly once.
        bias = (
            self.param("bias", nn.initializers.zeros, (self.output_size,),
                       self.params_dtype)
            if self.use_bias
            else None
        )
        y = row_parallel_linear(
            x,
            kernel,
            None if self.skip_bias_add else bias,
            input_is_parallel=self.input_is_parallel,
            axis_name=self.axis_name,
            sequence_parallel=self.sequence_parallel,
            overlap_comm=self.overlap_comm,
        )
        return y, (bias if self.skip_bias_add else None)

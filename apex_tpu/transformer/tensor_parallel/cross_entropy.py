"""Vocab-parallel softmax cross-entropy.

Reference: ``apex/transformer/tensor_parallel/cross_entropy.py:23-103`` —
computes CE over logits whose vocab dim is sharded across TP ranks with three
collectives: all-reduce **MAX** of per-position logit maxima (numerical
stability), all-reduce **SUM** of the locally-gathered target logits (each
position's target lives on exactly one rank; others contribute 0), and
all-reduce **SUM** of the local exp-sums. Backward is `(softmax - onehot)`
masked to the local vocab range, scaled by the upstream grad.

TPU re-design: one ``custom_vjp`` function over the tp axis using
``lax.pmax``/``lax.psum``; softmax is recomputed locally in fp32 and the
residuals saved for backward are exactly the reference's
(softmax, target_mask, masked_target) — saving the softmax instead of the
logits is the memory trade the CUDA kernel makes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target, axis_name=TP_AXIS):
    """Per-position CE loss (same shape as ``target``), fp32.

    ``vocab_parallel_logits``: (..., vocab/tp) — this rank's vocab shard.
    ``target``: (...) integer ids in the **global** vocab.
    Ref ``cross_entropy.py:100-103``.
    """
    loss, _ = _ce_fwd(vocab_parallel_logits, target, axis_name)
    return loss


def _local_vocab_info(partition_vocab_size, axis_name):
    rank = lax.axis_index(axis_name)
    world = lax.axis_size(axis_name)
    start, end = VocabUtility.vocab_range_from_per_partition_vocab_size(
        partition_vocab_size, rank, world
    )
    return start, end


def _ce_fwd(logits, target, axis_name):
    partition_vocab = logits.shape[-1]
    x32 = logits.astype(jnp.float32)

    # Global max for stability (ref :27-33, all_reduce MAX).
    logits_max = lax.pmax(jnp.max(x32, axis=-1), axis_name)
    x32 = x32 - logits_max[..., None]

    # Local index of each target, masked outside this rank's range (ref :36-45).
    vocab_start, vocab_end = _local_vocab_info(partition_vocab, axis_name)
    target_mask = (target < vocab_start) | (target >= vocab_end)
    masked_target = jnp.where(target_mask, 0, target - vocab_start)

    # Target logit: zero contribution off-rank, psum picks up the owner's
    # value (ref :47-61).
    predicted = jnp.take_along_axis(x32, masked_target[..., None], axis=-1)[..., 0]
    predicted = lax.psum(jnp.where(target_mask, 0.0, predicted), axis_name)

    # Global partition function (ref :63-69).
    exp_logits = jnp.exp(x32)
    sum_exp = lax.psum(jnp.sum(exp_logits, axis=-1), axis_name)

    loss = jnp.log(sum_exp) - predicted  # ref :71-72
    # Memory trade (the contrib-xentropy one, ``apex/contrib/csrc/xentropy``):
    # save the ORIGINAL-dtype logits + per-position max and log-partition and
    # recompute softmax in backward, instead of materializing an fp32 softmax
    # (2-4x the residual bytes at GPT vocab sizes).
    return loss, (logits, logits_max, jnp.log(sum_exp), target_mask,
                  masked_target)


def _ce_bwd(axis_name, res, g):
    logits, logits_max, log_sum_exp, target_mask, masked_target = res
    # softmax = exp(x - max - logZ), recomputed fp32 (ref backward :80-100)
    softmax = jnp.exp(
        logits.astype(jnp.float32) - logits_max[..., None]
        - log_sum_exp[..., None])
    iota = lax.broadcasted_iota(jnp.int32, softmax.shape, softmax.ndim - 1)
    is_target = (iota == masked_target[..., None]) & ~target_mask[..., None]
    grad = (softmax - is_target.astype(jnp.float32)) * g[..., None].astype(
        jnp.float32)
    return grad.astype(logits.dtype), None


vocab_parallel_cross_entropy.defvjp(_ce_fwd, _ce_bwd)

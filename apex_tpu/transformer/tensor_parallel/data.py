"""Input-data broadcast across the tensor-parallel group.

Reference: ``apex/transformer/tensor_parallel/data.py:77-121`` — only TP rank
0 reads the batch from the data iterator; ``broadcast_data`` flattens the
dict of int64 tensors, ``torch.distributed.broadcast``s one buffer across the
TP group, and unpacks. Helpers ``_check_data_types`` / ``_build_key_size_numel_dictionaries``
(:17-75) validate dtypes and ship the shapes first.

TPU re-design: under single-controller SPMD every device in a mesh program
sees the same traced inputs, so the *intra-process* broadcast is structural.
What remains is the multi-host case: each JAX process must feed identical
host data for TP-replicated inputs. ``broadcast_data`` therefore (a) verifies
dtypes like the reference, and (b) on multi-process runs routes through
``multihost_utils.broadcast_one_to_all`` so process 0's batch wins — the
honest analogue of "TP rank 0 reads, everyone else receives".
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _check_data_types(keys: Sequence[str], data: Dict[str, jnp.ndarray], dtype):
    """Ref data.py:17-23."""
    for k in keys:
        if np.dtype(data[k].dtype) != np.dtype(dtype):
            raise TypeError(
                f"{k} has data type {data[k].dtype} which is different than {dtype}"
            )


def _build_key_size_numel_dictionaries(keys, data):
    """Ref data.py:26-75 — shape/numel bookkeeping (no collective needed:
    shapes are host metadata and identical by construction under SPMD)."""
    key_size = {k: tuple(data[k].shape) for k in keys}
    key_numel = {k: int(np.prod(data[k].shape)) for k in keys}
    total_numel = sum(key_numel.values())
    return key_size, key_numel, total_numel


def broadcast_data(keys, data, datatype=jnp.int32):
    """Broadcast process-0's data members to all processes (ref data.py:77-121).

    ``keys``: members to broadcast; ``data``: dict of same-shaped arrays on
    every process; returns dict of device arrays.
    """
    key_size, _, _ = _build_key_size_numel_dictionaries(keys, data)
    _check_data_types(keys, data, datatype)

    if jax.process_count() == 1:
        return {k: jnp.asarray(data[k]) for k in keys}

    from jax.experimental import multihost_utils

    flat = jnp.concatenate(
        [jnp.asarray(data[k], datatype).reshape(-1) for k in keys]
    )
    flat = multihost_utils.broadcast_one_to_all(flat)
    out = {}
    offset = 0
    for k in keys:
        n = int(np.prod(key_size[k]))
        out[k] = flat[offset : offset + n].reshape(key_size[k])
        offset += n
    return out

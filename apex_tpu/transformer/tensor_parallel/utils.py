"""Shape/vocab partition helpers for tensor parallelism.

Reference: ``apex/transformer/tensor_parallel/utils.py:22-55`` (
``split_tensor_along_last_dim``, ``VocabUtility``) and ``apex/transformer/
utils.py`` (``divide``/``ensure_divisibility``).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """Split along the last dim into equal chunks (ref utils.py:22-39).

    Returns a tuple of arrays. (On TPU there is no contiguity knob — XLA owns
    layout — so the reference's ``contiguous_split_chunks`` flag has no
    analogue.)
    """
    divide(tensor.shape[-1], num_partitions)  # divisibility check
    return tuple(jnp.split(tensor, num_partitions, axis=-1))


class VocabUtility:
    """Vocab range [first, last) owned by ``rank`` out of ``world_size``
    (ref utils.py:40-55)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ):
        index_f = rank * per_partition_vocab_size
        index_l = index_f + per_partition_vocab_size
        return index_f, index_l

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        per_partition = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank, world_size
        )

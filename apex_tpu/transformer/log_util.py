"""Transformer-stack logging helpers (ref ``apex/transformer/log_util.py``)."""

from __future__ import annotations

import logging
import os


def get_transformer_logger(name: str) -> logging.Logger:
    name_wo_ext = os.path.splitext(name)[0]
    return logging.getLogger(name_wo_ext)


def set_logging_level(verbosity) -> None:
    """Change the library root logger severity (ref set_logging_level)."""
    from apex_tpu._logging import get_logger

    get_logger("apex_tpu").setLevel(verbosity)

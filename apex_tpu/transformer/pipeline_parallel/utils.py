"""Pipeline-parallel utilities.

Reference: ``apex/transformer/pipeline_parallel/utils.py`` — microbatch
calculator setup (:58), microbatch slicing (:105-139), DP loss averaging
(:242), params-l2-norm (:213), memory report (:253), GPT left-to-right mask
builder (:303).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import DP_AXIS
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    NumMicroBatchesCalculator,
    build_num_microbatches_calculator,
)

_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """Ref utils.py:58-80 (singleton with re-init guard)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def _ensure_calculator() -> NumMicroBatchesCalculator:
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError(
            "num microbatches calculator is not initialized; call "
            "setup_microbatch_calculator() first"
        )
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_num_microbatches() -> int:
    return _ensure_calculator().get()


def get_current_global_batch_size() -> int:
    return _ensure_calculator().get_current_global_batch_size()


def get_micro_batch_size() -> int:
    return _ensure_calculator().micro_batch_size


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    _ensure_calculator().update(consumed_samples, consistency_check)


# ---------------------------------------------------------------------------


def average_losses_across_data_parallel_group(losses: Sequence[jnp.ndarray],
                                              axis_name: str = DP_AXIS):
    """Ref utils.py:242-252. Inside a mesh program: pmean of the stacked
    losses over the dp axis."""
    stacked = jnp.stack([jnp.asarray(l) for l in losses])
    return lax.pmean(stacked, axis_name)


def _spec_axes(spec) -> set:
    """Mesh axis names a PartitionSpec entry shards over."""
    out = set()
    for entry in (spec or ()):
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            if a is not None:
                out.add(a)
    return out


def calc_params_l2_norm(params: Any, model_parallel_axes: Sequence[str] = (),
                        specs: Any = None):
    """Global parameter L2 norm (ref utils.py:213-240).

    Without ``specs``: sum of squares over the local pytree, psum over ALL
    ``model_parallel_axes`` (assumes every leaf is sharded over each axis).

    With ``specs`` (a PartitionSpec pytree matching ``params``): each
    leaf's square-sum is psum'd only over the model-parallel axes that
    actually shard THAT leaf, so TP-replicated leaves (LayerNorm weights,
    row-parallel biases) are counted once instead of tp times — the
    reference's ``param_is_not_tensor_parallel_duplicate`` dedup
    (ref tensor_parallel/layers.py:55-58).
    """
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        _pvary,
    )

    mp = set(model_parallel_axes)

    def leaf_sq(p, spec):
        sq = jnp.sum(jnp.square(p.astype(jnp.float32)))
        for a in sorted(_spec_axes(spec) & mp if specs is not None else mp):
            sq = lax.psum(_pvary(sq, a), a)
        return sq

    if specs is None:
        sqs = [leaf_sq(p, None) for p in jax.tree.leaves(params)]
    else:
        sqs = jax.tree.leaves(jax.tree.map(leaf_sq, params, specs))
    total = sum(sqs)
    # make the result invariant over the remaining axes for downstream use
    for a in sorted(mp):
        total = lax.pmax(_pvary(total, a), a)
    return jnp.sqrt(total)


def clip_grad_norm(grads: Any, max_norm: float,
                   model_parallel_axes: Sequence[str] = (),
                   specs: Any = None):
    """Megatron-style global-norm gradient clipping (the reference pairs
    ``calc_params_l2_norm``-class dedup with ``clip_grad_norm_fp32``; apex
    surfaces it as ``fp16_utils.clip_grad_norm`` and the ZeRO optimizers'
    ``max_grad_norm``). Returns ``(clipped_grads, global_norm)``; the same
    ``specs`` dedup rules as :func:`calc_params_l2_norm` apply."""
    norm = calc_params_l2_norm(grads, model_parallel_axes, specs)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads), norm


def report_memory(name: str = "") -> str:
    """Ref utils.py:253-270 — CUDA allocator stats; here: per-device live
    bytes from the TPU/host allocator."""
    lines = []
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except (RuntimeError, AttributeError, jax.errors.JaxRuntimeError):
            pass
        used = stats.get("bytes_in_use", 0)
        peak = stats.get("peak_bytes_in_use", 0)
        lines.append(
            f"[{name}] {d}: in_use={used / 2**20:.1f}MiB "
            f"peak={peak / 2**20:.1f}MiB"
        )
    report = "\n".join(lines)
    from apex_tpu._logging import get_logger

    get_logger(__name__).info("%s", report)
    return report


# ---------------------------------------------------------------------------


def get_ltor_masks_and_position_ids(
    data: jnp.ndarray,
    eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Build GPT left-to-right masks + position ids (ref utils.py:303-367).

    Returns ``(attention_mask, loss_mask, position_ids)`` with the reference's
    conventions: attention_mask boolean with True = MASKED OUT (shape
    ``(b, 1, seq, seq)``), loss_mask float (0 at eod when ``eod_mask_loss``),
    position_ids ``(b, seq)``.

    The reference's per-document reset path walks eod positions in a Python
    loop (:330-360); here it is vectorized: the document id of each token is
    ``cumsum(prev-token == eod)``, attention is additionally masked across
    document boundaries, and position ids restart via a segment-local
    cumulative count.
    """
    b, seq = data.shape
    causal = jnp.tril(jnp.ones((seq, seq), dtype=bool))
    attention_mask = jnp.broadcast_to(causal, (b, 1, seq, seq))

    loss_mask = jnp.ones((b, seq), dtype=jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(seq), (b, seq))

    if reset_position_ids or reset_attention_mask:
        prev_is_eod = jnp.concatenate(
            [jnp.zeros((b, 1), dtype=bool), (data == eod_token)[:, :-1]], axis=1
        )
        doc_id = jnp.cumsum(prev_is_eod.astype(jnp.int32), axis=1)
        if reset_attention_mask:
            same_doc = doc_id[:, :, None] == doc_id[:, None, :]
            attention_mask = attention_mask & same_doc[:, None, :, :]
        if reset_position_ids:
            # position within document: index - index-of-document-start
            idx = jnp.broadcast_to(jnp.arange(seq), (b, seq))
            doc_start = jnp.where(prev_is_eod, idx, 0)
            doc_start = jax.lax.cummax(doc_start, axis=1)
            position_ids = idx - doc_start

    # flip to the reference's "True = masked out" convention (utils.py:365)
    return ~attention_mask, loss_mask, position_ids


def print_rank_0(message: str) -> None:
    """Print once per job (ref ``pipeline_parallel/utils.py:159-168``): under
    SPMD all devices run one program per host, so "rank 0" = host process 0."""
    import jax

    if jax.process_index() == 0:
        print(message, flush=True)


def print_rank_last(message: str) -> None:
    """Ref ``:170-177`` (the reference prints on the last pipeline rank; the
    natural multi-host analogue is the last host process)."""
    import jax

    if jax.process_index() == jax.process_count() - 1:
        print(message, flush=True)

"""Pipeline-model-parallel runtime (ref ``apex/transformer/pipeline_parallel/``)."""

from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
from apex_tpu.transformer.pipeline_parallel import utils  # noqa: F401
from apex_tpu.transformer.pipeline_parallel.microbatches import (  # noqa: F401
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    PipelineSpec,
    build_model,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel.utils import (  # noqa: F401
    average_losses_across_data_parallel_group,
    calc_params_l2_norm,
    clip_grad_norm,
    get_current_global_batch_size,
    get_ltor_masks_and_position_ids,
    get_micro_batch_size,
    get_num_microbatches,
    setup_microbatch_calculator,
    update_num_microbatches,
)

"""Global microbatch calculator — constant and ramp-up schedules.

Reference: ``apex/transformer/pipeline_parallel/microbatches.py`` —
``build_num_microbatches_calculator`` (:26), ``ConstantNumMicroBatches``
(:87), ``RampupBatchsizeNumMicroBatches`` (:118). Host-level bookkeeping (the
number of microbatches is a trace-time constant for the schedule programs),
so this is a near-semantic match rather than a re-design: the calculator maps
``consumed_samples`` to (global_batch_size, num_micro_batches).
"""

from __future__ import annotations

from typing import List, Optional, Union


class NumMicroBatchesCalculator:
    """Base interface (ref microbatches.py:70-85)."""

    def __init__(self) -> None:
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Fixed global batch size (ref microbatches.py:87-116)."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int) -> None:
        super().__init__()
        micro_batch_times_data_parallel = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_data_parallel != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_data_parallel
        if self.num_micro_batches < 1:
            raise ValueError("num_micro_batches must be >= 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear global-batch-size ramp-up (ref microbatches.py:118-177):
    batch size grows from ``start_batch_size`` by ``batch_size_increment``
    every ``ramup_samples / steps`` consumed samples until
    ``global_batch_size``."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int) -> None:
        super().__init__()
        if batch_size_increment <= 0:
            raise ValueError("batch_size_increment must be positive")
        if start_batch_size <= 0 or global_batch_size <= 0:
            raise ValueError("batch sizes must be positive")
        diff_batch_size = global_batch_size - start_batch_size
        if diff_batch_size < 0:
            raise ValueError("global_batch_size must be >= start_batch_size")
        if diff_batch_size % batch_size_increment != 0:
            raise ValueError(
                "expected global batch size interval to be divisible by the "
                "batch size increment"
            )
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )

        num_increments = diff_batch_size // batch_size_increment
        # start == global means there is nothing to ramp: jump straight to the
        # final batch size (avoids a 0/0 below).
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0
            else float("inf")
        )
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            if self.current_global_batch_size > self.global_batch_size:
                self.current_global_batch_size = self.global_batch_size
        if consistency_check and (
            self.current_global_batch_size
            % self.micro_batch_times_data_parallel_size != 0
        ):
            raise ValueError(
                f"current global batch size "
                f"({self.current_global_batch_size}) is not divisible by "
                f"micro-batch-size ({self.micro_batch_size}) times "
                f"data parallel size ({self.data_parallel_size})"
            )
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size
        )


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> NumMicroBatchesCalculator:
    """Ref microbatches.py:26-68."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            f"rampup_batch_size takes exactly three ints — start size, "
            f"increment, ramp-up samples — got {rampup_batch_size!r}"
        )
    start, increment, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start, increment, samples, global_batch_size,
        micro_batch_size, data_parallel_size,
    )

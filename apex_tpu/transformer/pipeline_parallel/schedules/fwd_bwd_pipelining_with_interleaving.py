"""Interleaved (virtual-pipeline) schedule: the circular ring.

Reference: ``fwd_bwd_pipelining_with_interleaving.py:25-300`` — each stage
holds ``vp`` model chunks; chunk ``v`` on stage ``s`` owns layer block
``v * pp + s``; microbatches visit stage 0..pp-1 for chunk 0, wrap back to
stage 0 for chunk 1, etc. The interleaving shrinks the pipeline bubble by
``~vp``× at the cost of ``vp``× more p2p traffic.

TPU re-design: the wrap-around IS the ``ppermute`` ring: the non-interleaved
schedule already shifts stage pp-1 → stage 0; here that wrapped value becomes
the input of the next chunk instead of being discarded. Microbatches are
processed in groups of ``pp`` (the reference asserts
``num_microbatches % pp == 0``); within a group the pp in-flight microbatches
circle the ring ``vp`` times, and groups follow each other with zero bubble
(the ring is saturated except for the single global fill/drain of pp-1
ticks — total bubble (pp-1)/(M·vp + pp-1) vs the non-interleaved
(pp-1)/(M + pp-1)).

Tick → work-item map (u = t - rank):
    g = u // (pp·vp)   — microbatch group
    r = (u mod pp·vp) // pp  — chunk (virtual stage) index
    i = u mod pp       — index within group → microbatch m = g·pp + i
Chunk params are gathered per tick with a dynamic index into the local
``[vp, ...]`` chunk stack.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from apex_tpu.transformer.pipeline_parallel.schedules._compat import (
    shard_map,
)
from jax.sharding import PartitionSpec as P

from apex_tpu.monitor.trace import span
from apex_tpu.parallel.mesh import DP_AXIS, PP_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineSpec,
    append_dropout_operand,
    check_dropout_spec,
    derive_microbatch_keys,
    embed_microbatches,
    replicate_loss,
    split_microbatches,
    stage_params_spec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    _mesh_axis_names,
    _pvary_all,
    _ring_shift,
    _tree_index,
    _tree_where,
)

Pytree = Any


def pipeline_ring_interleaved(
    stage_fn: Callable[[Pytree, Pytree], Pytree],
    chunk_params: Pytree,
    h_mb: Pytree,
    *,
    num_microbatches: int,
    virtual_pipeline_size: int,
    axis_name: str = PP_AXIS,
    remat: bool = True,
    returns_aux: bool = False,
    keys_mb: Optional[jax.Array] = None,
) -> Pytree:
    """Circular ring inside a mesh program. ``chunk_params`` is this stage's
    ``[vp, ...]`` chunk stack (pp axis already squeezed). Returns ``[M, ...]``
    final-chunk outputs, valid on the last stage. With ``returns_aux`` the
    stage function yields ``(h, aux_scalar)`` and the result is
    ``(outputs, aux_mean)``: the stage's aux averaged over its real
    (microbatch, chunk) ticks.

    ``keys_mb`` ([M]-stacked PRNG keys) activates dropout routing: the
    stage function is called ``stage_fn(params, h, key)`` with the
    microbatch's key folded by the CHUNK index — chunks on one stage share
    its pp rank, so without the fold chunk r and r' would reuse the same
    per-layer mask streams."""
    pp = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    M, vp = num_microbatches, virtual_pipeline_size
    if M % pp != 0:
        raise ValueError(
            f"interleaved schedule requires num_microbatches ({M}) divisible "
            f"by pipeline size ({pp})"  # ref interleaving.py assert
        )
    G = M // pp
    work = G * pp * vp
    T = work + pp - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    axes = _mesh_axis_names()

    def tick(carry, t):
        h, aux_sum = carry
        u = jnp.clip(t - rank, 0, work - 1)
        g = u // (pp * vp)
        w = u % (pp * vp)
        r = w // pp
        i = w % pp
        m = jnp.clip(g * pp + i, 0, M - 1)
        x0 = _tree_index(h_mb, m)
        take_new = (rank == 0) & (r == 0)
        inp = _tree_where(take_new, x0, h)
        p_r = _tree_index(chunk_params, r)
        args = (p_r, inp)
        if keys_mb is not None:
            key_m = lax.dynamic_index_in_dim(keys_mb, m, 0, keepdims=False)
            args += (jax.random.fold_in(key_m, r),)
        # monitor spans: stage compute vs ring p2p as distinct layer paths
        # (same names as the non-interleaved schedule for uniform reports)
        if returns_aux:
            with span("pp_stage"):
                out, aux = fn(*args)
            valid = (t >= rank) & (t - rank <= work - 1)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        else:
            with span("pp_stage"):
                out = fn(*args)
        with span("pp_ring_shift"):
            shifted = _ring_shift(out, axis_name)
        return (_pvary_all(shifted, axes),
                _pvary_all(aux_sum, axes)), out

    init = (
        _pvary_all(jax.tree.map(lambda a: jnp.zeros_like(a[0]), h_mb), axes),
        _pvary_all(jnp.zeros((), jnp.float32), axes),
    )
    (_, aux_sum), ys = lax.scan(tick, init, jnp.arange(T))
    # microbatch m = g*pp+i finishes chunk vp-1 on the last stage at tick
    # g*pp*vp + (vp-1)*pp + i + (pp-1)
    idx = np.asarray(
        [g * pp * vp + (vp - 1) * pp + i + pp - 1
         for g in range(G) for i in range(pp)],
        dtype=np.int32,
    )
    outs = jax.tree.map(lambda a: a[idx], ys)
    if returns_aux:
        return outs, aux_sum / work
    return outs


def _pipeline_body(
    params: Pytree,
    inputs_mb: Pytree,
    targets_mb: Pytree,
    keys_mb: Optional[jax.Array] = None,
    *,
    spec: PipelineSpec,
    num_microbatches: int,
    virtual_pipeline_size: int,
    mesh,
    remat: bool,
):
    # stages leaves are [vp, 1, ...] locally (pp axis sharded at dim 1)
    chunk_local = jax.tree.map(lambda a: a[:, 0], params["stages"])
    h_mb = embed_microbatches(spec.embed_fn, params["embed"], inputs_mb,
                              keys_mb)
    ys = pipeline_ring_interleaved(
        spec.stage_fn,
        chunk_local,
        h_mb,
        num_microbatches=num_microbatches,
        virtual_pipeline_size=virtual_pipeline_size,
        remat=remat,
        returns_aux=spec.stage_aux,
        keys_mb=keys_mb,
    )
    aux = None
    if spec.stage_aux:
        ys, aux = ys
    losses = jax.vmap(spec.loss_fn, in_axes=(None, 0, 0))(
        params["head"], ys, targets_mb
    )
    pp = lax.axis_size(PP_AXIS)
    is_last = lax.axis_index(PP_AXIS) == pp - 1
    local = jnp.where(is_last, jnp.mean(losses), 0.0)
    total = replicate_loss(local, mesh)
    if aux is not None:
        # per-stage (chunk-mean) aux -> model-wide layer mean (psum/pp)
        total = total + replicate_loss(aux, mesh, masked_axis=None)
    return total


def forward_backward_pipelining_with_interleaving(
    spec: PipelineSpec,
    params: Pytree,
    batch: Tuple[Pytree, Pytree],
    *,
    num_microbatches: int,
    virtual_pipeline_size: int,
    mesh=None,
    params_specs: Optional[Pytree] = None,
    data_spec: P = P(None, DP_AXIS),
    loss_scale: Optional[jnp.ndarray] = None,
    remat: bool = True,
    dropout_key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, Pytree]:
    """Driver (ref :25). Same contract as the non-interleaved driver except
    ``params["stages"]`` carries leading ``[vp, pp]`` axes (see
    ``common.build_model``). ``dropout_key`` as in the non-interleaved
    driver, with the chunk index additionally folded per tick."""
    from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_enc_dec import (
        EncDecPipelineSpec,
    )

    if isinstance(spec, EncDecPipelineSpec):
        # Matches the reference: the interleaved schedule rejects
        # ModelType.encoder_and_decoder (ref schedules/__init__.py guard).
        raise ValueError(
            "the interleaved schedule supports encoder-or-decoder models "
            "only; use forward_backward_pipelining_without_interleaving for "
            "encoder-decoder specs"
        )
    if mesh is None:
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.get_mesh()
    if params_specs is None:
        params_specs = {
            "embed": jax.tree.map(lambda _: P(), params["embed"]),
            "stages": stage_params_spec(params["stages"], interleaved=True),
            "head": jax.tree.map(lambda _: P(), params["head"]),
        }
    inputs, targets = batch
    inputs_mb = split_microbatches(inputs, num_microbatches)
    targets_mb = split_microbatches(targets, num_microbatches)
    check_dropout_spec(spec, dropout_key)
    keys_mb = derive_microbatch_keys(dropout_key, num_microbatches)

    body = functools.partial(
        _pipeline_body,
        spec=spec,
        num_microbatches=num_microbatches,
        virtual_pipeline_size=virtual_pipeline_size,
        mesh=mesh,
        remat=remat,
    )
    in_specs = [
        params_specs,
        jax.tree.map(lambda _: data_spec, inputs_mb),
        jax.tree.map(lambda _: data_spec, targets_mb),
    ]
    args = [inputs_mb, targets_mb]
    append_dropout_operand(in_specs, args, keys_mb)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(),
    )

    scale = 1.0 if loss_scale is None else loss_scale

    def scaled(p):
        loss = sharded(p, *args)
        return loss * scale, loss

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params)
    return loss, grads

"""Encoder-decoder (T5-style) pipeline schedule.

Reference: ``apex/transformer/pipeline_parallel/schedules/common.py:72-96``
(``ModelType.encoder_and_decoder`` build: ranks before
``pipeline_model_parallel_split_rank`` hold encoder blocks, ranks at/after it
hold decoder blocks) and the double-tensor plumbing in
``fwd_bwd_pipelining_without_interleaving.py`` (decoder stages forward BOTH
the decoder hidden state and the encoder output between stages, and the
backward pass returns two cotangents).

TPU re-design — two pipelined phases over the SAME pp ring instead of a
static device split:

* The reference must partition devices at ``split_rank`` because each process
  is bound to either encoder or decoder layers for the whole run; whichever
  side has fewer layers idles while the other works. Under SPMD one device
  can hold one encoder chunk AND one decoder chunk, so here ALL ``pp`` stages
  pipeline the encoder (ring #1), the encoder outputs are broadcast from the
  last stage, then ALL ``pp`` stages pipeline the decoder (ring #2) — full
  utilization in both phases, and no split-rank balance problem to tune.
  ``parallel_state`` still exposes the split-rank accessors for API parity.
* Cross-attention memory: every decoder stage needs the encoder output of
  the microbatch it is currently processing. After ring #1 the per-microbatch
  encoder outputs ``[M, ...]`` are made pp-invariant with one masked ``psum``
  (the last stage holds the valid values); ring #2's tick ``t`` on stage
  ``r`` then indexes microbatch ``t - r``. This replaces the reference's
  per-hop "send encoder output along with hidden" p2p chain with one
  collective, and holds ``M`` microbatches of encoder output per device —
  the same budget as the ``[M, ...]`` stage-0 inputs the uniform rings
  already keep resident.
* The backward "double grad" path (ref ``backward_step``'s two-cotangent
  handling) is autodiff: the decoder ring consumes ``mem`` at every tick, so
  its cotangent accumulates across ticks and flows through the broadcast
  transpose into ring #1's scan transpose — exactly the encoder-side gradient
  traffic the reference hand-schedules.

The interleaved (virtual-pipeline) schedule does not support
encoder-decoder models, matching the reference's restriction.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from apex_tpu.transformer.pipeline_parallel.schedules._compat import (
    shard_map,
)
from jax.sharding import PartitionSpec as P

from apex_tpu.monitor.trace import span
from apex_tpu.parallel.mesh import DP_AXIS, PP_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    _pvary,
    append_dropout_operand,
    check_dropout_spec,
    derive_microbatch_keys,
    embed_microbatches,
    replicate_loss,
    split_microbatches,
    stage_params_spec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    pipeline_ring,
)

Pytree = Any


@dataclasses.dataclass(frozen=True)
class EncDecPipelineSpec:
    """The encoder-decoder pipelined model contract
    (``ModelType.encoder_and_decoder``'s ``model_provider_func`` analogue,
    ref common.py:80-103 ``add_encoder``/``add_decoder``).

    enc_embed_fn(embed_params, enc_inputs_mb) -> enc_hidden
        Encoder-side ``pre_process`` for ONE microbatch.
    enc_stage_fn(enc_stage_params, h) -> h
        One encoder pipeline stage (``num_enc_layers / pp`` layers),
        shape-preserving.
    dec_embed_fn(embed_params, dec_inputs_mb) -> dec_hidden
        Decoder-side ``pre_process`` (the reference's second ``pre_process``
        at ``rank == split_rank``, common.py:93).
    dec_stage_fn(dec_stage_params, h, memory) -> h
        One decoder pipeline stage: self-attention + cross-attention over
        ``memory`` (the encoder output for the SAME microbatch) + MLP.
        Shape-preserving in ``h``; ``memory`` may have a different sequence
        length.
    loss_fn(head_params, h, targets_mb) -> scalar
        Decoder-side ``post_process``, averaged over the microbatch.
    """

    enc_embed_fn: Callable[[Pytree, Pytree], Pytree]
    enc_stage_fn: Callable[[Pytree, Pytree], Pytree]
    dec_embed_fn: Callable[[Pytree, Pytree], Pytree]
    dec_stage_fn: Callable[[Pytree, Pytree, Pytree], Pytree]
    loss_fn: Callable[[Pytree, Pytree, Pytree], jnp.ndarray]
    # True: embed and stage functions take a trailing per-microbatch PRNG
    # key — ``enc_embed_fn(p, tok, key)`` / ``enc_stage_fn(p, h, key)`` /
    # ``dec_stage_fn(p, h, memory, key)`` — so embedding dropout matches
    # the sequential path (t5_encode/t5_decode apply it, salts 100/101).
    # Per-side / per-stage decorrelation is the model's job.
    takes_dropout_key: bool = False


def broadcast_from_last_stage(x: Pytree, axis_name: str = PP_AXIS) -> Pytree:
    """Replicate the last pipeline stage's values over the pp axis.

    The fill/drain garbage on earlier stages is finite (zero-init through
    finite stage math), so a masked psum both discards it and broadcasts in
    one collective.
    """
    pp = lax.axis_size(axis_name)
    is_last = lax.axis_index(axis_name) == pp - 1

    def one(a):
        masked = jnp.where(is_last, a, jnp.zeros_like(a))
        return lax.psum(_pvary(masked, axis_name), axis_name)

    return jax.tree.map(one, x)


def decoder_ring(
    dec_fn: Callable[[Pytree, Pytree, Pytree], Pytree],
    stage_params: Pytree,
    h_mb: Pytree,
    mem_mb: Pytree,
    *,
    num_microbatches: int,
    axis_name: str = PP_AXIS,
    remat: bool = True,
    keys_mb: Optional[jax.Array] = None,
) -> Pytree:
    """``pipeline_ring`` with a per-tick cross-attention memory operand.

    ``mem_mb`` is ``[M, ...]`` encoder outputs, valid on EVERY device (run
    :func:`broadcast_from_last_stage` first). At tick ``t`` stage ``r``
    processes microbatch ``t - r``, so it cross-attends to
    ``mem_mb[t - r]``; fill/drain ticks index a clipped microbatch and are
    masked out of the loss downstream, contributing exactly-zero cotangents
    to ``mem_mb`` through the finite stage math.

    ``keys_mb`` ([M]-stacked PRNG keys) rides the same per-microbatch side
    channel, arriving as ``dec_fn(params, h, memory, key)``.
    """
    fn = dec_fn
    extra = mem_mb
    if keys_mb is not None:
        extra = (mem_mb, keys_mb)
        fn = lambda p, h, mem_key: dec_fn(p, h, *mem_key)  # noqa: E731
    return pipeline_ring(
        fn,
        stage_params,
        h_mb,
        num_microbatches=num_microbatches,
        axis_name=axis_name,
        remat=remat,
        extra_mb=extra,
    )


def _enc_dec_body(
    params: Pytree,
    enc_inputs_mb: Pytree,
    dec_inputs_mb: Pytree,
    targets_mb: Pytree,
    keys_mb: Optional[jax.Array] = None,
    *,
    spec: EncDecPipelineSpec,
    num_microbatches: int,
    mesh,
    remat: bool,
):
    enc_local = jax.tree.map(lambda a: a[0], params["enc_stages"])
    dec_local = jax.tree.map(lambda a: a[0], params["dec_stages"])

    # Phase 1: encoder ring over all pp stages. The monitor spans nest the
    # ring's own pp_stage/pp_ring_shift ranges under a per-phase name, so
    # trace/pyprof reports split enc vs dec vs memory-broadcast time.
    with span("pp_encode"):
        h_enc_mb = embed_microbatches(spec.enc_embed_fn, params["embed"],
                                      enc_inputs_mb, keys_mb)
        enc_out_mb = pipeline_ring(
            spec.enc_stage_fn,
            enc_local,
            h_enc_mb,
            num_microbatches=num_microbatches,
            remat=remat,
            extra_mb=keys_mb,
        )
    with span("pp_memory_broadcast"):
        mem_mb = broadcast_from_last_stage(enc_out_mb)

    # Phase 2: decoder ring, cross-attending to the broadcast memory.
    with span("pp_decode"):
        h_dec_mb = embed_microbatches(spec.dec_embed_fn, params["embed"],
                                      dec_inputs_mb, keys_mb)
        ys = decoder_ring(
            spec.dec_stage_fn,
            dec_local,
            h_dec_mb,
            mem_mb,
            num_microbatches=num_microbatches,
            remat=remat,
            keys_mb=keys_mb,
        )
    losses = jax.vmap(spec.loss_fn, in_axes=(None, 0, 0))(
        params["head"], ys, targets_mb
    )
    pp = lax.axis_size(PP_AXIS)
    is_last = lax.axis_index(PP_AXIS) == pp - 1
    local = jnp.where(is_last, jnp.mean(losses), 0.0)
    return replicate_loss(local, mesh)


def forward_backward_pipelining_enc_dec(
    spec: EncDecPipelineSpec,
    params: Pytree,
    batch: Tuple[Pytree, Pytree, Pytree],
    *,
    num_microbatches: int,
    mesh=None,
    params_specs: Optional[Pytree] = None,
    data_spec: P = P(None, DP_AXIS),
    loss_scale: Optional[jnp.ndarray] = None,
    remat: bool = True,
    dropout_key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, Pytree]:
    """Encoder-decoder 1F1B driver. ``batch = (enc_inputs, dec_inputs,
    targets)`` pytrees with a leading global-batch dim. Returns
    ``(mean_unscaled_loss, grads)``; grads are w.r.t. ``loss * loss_scale``.

    ``params = {"embed": ..., "enc_stages": <[pp] axis>, "dec_stages":
    <[pp] axis>, "head": ...}`` — each device holds one encoder AND one
    decoder chunk (see module docstring for why this beats the reference's
    split-rank device partition on TPU).

    ``dropout_key`` (requires ``spec.takes_dropout_key``) derives one key
    per microbatch, delivered to BOTH rings' stage functions through the
    per-microbatch side channel (``enc_stage_fn(p, h, key)`` /
    ``dec_stage_fn(p, h, mem, key)``); per-side and per-stage
    decorrelation is the model's fold (see ``t5_enc_dec_spec``).
    """
    if mesh is None:
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.get_mesh()
    if params_specs is None:
        params_specs = {
            "embed": jax.tree.map(lambda _: P(), params["embed"]),
            "enc_stages": stage_params_spec(params["enc_stages"]),
            "dec_stages": stage_params_spec(params["dec_stages"]),
            "head": jax.tree.map(lambda _: P(), params["head"]),
        }
    enc_inputs, dec_inputs, targets = batch
    enc_mb = split_microbatches(enc_inputs, num_microbatches)
    dec_mb = split_microbatches(dec_inputs, num_microbatches)
    tgt_mb = split_microbatches(targets, num_microbatches)
    check_dropout_spec(spec, dropout_key)
    keys_mb = derive_microbatch_keys(dropout_key, num_microbatches)

    body = functools.partial(
        _enc_dec_body,
        spec=spec,
        num_microbatches=num_microbatches,
        mesh=mesh,
        remat=remat,
    )
    in_specs = [
        params_specs,
        jax.tree.map(lambda _: data_spec, enc_mb),
        jax.tree.map(lambda _: data_spec, dec_mb),
        jax.tree.map(lambda _: data_spec, tgt_mb),
    ]
    args = [enc_mb, dec_mb, tgt_mb]
    append_dropout_operand(in_specs, args, keys_mb)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(),
    )

    scale = 1.0 if loss_scale is None else loss_scale

    def scaled(p):
        loss = sharded(p, *args)
        return loss * scale, loss

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params)
    return loss, grads

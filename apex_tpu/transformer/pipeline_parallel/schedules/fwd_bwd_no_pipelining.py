"""No-pipelining schedule: a gradient-accumulation microbatch loop.

Reference: ``fwd_bwd_no_pipelining.py:31-95`` — runs all-but-last microbatches
inside ``model.no_sync()`` (suppressing the DDP all-reduce), accumulating
grads, then the last microbatch with the all-reduce enabled.

TPU re-design: a ``lax.scan`` of ``jax.value_and_grad`` over microbatches,
summing gradient pytrees on device. The reference's no_sync dance exists to
all-reduce once instead of M times; here grads are accumulated locally inside
the jitted step and the data-parallel ``psum`` happens once wherever the
caller's DP wrapper puts it (see ``apex_tpu.parallel.distributed``) — the
same "reduce once at the end" schedule, enforced by construction.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.monitor.trace import span
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    derive_microbatch_keys,
    split_microbatches,
)

Pytree = Any


def forward_backward_no_pipelining(
    forward_step_func: Callable[[Pytree, Pytree], jnp.ndarray],
    batch: Pytree,
    params: Pytree,
    *,
    num_microbatches: int,
    loss_scale: Optional[jnp.ndarray] = None,
    unroll: int = 1,
    dropout_key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, Pytree]:
    """Returns ``(mean_unscaled_loss, grads)``; grads are of
    ``mean(loss) * loss_scale`` summed over microbatches (ref common.py:226-256
    scales each microbatch loss by 1/num_microbatches before backward).

    ``forward_step_func(params, microbatch) -> scalar loss`` is the analogue
    of the reference's ``forward_step_func(batch, model)``. With
    ``dropout_key`` it is called ``forward_step_func(params, microbatch,
    key)`` with a per-microbatch key (microbatches must drop independent
    positions, matching the reference's stateful per-call RNG advance).
    """
    mb = split_microbatches(batch, num_microbatches)
    scale = 1.0 if loss_scale is None else loss_scale
    _ARITY_HINT = (
        "dropout_key given but forward_step_func does not accept a third "
        "per-microbatch key argument (params, microbatch, key)")
    if dropout_key is not None:
        # fail loudly before tracing: a 2-arg step func with dropout_key
        # would otherwise die with an opaque arity TypeError inside scan
        import inspect

        try:
            sig = inspect.signature(forward_step_func)
        except (TypeError, ValueError):
            sig = None  # uninspectable (C callable etc.) — let it through
        if sig is not None:
            try:
                sig.bind(object(), object(), object())
            except TypeError:
                raise ValueError(_ARITY_HINT) from None

    keys_mb = derive_microbatch_keys(dropout_key, num_microbatches)

    def scaled(p, m, key):
        # second line of defense: a (*args, **kwargs) wrapper over a 2-arg
        # step func binds the 3-arg signature above just fine, then the
        # wrapped callable dies HERE at trace time. On TypeError, PROBE the
        # 2-arg call: if it succeeds the function genuinely takes no key —
        # raise the arity hint; if the probe also raises (a correct 3-arg
        # func whose BODY threw, incl. nested arity bugs) the original
        # error propagates untouched — a wrong "fix your signature"
        # diagnosis would be worse than the opaque error. The probe costs
        # one extra trace on the error path only.
        try:
            loss = (forward_step_func(p, m) if key is None
                    else forward_step_func(p, m, key))
        except TypeError as e:
            if key is not None:
                try:
                    forward_step_func(p, m)
                except Exception:
                    raise e from None
                raise ValueError(f"{_ARITY_HINT} (original error: {e})") \
                    from e
            raise
        return loss * scale / num_microbatches, loss

    vg = jax.value_and_grad(scaled, has_aux=True)

    def body(acc, m_key):
        m, key = m_key
        loss_sum, grad_sum = acc
        # monitor span: one per-microbatch fwd+bwd range in trace/pyprof
        with span("fwd_bwd"):
            (_, loss), g = vg(params, m, key)
        return (
            loss_sum + loss,
            jax.tree.map(jnp.add, grad_sum, g),
        ), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    if keys_mb is not None:
        (loss_sum, grads), _ = lax.scan(
            body, (jnp.zeros(()), zeros), (mb, keys_mb), unroll=unroll
        )
    else:
        (loss_sum, grads), _ = lax.scan(
            lambda acc, m: body(acc, (m, None)),
            (jnp.zeros(()), zeros), mb, unroll=unroll
        )
    return loss_sum / num_microbatches, grads

"""1F1B pipeline schedule over a ppermute ring.

Reference: ``fwd_bwd_pipelining_without_interleaving.py:155-345`` — warmup
forwards (pp - rank - 1), steady-state one-forward-one-backward with fused
``send_forward_recv_backward`` p2p, cooldown backwards; activation/cotangent
tensors move between stage processes with batched isend/irecv.

TPU re-design: the whole schedule is ONE shard_map program containing a
``lax.scan`` over ``M + pp - 1`` ticks. Each tick every stage applies its
layer block and the ring shifts activations one stage forward
(``lax.ppermute`` — collective permute is the ICI-native neighbor exchange).
Differentiating the program yields the backward pipeline automatically: the
transpose of the scan is the reverse-tick scan and the transpose of the
ppermute is the reverse shift, i.e. exactly the reference's cooldown/steady
backward traffic, scheduled by XLA instead of by hand. The 1F1B memory
property (≤ pp microbatches of activations live per stage) is approximated
with ``jax.checkpoint`` on the stage function: only the stage-boundary
activations of each tick are saved (one microbatch-sized tensor per tick);
interior activations are rematerialized in the backward sweep.

Fill/drain ticks compute on zero-initialized garbage that is masked out of
the loss; with finite stage math (any standard transformer block) those paths
contribute exactly-zero cotangents.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from apex_tpu.transformer.pipeline_parallel.schedules._compat import (
    shard_map,
)
from jax.sharding import PartitionSpec as P

from apex_tpu.monitor.trace import span
from apex_tpu.parallel.mesh import DP_AXIS, PP_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineSpec,
    append_dropout_operand,
    check_dropout_spec,
    derive_microbatch_keys,
    embed_microbatches,
    replicate_loss,
    split_microbatches,
    stage_params_spec,
)

Pytree = Any


def _tree_index(tree: Pytree, i) -> Pytree:
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree
    )


def _tree_where(cond, a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(lambda x, y: jnp.where(cond, x, y), a, b)


def _ring_shift(x: Pytree, axis_name: str) -> Pytree:
    from apex_tpu.transformer.pipeline_parallel import p2p_communication

    return p2p_communication.send_forward_recv_forward(x, axis_name)


def _pvary_all(x: Pytree, axis_names) -> Pytree:
    """Mark every leaf varying over all given axes (identity value-wise) so
    the scan carry has a fixed VMA type regardless of what collectives the
    stage function ends with."""

    def one(a):
        for name in axis_names:
            try:
                if name in jax.typeof(a).vma:
                    continue
            except (AttributeError, TypeError):
                return a  # no vma tracking
            a = lax.pcast(a, name, to="varying")
        return a

    return jax.tree.map(one, x)


def _mesh_axis_names():
    from apex_tpu.parallel.mesh import AXIS_ORDER

    return AXIS_ORDER


def pipeline_ring(
    stage_fn: Callable[..., Pytree],
    stage_params: Pytree,
    h_mb: Pytree,
    *,
    num_microbatches: int,
    axis_name: str = PP_AXIS,
    remat: bool = True,
    returns_aux: bool = False,
    extra_mb: Optional[Pytree] = None,
) -> Pytree:
    """Run ``num_microbatches`` activations through the pp-stage ring.

    Must be called inside a mesh program. ``stage_params`` is this stage's
    local params (stage axis already squeezed); ``h_mb`` is ``[M, ...]``
    stage-0 inputs (present on every device, consumed at stage 0). Returns
    ``[M, ...]`` outputs, valid on the LAST stage (garbage elsewhere — mask
    before use). With ``returns_aux`` the stage function yields
    ``(h, aux_scalar)`` and the result is ``(outputs, aux_mean)`` where
    ``aux_mean`` averages the stage's aux over its real microbatch ticks
    (fill/drain garbage is masked out).

    ``extra_mb`` is an optional ``[M, ...]`` per-microbatch side operand
    valid on EVERY device (e.g. encoder memory for a decoder ring); when
    given, the stage function is called ``stage_fn(params, h, extra_t)``
    with ``extra_t`` the entry for the microbatch this stage processes at
    this tick (``t - rank``, clipped on fill/drain ticks whose outputs are
    masked downstream).
    """
    pp = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    M = num_microbatches
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    axes = _mesh_axis_names()

    def tick(carry, t):
        h, aux_sum = carry
        x0 = _tree_index(h_mb, jnp.clip(t, 0, M - 1))
        inp = _tree_where(rank == 0, x0, h)
        args = (stage_params, inp)
        if extra_mb is not None:
            # stage `rank` holds microbatch t-rank at tick t
            args += (_tree_index(extra_mb, jnp.clip(t - rank, 0, M - 1)),)
        # monitor spans: per-tick stage compute vs ring p2p show up as
        # distinct layer paths in the trace/measured tables — with the
        # analytic bubble share from monitor.pipeline_bubble_fraction this
        # is the schedule's bubble attribution
        if returns_aux:
            with span("pp_stage"):
                out, aux = fn(*args)
            valid = (t >= rank) & (t - rank <= M - 1)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        else:
            with span("pp_stage"):
                out = fn(*args)
        with span("pp_ring_shift"):
            shifted = _ring_shift(out, axis_name)
        return (_pvary_all(shifted, axes),
                _pvary_all(aux_sum, axes)), out

    init = (
        _pvary_all(jax.tree.map(lambda a: jnp.zeros_like(a[0]), h_mb), axes),
        _pvary_all(jnp.zeros((), jnp.float32), axes),
    )
    (_, aux_sum), ys = lax.scan(tick, init, jnp.arange(M + pp - 1))
    # tick pp-1+i holds microbatch i's final output on the last stage
    outs = jax.tree.map(lambda a: a[pp - 1:], ys)
    if returns_aux:
        return outs, aux_sum / M
    return outs


def _pipeline_body(
    params: Pytree,
    inputs_mb: Pytree,
    targets_mb: Pytree,
    keys_mb: Optional[Pytree] = None,
    *,
    spec: PipelineSpec,
    num_microbatches: int,
    mesh,
    remat: bool,
):
    stage_local = jax.tree.map(lambda a: a[0], params["stages"])
    h_mb = embed_microbatches(spec.embed_fn, params["embed"], inputs_mb,
                              keys_mb)
    ys = pipeline_ring(
        spec.stage_fn,
        stage_local,
        h_mb,
        num_microbatches=num_microbatches,
        remat=remat,
        returns_aux=spec.stage_aux,
        extra_mb=keys_mb,
    )
    aux = None
    if spec.stage_aux:
        ys, aux = ys
    losses = jax.vmap(spec.loss_fn, in_axes=(None, 0, 0))(
        params["head"], ys, targets_mb
    )
    pp = lax.axis_size(PP_AXIS)
    is_last = lax.axis_index(PP_AXIS) == pp - 1
    local = jnp.where(is_last, jnp.mean(losses), 0.0)
    total = replicate_loss(local, mesh)
    if aux is not None:
        # per-stage layer-mean aux -> model-wide layer mean (psum/pp), same
        # dp averaging as the main loss
        total = total + replicate_loss(aux, mesh, masked_axis=None)
    return total


def forward_backward_pipelining_without_interleaving(
    spec: PipelineSpec,
    params: Pytree,
    batch: Tuple[Pytree, Pytree],
    *,
    num_microbatches: int,
    mesh=None,
    params_specs: Optional[Pytree] = None,
    data_spec: P = P(None, DP_AXIS),
    loss_scale: Optional[jnp.ndarray] = None,
    remat: bool = True,
    dropout_key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, Pytree]:
    """The driver (ref :155). ``batch = (inputs, targets)`` pytrees with a
    leading global-batch dim. Returns ``(mean_unscaled_loss, grads)``; grads
    are w.r.t. ``loss * loss_scale``.

    ``params = {"embed": ..., "stages": <leading [pp] axis>, "head": ...}``.
    ``params_specs`` mirrors ``params`` with PartitionSpecs (default:
    embed/head replicated, stages ``P("pp")`` — supply your own to lay TP
    shards onto the mesh). ``data_spec`` shards the microbatched data
    ``[M, B, ...]``; the default splits the per-microbatch batch dim over dp.

    ``dropout_key`` (training mode; requires a spec built with
    ``takes_dropout_key``) derives one key per microbatch and routes it to
    the embed/stage functions through the ring's per-microbatch side
    channel, so microbatches drop independent positions; stage/sp
    decorrelation is the model's own axis-fold (ref ParallelTransformer
    trains with dropout under every schedule).
    """
    from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_enc_dec import (
        EncDecPipelineSpec,
        forward_backward_pipelining_enc_dec,
    )

    if isinstance(spec, EncDecPipelineSpec):
        # ModelType.encoder_and_decoder routing (ref common.py:80-103): the
        # same driver name serves both model types, as in the reference.
        return forward_backward_pipelining_enc_dec(
            spec,
            params,
            batch,
            num_microbatches=num_microbatches,
            mesh=mesh,
            params_specs=params_specs,
            data_spec=data_spec,
            loss_scale=loss_scale,
            remat=remat,
            dropout_key=dropout_key,
        )
    if mesh is None:
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.get_mesh()
    if params_specs is None:
        params_specs = {
            "embed": jax.tree.map(lambda _: P(), params["embed"]),
            "stages": stage_params_spec(params["stages"]),
            "head": jax.tree.map(lambda _: P(), params["head"]),
        }
    inputs, targets = batch
    inputs_mb = split_microbatches(inputs, num_microbatches)
    targets_mb = split_microbatches(targets, num_microbatches)
    check_dropout_spec(spec, dropout_key)
    keys_mb = derive_microbatch_keys(dropout_key, num_microbatches)

    body = functools.partial(
        _pipeline_body,
        spec=spec,
        num_microbatches=num_microbatches,
        mesh=mesh,
        remat=remat,
    )
    in_specs = [
        params_specs,
        jax.tree.map(lambda _: data_spec, inputs_mb),
        jax.tree.map(lambda _: data_spec, targets_mb),
    ]
    args = [inputs_mb, targets_mb]
    append_dropout_operand(in_specs, args, keys_mb)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(),
    )

    scale = 1.0 if loss_scale is None else loss_scale

    def scaled(p):
        loss = sharded(p, *args)
        return loss * scale, loss

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params)
    return loss, grads

"""Shared plumbing for the forward-backward schedule drivers.

Reference: ``apex/transformer/pipeline_parallel/schedules/common.py`` —
``build_model`` (:25) constructs per-stage modules with ``pre_process`` /
``post_process`` flags (and virtual chunks), ``forward_step`` (:226) runs one
microbatch forward with loss scaling by ``num_microbatches``, and
``backward_step`` (:288) feeds the received output-cotangent into
``torch.autograd.backward``.

TPU re-design: under jax the forward/backward split is autodiff, so the
driver contract is value-based:

* The model is a :class:`PipelineSpec` of three pure functions. The
  embedding (``pre_process``) and loss head (``post_process``) run *outside*
  the ring — they are cheap relative to the stack, and keeping the pipelined
  region shape-uniform is what lets the whole schedule live in one
  ``lax.scan``. The reference's separate "embedding group" all-reduce that
  ties input/output embedding gradients across the first and last stage
  (``parallel_state`` embedding group) disappears: if ``loss_fn`` reuses the
  embed table, autodiff sums both contributions in one grad pytree.
* ``build_model`` stacks per-stage parameter pytrees along a leading ``pp``
  axis (plus a ``vp`` chunk axis for the interleaved schedule) so one
  ``P("pp", ...)`` sharding puts each stage's weights on its devices.
* ``backward_step`` needs no analogue: the transpose of the schedule's
  ``ppermute`` ring is the reverse ring, derived by XLA.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import AXIS_ORDER, DP_AXIS, PP_AXIS

Pytree = Any


@dataclasses.dataclass(frozen=True)
class PipelineSpec:
    """The pipelined model contract (the ``model_provider_func`` analogue,
    ref common.py:25-148).

    embed_fn(embed_params, inputs_mb) -> hidden
        The ``pre_process`` half: token/position embedding for ONE
        microbatch. Runs on every device (its FLOPs are negligible; its
        output is consumed at stage 0 only).
    stage_fn(stage_params, hidden) -> hidden
        One pipeline stage (``num_layers / (pp * vp)`` transformer layers).
        Must be shape-preserving — this uniformity is what the ring relies
        on. May use TP/SP collectives internally.
    loss_fn(head_params, hidden, targets_mb) -> scalar
        The ``post_process`` half: final norm + head + loss for ONE
        microbatch, already averaged over the microbatch's tokens.
    stage_aux
        When True, ``stage_fn`` returns ``(hidden, aux_scalar)`` — a
        per-stage side loss (e.g. the MoE router aux). The schedules
        accumulate it over real (non-fill/drain) ticks, average over
        microbatches and stages, and ADD it to the returned loss, so its
        gradients reach the stage params through the same AD sweep.
    """

    embed_fn: Callable[[Pytree, Pytree], Pytree]
    stage_fn: Callable[[Pytree, Pytree], Pytree]
    loss_fn: Callable[[Pytree, Pytree, Pytree], jnp.ndarray]
    stage_aux: bool = False
    # True: embed_fn/stage_fn take a trailing per-microbatch PRNG key arg
    # (training dropout). The schedules derive one key per microbatch from
    # their ``dropout_key`` argument and route it alongside the microbatch
    # (the stage/pp/sp decorrelation folds live inside the model, see
    # standalone_gpt._layer_stack); passing dropout_key to a schedule
    # requires a spec built with this flag and vice versa.
    takes_dropout_key: bool = False


def check_dropout_spec(spec: "PipelineSpec", dropout_key) -> None:
    """Validate the spec/dropout_key pairing in BOTH directions before
    tracing: a mismatch otherwise fails with an opaque arity TypeError
    deep inside shard_map/vmap."""
    if dropout_key is not None and not spec.takes_dropout_key:
        raise ValueError(
            "dropout_key given but the PipelineSpec was built without "
            "takes_dropout_key (e.g. gpt_pipeline_spec(cfg, dropout=True))")
    if dropout_key is None and spec.takes_dropout_key:
        raise ValueError(
            "the PipelineSpec was built with takes_dropout_key but no "
            "dropout_key was passed; pass one (training) or build the "
            "spec without dropout (eval)")


def derive_microbatch_keys(dropout_key, num_microbatches: int):
    """One PRNG key per microbatch (``fold_in(dropout_key, m)``), or None.
    The single derivation every schedule driver shares — test sequential
    references replay exactly this."""
    if dropout_key is None:
        return None
    return jax.vmap(lambda i: jax.random.fold_in(dropout_key, i))(
        jnp.arange(num_microbatches))


def embed_microbatches(embed_fn, embed_params, inputs_mb, keys_mb=None):
    """vmap a spec's embed_fn over the microbatch axis, threading the
    per-microbatch keys when dropout is active — one routing shared by
    every pipelined driver."""
    if keys_mb is not None:
        return jax.vmap(embed_fn, in_axes=(None, 0, 0))(
            embed_params, inputs_mb, keys_mb)
    return jax.vmap(embed_fn, in_axes=(None, 0))(embed_params, inputs_mb)


def append_dropout_operand(in_specs: list, args: list, keys_mb) -> None:
    """Append the replicated per-microbatch keys operand to a driver's
    shard_map spec/arg lists (no-op without dropout; the model folds the
    mesh axes itself)."""
    if keys_mb is not None:
        in_specs.append(P())
        args.append(keys_mb)


def build_model(
    stage_init_fn: Callable[[jax.Array, int], Pytree],
    rng: jax.Array,
    num_stages: int,
    virtual_pipeline_size: Optional[int] = None,
) -> Pytree:
    """Initialize and stack per-stage params (ref common.py:25-147).

    ``stage_init_fn(rng, global_chunk_index)`` returns one chunk's params.
    Non-interleaved: leaves gain a leading ``[pp]`` axis. Interleaved: a
    leading ``[vp, pp]`` pair, laid out so chunk ``v`` on stage ``s`` holds
    layer-block ``v * pp + s`` — the Megatron interleaved assignment
    (ref fwd_bwd_pipelining_with_interleaving.py:25-60).
    """
    vp = virtual_pipeline_size or 1
    chunks = [
        stage_init_fn(jax.random.fold_in(rng, c), c) for c in range(vp * num_stages)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *chunks)
    if virtual_pipeline_size is None:
        return stacked
    # [vp*pp, ...] -> [vp, pp, ...] with chunk-major order matching v*pp+s.
    return jax.tree.map(
        lambda x: x.reshape((vp, num_stages) + x.shape[1:]), stacked
    )


def stage_params_spec(params: Pytree, interleaved: bool = False) -> Pytree:
    """Default PartitionSpecs for stacked stage params: shard the stage axis
    over ``pp``, replicate the rest. Callers with TP-sharded weights supply
    their own tree instead."""
    lead = P(None, PP_AXIS) if interleaved else P(PP_AXIS)
    return jax.tree.map(lambda _: lead, params)


def split_microbatches(batch: Pytree, num_microbatches: int) -> Pytree:
    """[B, ...] -> [M, B/M, ...] on every leaf (ref
    pipeline_parallel/utils.py:105-139 ``get_kth_microbatch``, vectorized)."""

    def one(x):
        b = x.shape[0]
        if b % num_microbatches != 0:
            raise ValueError(
                f"batch dim {b} not divisible by num_microbatches "
                f"{num_microbatches}"
            )
        return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    return jax.tree.map(one, batch)


def replicate_loss(local_loss, mesh, masked_axis: str = PP_AXIS):
    """Turn a loss that is nonzero only on the last pipeline stage (and
    identical across tp/sp, different across dp) into a scalar that is
    invariant over the whole mesh:

    * psum over ``pp`` collects the last stage's value;
    * psum/size over ``dp`` averages across data-parallel replicas — the
      ``average_losses_across_data_parallel_group`` semantics
      (ref pipeline_parallel/utils.py:242-252);
    * psum/size over the remaining axes turns "replicated by construction"
      into "invariant for the VMA system".
    """
    loss = local_loss
    for a in mesh.axis_names:
        n = mesh.shape[a]
        loss = lax.psum(_pvary(loss, a), a)
        if a != masked_axis:
            loss = loss / n
    return loss


def _pvary(x, axis_name: str):
    """Mark x varying over axis (identity value-wise) so psum is legal under
    check_vma; no-op if already varying."""
    try:
        if axis_name in jax.typeof(x).vma:
            return x
    except (AttributeError, TypeError):
        return x
    return lax.pcast(x, axis_name, to="varying")

"""One home for the ``jax.shard_map`` import so the schedule modules stay
importable on stock jax.

The mesh schedules NEED the graft toolchain to run, but merely importing
them must not take down the whole ``apex_tpu.transformer`` tree (the
serve/testing modules are stock-jax-usable). Pre-graft jax has no
``jax.shard_map``; this stub keeps the import graceful and fails loudly
at CALL time instead."""

from __future__ import annotations

try:
    from jax import shard_map  # noqa: F401
except ImportError:  # stock jax: importable, but the schedules need graft
    def shard_map(*_a, **_k):
        raise NotImplementedError(
            "jax.shard_map unavailable (stock jax); this pipeline "
            "schedule needs the graft toolchain")

"""Schedule selection (ref ``schedules/__init__.py:16-39``)."""

from __future__ import annotations

from apex_tpu.transformer.pipeline_parallel.schedules.common import (  # noqa: F401
    PipelineSpec,
    build_model,
    split_microbatches,
    stage_params_spec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_enc_dec import (  # noqa: F401
    EncDecPipelineSpec,
    broadcast_from_last_stage,
    decoder_ring,
    forward_backward_pipelining_enc_dec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (  # noqa: F401
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (  # noqa: F401
    forward_backward_pipelining_with_interleaving,
    pipeline_ring_interleaved,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (  # noqa: F401
    forward_backward_pipelining_without_interleaving,
    pipeline_ring,
)


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size=None,
    pipeline_model_parallel_size=None,
):
    """Pick the driver the way the reference does (``schedules/__init__.py:16``):
    pp>1 and vp → interleaved; pp>1 → 1F1B ring; else grad-accum loop."""
    if pipeline_model_parallel_size is None:
        from apex_tpu.transformer import parallel_state

        pipeline_model_parallel_size = (
            parallel_state.get_pipeline_model_parallel_world_size()
        )
    if virtual_pipeline_model_parallel_size is None:
        from apex_tpu.transformer import parallel_state

        virtual_pipeline_model_parallel_size = (
            parallel_state.get_virtual_pipeline_model_parallel_world_size()
        )
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining

"""Stage-to-stage activation exchange over the pipeline mesh axis.

Reference: ``apex/transformer/pipeline_parallel/p2p_communication.py`` —
``_communicate`` (:70) batches ``isend``/``irecv`` (``P2POp`` +
``batch_isend_irecv``, :29-68) between adjacent pipeline stages and exposes
eight public ops (:187-408): ``recv_forward``, ``recv_backward``,
``send_forward``, ``send_backward``, and the four fused
``send_*_recv_*`` combinations.

TPU re-design: under SPMD there is no per-rank send/recv — every stage runs
the same program, so a "send to next stage" IS a "receive from the previous
stage" on the shifted device. The ICI-native primitive for this is
``lax.ppermute`` (collective permute), which XLA schedules to overlap with
compute (the reference manages this overlap by hand with separate NCCL ops).
Consequently the eight reference ops collapse onto two ring shifts:

* forward direction (activations): shift **+1** along the ``pp`` axis —
  :func:`send_forward_recv_forward`.
* backward direction (cotangents): shift **-1** — handled *automatically* by
  autodiff (the transpose of a ppermute is the inverse ppermute), but also
  exposed as :func:`send_backward_recv_backward` for hand-rolled schedules.

The individual ``send_forward`` / ``recv_forward`` names are kept as aliases
of the fused shift so schedule code written against the reference API reads
naturally. All functions must run inside a mesh program (``shard_map``).

The reference's ``scatter_gather_tensors_in_pipeline`` option (:70-186)
splits the transferred tensor across TP ranks to cut p2p volume; the analogue
here is :func:`send_forward_recv_forward` with ``scatter_gather=True``, which
reduce-scatters over ``tp`` before the shift and all-gathers after —
profitable when the TP all-gather is cheaper than (tp-1)/tp of the PP hop.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from apex_tpu.parallel.mesh import PP_AXIS, TP_AXIS


def _ring_perm(n: int, shift: int):
    return [(i, (i + shift) % n) for i in range(n)]


def _shift(x, shift: int, axis_name: str):
    n = lax.axis_size(axis_name)
    perm = _ring_perm(n, shift)
    return jax.tree.map(lambda a: lax.ppermute(a, axis_name, perm), x)


def send_forward_recv_forward(output_tensor, axis_name: str = PP_AXIS,
                              *, scatter_gather: bool = False):
    """Hand my stage's output to the next stage; receive the previous stage's
    (ref p2p_communication.py:350-372). Ring-wrapped: the last stage's output
    arrives at stage 0, where schedules either ignore it or (interleaved
    schedule) treat it as the next model chunk's input.
    """
    if scatter_gather:
        return _scatter_shift_gather(output_tensor, +1, axis_name)
    return _shift(output_tensor, +1, axis_name)


def send_backward_recv_backward(input_tensor_grad, axis_name: str = PP_AXIS):
    """Hand my stage's input-gradient to the previous stage
    (ref p2p_communication.py:373-395). Autodiff of
    :func:`send_forward_recv_forward` produces exactly this shift; it exists
    as a public op for schedules written with explicit VJPs."""
    return _shift(input_tensor_grad, -1, axis_name)


# Aliases: under SPMD each of these IS the fused shift (see module docstring).
# They take/return the full pytree; "recv" names return the shifted value,
# "send" names return it too (callers that only send simply drop it).

def send_forward(output_tensor, axis_name: str = PP_AXIS):
    """Ref :237-263."""
    return send_forward_recv_forward(output_tensor, axis_name)


def recv_forward(output_tensor_from_prev, axis_name: str = PP_AXIS):
    """Ref :187-212 — in SPMD the value to 'receive' is the previous stage's
    output, so the caller passes the pytree that every stage computed and
    gets back the shifted view."""
    return send_forward_recv_forward(output_tensor_from_prev, axis_name)


def send_backward(input_tensor_grad, axis_name: str = PP_AXIS):
    """Ref :264-290."""
    return send_backward_recv_backward(input_tensor_grad, axis_name)


def recv_backward(grad_from_next, axis_name: str = PP_AXIS):
    """Ref :213-236."""
    return send_backward_recv_backward(grad_from_next, axis_name)


def send_forward_recv_backward(output_tensor, grad_tensor,
                               axis_name: str = PP_AXIS):
    """Ref :291-319 — the 1F1B steady-state exchange: activations go up,
    cotangents come down, in one batched launch. Here: two independent
    ppermutes that XLA schedules concurrently over opposite ICI directions.
    Returns ``(recv_forward_value, recv_backward_value)``."""
    return _shift(output_tensor, +1, axis_name), _shift(grad_tensor, -1, axis_name)


def send_backward_recv_forward(input_tensor_grad, output_tensor,
                               axis_name: str = PP_AXIS):
    """Ref :320-349. Returns ``(recv_backward_value, recv_forward_value)``."""
    return _shift(input_tensor_grad, -1, axis_name), _shift(output_tensor, +1, axis_name)


def _scatter_shift_gather(x, shift: int, axis_name: str,
                          tp_axis: str = TP_AXIS):
    """Shift 1/tp of the tensor per TP rank, then reassemble
    (the ``scatter_gather_tensors_in_pipeline`` optimization,
    ref p2p_communication.py:100-186): each (pp, tp) device moves only its
    slice over the pp hop, and the full tensor is rebuilt with a TP
    all-gather, which rides the (faster/shorter) tp ICI ring."""

    def one(a):
        tp = lax.axis_size(tp_axis)
        if tp == 1 or a.shape[-1] % tp != 0:
            return lax.ppermute(a, axis_name, _ring_perm(lax.axis_size(axis_name), shift))
        i = lax.axis_index(tp_axis)
        chunk = a.shape[-1] // tp
        piece = lax.dynamic_slice_in_dim(a, i * chunk, chunk, a.ndim - 1)
        piece = lax.ppermute(piece, axis_name, _ring_perm(lax.axis_size(axis_name), shift))
        return lax.all_gather(piece, tp_axis, axis=a.ndim - 1, tiled=True)

    return jax.tree.map(one, x)

"""Shared test fixtures for the transformer stack.

Reference: ``apex/transformer/testing/commons.py`` — ``initialize_distributed``
(:105, TCP init from RANK/WORLD_SIZE) and ``fwd_step_func`` (:60) used by all
L0 transformer tests.

TPU analogue: "distributed init" is mesh construction (single process, all
devices — real chips or ``--xla_force_host_platform_device_count`` fakes),
and the forward-step fixture is a loss closure over the standalone GPT.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing.standalone_gpt import GPTConfig


def initialize_distributed(tp: int = 1, pp: int = 1, sp: int = 1,
                           vp: Optional[int] = None):
    """Build the mesh + parallel_state (ref commons.py:105-135; world size =
    visible devices, env-free)."""
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp,
        pipeline_model_parallel_size_=pp,
        sequence_parallel_size_=sp,
        virtual_pipeline_model_parallel_size_=vp,
    )


def set_random_seed(seed: int) -> jax.Array:
    """Ref commons set_random_seed: one PRNGKey, split per use."""
    return jax.random.PRNGKey(seed)


def make_test_batch(key, cfg: GPTConfig, batch: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Random (tokens, shifted-target) pair for LM steps."""
    tokens = jax.random.randint(key, (batch, cfg.max_seq), 0, cfg.vocab_size)
    return tokens, jnp.roll(tokens, -1, axis=1)


def fwd_step_func(cfg: GPTConfig):
    """Ref commons.py:60 — returns ``f(params, batch) -> loss`` over the
    standalone GPT (call inside a mesh program)."""
    from apex_tpu.transformer.testing.standalone_gpt import gpt_loss

    def f(params, batch):
        tokens, targets = batch
        return gpt_loss(params, tokens, targets, cfg)

    return f

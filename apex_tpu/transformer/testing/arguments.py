"""Megatron-style argument parser for the test/example stack.

Reference: ``apex/transformer/testing/arguments.py`` (808 LoC / ~150 flags of
Megatron surface). The TPU build's source of truth is :class:`GPTConfig`;
this parser accepts the reference-shaped invocations and converts them to a
config + parallel sizes + optimizer/schedule settings. Three tiers:

* flags that map onto the TPU stack are parsed and *used* (model shape,
  parallel sizes, dropout, remat/recompute, precision, loss scaling,
  batch ramp-up, optimizer hyperparameters, train length, seed);
* recognized-but-inert reference flags parse without error and are listed in
  ``namespace.inert_flags`` with a warning (the TPU design makes them
  meaningless — e.g. ``--distributed-backend``, NCCL/DDP plumbing);
* unknown flags do NOT abort: ``parse_args`` uses ``parse_known_args`` and
  warns, so any reference-shaped command line runs (the unknown remainder is
  in ``namespace.unknown_flags``).
"""

from __future__ import annotations

import argparse
import warnings
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from apex_tpu.transformer.testing.standalone_gpt import GPTConfig

# parsed, accepted, and deliberately inert on TPU (XLA owns the concern).
_INERT_FLAGS = {
    "--distributed-backend": str,   # collectives are XLA's, not NCCL/gloo
    "--DDP-impl": str,              # one DDP: parallel.DistributedDataParallel
    "--local_rank": int,            # no per-process launcher rank under SPMD
    "--use-cpu-initialization": None,  # init is TP-invariant by construction
    "--masked-softmax-fusion": None,   # XLA/Pallas fuse unconditionally
    "--bias-gelu-fusion": None,
    "--bias-dropout-fusion": None,
    "--gradient-accumulation-fusion": None,  # optimizers/grad_accumulation
    "--num-workers": int,           # data loading is the native loader's job
    "--dataloader-type": str,
    "--recompute-method": str,      # scan-over-layers has one method
    "--recompute-num-layers": int,
    "--layernorm-epsilon": float,   # GPTConfig pins layer_norm's default eps
}


def parse_args(argv: Optional[Sequence[str]] = None,
               allow_unknown: bool = True) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="apex_tpu transformer test args")
    g = p.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=12)
    g.add_argument("--hidden-size", type=int, default=768)
    g.add_argument("--num-attention-heads", type=int, default=12)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--seq-length", type=int, default=1024)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--vocab-size", type=int, default=50304)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--untie-embeddings-and-output-weights",
                   action="store_true")

    g = p.add_argument_group("regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)

    g = p.add_argument_group("parallel")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--sequence-parallel-size", type=int, default=1)

    g = p.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=1)
    g.add_argument("--global-batch-size", type=int, default=8)
    g.add_argument("--rampup-batch-size", nargs=3, type=int, default=None)
    g.add_argument("--train-iters", type=int, default=None)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--optimizer", default="adam", choices=["adam", "sgd"])
    g.add_argument("--seed", type=int, default=1234)

    g = p.add_argument_group("learning rate")
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--min-lr", type=float, default=0.0)
    g.add_argument("--lr-decay-style", default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)

    g = p.add_argument_group("checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)

    g = p.add_argument_group("mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")

    g = p.add_argument_group("activation checkpointing")
    g.add_argument("--no-activation-checkpoint", action="store_true",
                   dest="no_remat")
    g.add_argument("--recompute-granularity", default=None,
                   choices=["full", "selective"])

    g = p.add_argument_group("accepted-but-inert (reference compat)")
    for flag, typ in _INERT_FLAGS.items():
        if typ is None:
            g.add_argument(flag, action="store_true")
        else:
            g.add_argument(flag, type=typ, default=None)

    if allow_unknown:
        args, unknown = p.parse_known_args(argv)
        if unknown:
            warnings.warn(
                f"ignoring unknown reference flags: {unknown}", stacklevel=2)
        args.unknown_flags = unknown
    else:
        args = p.parse_args(argv)
        args.unknown_flags = []

    # store_true inert flags read False when absent; typed ones default None
    # (a set 0 — e.g. --local_rank 0 — must still be reported)
    inert = []
    for f in _INERT_FLAGS:
        val = getattr(args, f.lstrip("-").replace("-", "_"), None)
        if val is not None and val is not False:
            inert.append(f)
    if inert:
        warnings.warn(
            f"reference flags parsed but inert on TPU: {inert}", stacklevel=2)
    args.inert_flags = inert

    if args.fp16 and args.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    if (args.kv_channels is not None
            and args.kv_channels * args.num_attention_heads
            != args.hidden_size):
        raise ValueError(
            "kv-channels * num-attention-heads must equal hidden-size "
            "(independent head dims are not supported)")
    return args


def args_to_config(args: argparse.Namespace) -> GPTConfig:
    """Namespace -> :class:`GPTConfig` (the dataclass the models consume)."""
    dtype = jnp.float32
    if args.bf16:
        dtype = jnp.bfloat16
    elif args.fp16:
        dtype = jnp.float16
    hidden = args.hidden_size
    ffn = args.ffn_hidden_size or 4 * hidden
    if ffn % hidden:
        raise ValueError("ffn_hidden_size must be a multiple of hidden_size")
    remat_policy = "full"
    if args.recompute_granularity == "selective":
        remat_policy = "dots"
    return GPTConfig(
        vocab_size=args.vocab_size,
        max_seq=args.max_position_embeddings or args.seq_length,
        hidden=hidden,
        num_layers=args.num_layers,
        num_heads=args.num_attention_heads,
        ffn_mult=ffn // hidden,
        dtype=dtype,
        tie_embeddings=not args.untie_embeddings_and_output_weights,
        remat=not args.no_remat,
        remat_policy=remat_policy,
        attention_dropout=args.attention_dropout,
        hidden_dropout=args.hidden_dropout,
    )


def parallel_sizes(args: argparse.Namespace) -> Tuple[int, int, int]:
    """(tp, pp, sp) from the namespace."""
    return (args.tensor_model_parallel_size,
            args.pipeline_model_parallel_size,
            args.sequence_parallel_size)


def _iters_from_samples(args: argparse.Namespace) -> Optional[int]:
    """Iteration count implied by ``--train-samples``, walking the batch
    ramp-up when active (ramp-phase iterations consume fewer samples each,
    so a plain samples/global-batch division would end LR decay early)."""
    if not args.train_samples:
        return None
    if args.rampup_batch_size is None:
        return args.train_samples // args.global_batch_size
    # mirror RampupBatchsizeNumMicroBatches: batch grows from start by
    # increment every ramp_samples/num_increments consumed samples
    start, inc, ramp_samples = (int(v) for v in args.rampup_batch_size)
    if start <= 0 or inc <= 0:
        raise ValueError(
            f"--rampup-batch-size needs positive start and increment, got "
            f"{args.rampup_batch_size}")
    if (args.global_batch_size - start) % inc != 0:
        # mirror RampupBatchsizeNumMicroBatches' consistency check: a
        # non-dividing increment would silently floor num_increments here
        # while the microbatch calculator rejects the same config
        raise ValueError(
            f"--rampup-batch-size: global batch {args.global_batch_size} "
            f"minus start {start} must be a multiple of increment {inc}")
    num_inc = max((args.global_batch_size - start) // inc, 1)
    per_level = ramp_samples / num_inc
    iters, consumed, batch = 0, 0, start
    while consumed < min(ramp_samples, args.train_samples):
        batch = min(start + int(consumed / per_level) * inc,
                    args.global_batch_size)
        consumed += batch
        iters += 1
    remaining = args.train_samples - consumed
    if remaining > 0:
        iters += remaining // args.global_batch_size
    return iters


def make_optimizer(args: argparse.Namespace):
    """Namespace -> fused optimizer + optax LR schedule (ref Megatron
    optimizer/scheduler construction from the same flags)."""
    import optax

    from apex_tpu.optimizers import FusedAdam, FusedSGD

    total = (args.lr_decay_iters or args.train_iters
             or _iters_from_samples(args) or 10000)
    warmup = args.lr_warmup_iters
    if args.lr_warmup_fraction is not None:
        warmup = int(args.lr_warmup_fraction * total)
    if args.lr_decay_style == "constant":
        after = optax.constant_schedule(args.lr)
    elif args.lr_decay_style == "cosine":
        after = optax.cosine_decay_schedule(
            args.lr, max(total - warmup, 1),
            alpha=args.min_lr / args.lr if args.lr else 0.0)
    else:  # linear
        after = optax.linear_schedule(
            args.lr, args.min_lr, max(total - warmup, 1))
    if warmup > 0:
        schedule = optax.join_schedules(
            [optax.linear_schedule(0.0, args.lr, warmup), after], [warmup])
    else:
        schedule = after
    if args.optimizer == "sgd":
        return FusedSGD(lr=schedule, momentum=args.sgd_momentum,
                        weight_decay=args.weight_decay), schedule
    return FusedAdam(lr=schedule, betas=(args.adam_beta1, args.adam_beta2),
                     eps=args.adam_eps,
                     weight_decay=args.weight_decay), schedule


def make_loss_scaler(args: argparse.Namespace):
    """Namespace -> :class:`apex_tpu.amp.LossScaler` (ref Megatron
    ``--loss-scale*``/``--hysteresis`` wiring into its GradScaler). Static
    scale when ``--loss-scale`` is given, dynamic under ``--fp16``, and None
    for bf16/fp32 runs (TPU bf16 needs no scaling — the flags would be
    wasted work, not wrong answers)."""
    from apex_tpu.amp.scaler import LossScaler

    if args.loss_scale is not None:
        return LossScaler(args.loss_scale)
    if args.fp16:
        return LossScaler(
            "dynamic",
            init_scale=args.initial_loss_scale,
            min_loss_scale=args.min_loss_scale,
            scale_window=args.loss_scale_window,
            hysteresis=args.hysteresis,
        )
    return None


def make_microbatch_calculator(args: argparse.Namespace,
                               data_parallel_size: int, rank: int = 0):
    """Namespace -> microbatch calculator (ref ``--rampup-batch-size`` /
    ``--global-batch-size`` / ``--micro-batch-size`` into
    ``build_num_microbatches_calculator``)."""
    from apex_tpu.transformer.pipeline_parallel.microbatches import (
        build_num_microbatches_calculator,
    )

    return build_num_microbatches_calculator(
        rank, args.rampup_batch_size, args.global_batch_size,
        args.micro_batch_size, data_parallel_size)


def ddp_options(args: argparse.Namespace) -> dict:
    """Namespace -> :class:`parallel.DistributedDataParallel` kwargs
    (``--accumulate-allreduce-grads-in-fp32`` -> fp32 grad communication,
    the ref ``allreduce_always_fp32``/``main_grad`` pathway)."""
    return {"allreduce_always_fp32": args.accumulate_allreduce_grads_in_fp32}


class Checkpointer:
    """``--save``/``--load``/``--save-interval`` wired to
    ``utils.checkpoint`` (ref Megatron save/load_checkpoint surface)."""

    def __init__(self, save: Optional[str], load: Optional[str],
                 save_interval: Optional[int]):
        self.save_dir = save
        self.load_dir = load if load is not None else save
        self.save_interval = save_interval

    def load(self, target=None):
        """Restore the latest checkpoint from ``--load`` (None when absent
        or the directory is empty)."""
        import os
        import re

        from apex_tpu.utils.checkpoint import load_checkpoint

        if not self.load_dir or not os.path.isdir(self.load_dir):
            return None
        found = {}
        for d in os.listdir(self.load_dir):
            # anchored: orbax temp dirs from an interrupted save
            # (step_N.orbax-checkpoint-tmp-*) must not shadow step_N
            m = re.fullmatch(r"step_(\d+)(\.npz\.pkl)?", d)
            if m:
                n = int(m.group(1))
                # when both an orbax dir and a pickle exist for one step,
                # prefer the orbax dir regardless of listdir order
                if n not in found or m.group(2) is None:
                    found[n] = d
        if not found:
            return None
        return load_checkpoint(
            os.path.join(self.load_dir, found[max(found)]), target)

    def maybe_save(self, state, step: int) -> bool:
        """Save when ``--save`` is set and ``step`` hits the interval."""
        import os

        from apex_tpu.utils.checkpoint import save_checkpoint

        if not self.save_dir:
            return False
        if self.save_interval and step % self.save_interval:
            return False
        os.makedirs(self.save_dir, exist_ok=True)
        save_checkpoint(os.path.join(self.save_dir, f"step_{step}"), state)
        return True


def make_checkpointer(args: argparse.Namespace) -> Checkpointer:
    return Checkpointer(args.save, args.load, args.save_interval)

"""Megatron-style argument parser for the test/example stack.

Reference: ``apex/transformer/testing/arguments.py`` (808 LoC of Megatron
flags). The TPU build's source of truth is :class:`GPTConfig`; this parser
exposes the subset of flags the test stack actually exercises and converts
them to a config + parallel sizes, so reference-shaped test invocations
(``--tensor-model-parallel-size 2 --pipeline-model-parallel-size 2 ...``)
keep working.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from apex_tpu.transformer.testing.standalone_gpt import GPTConfig


def parse_args(argv: Optional[Sequence[str]] = None
               ) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="apex_tpu transformer test args")
    g = p.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=12)
    g.add_argument("--hidden-size", type=int, default=768)
    g.add_argument("--num-attention-heads", type=int, default=12)
    g.add_argument("--seq-length", type=int, default=1024)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--vocab-size", type=int, default=50304)
    g.add_argument("--ffn-hidden-size", type=int, default=None)

    g = p.add_argument_group("parallel")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--sequence-parallel-size", type=int, default=1)

    g = p.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=1)
    g.add_argument("--global-batch-size", type=int, default=8)
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--no-activation-checkpoint", action="store_true",
                   dest="no_remat")
    return p.parse_args(argv)


def args_to_config(args: argparse.Namespace) -> GPTConfig:
    """Namespace -> :class:`GPTConfig` (the dataclass the models consume)."""
    dtype = jnp.float32
    if args.bf16:
        dtype = jnp.bfloat16
    elif args.fp16:
        dtype = jnp.float16
    hidden = args.hidden_size
    ffn = args.ffn_hidden_size or 4 * hidden
    if ffn % hidden:
        raise ValueError("ffn_hidden_size must be a multiple of hidden_size")
    return GPTConfig(
        vocab_size=args.vocab_size,
        max_seq=args.max_position_embeddings or args.seq_length,
        hidden=hidden,
        num_layers=args.num_layers,
        num_heads=args.num_attention_heads,
        ffn_mult=ffn // hidden,
        dtype=dtype,
        remat=not args.no_remat,
    )


def parallel_sizes(args: argparse.Namespace) -> Tuple[int, int, int]:
    """(tp, pp, sp) from the namespace."""
    return (args.tensor_model_parallel_size,
            args.pipeline_model_parallel_size,
            args.sequence_parallel_size)

"""Self-contained Megatron-style test models (ref ``apex/transformer/testing``).

``standalone_gpt`` / ``standalone_bert`` are the fixtures the reference's L0
transformer suite trains through TP+PP (``standalone_gpt.py:1440``,
``standalone_bert.py``); here they double as the flagship models for the
benchmark harness.
"""

from apex_tpu.transformer.testing.standalone_gpt import (  # noqa: F401
    GPTConfig,
    gpt_forward,
    gpt_loss,
    gpt_param_specs,
    gpt_pipeline_params,
    gpt_pipeline_spec,
    gpt_pipeline_specs_tree,
    init_gpt_params,
)
from apex_tpu.transformer.testing.standalone_bert import (  # noqa: F401
    BertConfig,
    bert_forward,
    bert_mlm_loss,
    init_bert_params,
)

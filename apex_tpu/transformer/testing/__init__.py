"""Self-contained Megatron-style test models (ref ``apex/transformer/testing``).

``standalone_gpt`` / ``standalone_bert`` are the fixtures the reference's L0
transformer suite trains through TP+PP (``standalone_gpt.py:1440``,
``standalone_bert.py``); here they double as the flagship models for the
benchmark harness. ``standalone_t5`` adds the encoder-decoder consumer the
reference specifies (ModelType.encoder_and_decoder) but never shipped a
fixture for.
"""

from apex_tpu.transformer.testing.standalone_gpt import (  # noqa: F401
    GPTConfig,
    gpt_forward,
    gpt_loss,
    gpt_param_specs,
    gpt_pipeline_params,
    gpt_pipeline_spec,
    gpt_pipeline_specs_tree,
    init_gpt_params,
)
from apex_tpu.transformer.testing.standalone_bert import (  # noqa: F401
    BertConfig,
    bert_forward,
    bert_mlm_loss,
    init_bert_params,
)
from apex_tpu.transformer.testing.standalone_t5 import (  # noqa: F401
    T5Config,
    init_t5_params,
    t5_enc_dec_spec,
    t5_loss,
    t5_param_specs,
    t5_pipeline_params,
    t5_pipeline_specs_tree,
)

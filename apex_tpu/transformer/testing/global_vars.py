"""Global args registry for the transformer test stack.

Reference: ``apex/transformer/testing/global_vars.py:270`` — Megatron-style
singletons (``get_args``/``set_global_variables``). Kept minimal: the real
configuration system is :class:`apex_tpu.transformer.testing.GPTConfig`
(SURVEY §5: unify the reference's three config systems into dataclasses);
this registry only serves ported test code that expects ``get_args()``.
"""

from __future__ import annotations

from typing import Any, Optional

_GLOBAL_ARGS: Optional[Any] = None


def set_args(args: Any) -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = args


def get_args() -> Any:
    if _GLOBAL_ARGS is None:
        raise RuntimeError("global args not initialized (call set_args)")
    return _GLOBAL_ARGS


def destroy_global_vars() -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = None

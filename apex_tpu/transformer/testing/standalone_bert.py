"""Standalone Megatron-style BERT (ref ``apex/transformer/testing/standalone_bert.py``).

Bidirectional encoder over the same TP layer stack as the GPT fixture
(``standalone_gpt._layer_stack`` with ``causal=False`` and a padding mask),
token/position/type embeddings, and a tied MLM head with the Megatron
dense→gelu→LN transform. Used by the pipeline/TP tests the way the
reference's ``run_bert_minimal_test.py`` uses its BERT.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    vocab_parallel_embedding,
)
from apex_tpu.transformer.testing.standalone_gpt import (
    GPTConfig,
    _init_layer,
    _layer_stack,
)

Pytree = Any


@dataclasses.dataclass(frozen=True)
class BertConfig(GPTConfig):
    num_token_types: int = 2


def init_bert_params(rng, cfg: BertConfig) -> Pytree:
    cfg.validate()
    ke, kl, kh = jax.random.split(rng, 3)
    layer_rngs = jax.random.split(kl, cfg.num_layers)
    layers = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[_init_layer(k, cfg) for k in layer_rngs])
    dt = cfg.dtype
    h = cfg.hidden
    return {
        "embed": {
            "tok": (jax.random.normal(ke, (cfg.vocab_size, h)) * 0.02
                    ).astype(dt),
            "pos": (jax.random.normal(jax.random.fold_in(ke, 1),
                                      (cfg.max_seq, h)) * 0.02).astype(dt),
            "type": (jax.random.normal(jax.random.fold_in(ke, 2),
                                       (cfg.num_token_types, h)) * 0.02
                     ).astype(dt),
            "ln_w": jnp.ones((h,), dt), "ln_b": jnp.zeros((h,), dt),
        },
        "layers": layers,
        "head": {  # Megatron MLM head: dense+gelu+LN, decoder tied to embed
            "dense_kernel": (jax.random.normal(kh, (h, h)) * 0.02).astype(dt),
            "dense_bias": jnp.zeros((h,), dt),
            "ln_w": jnp.ones((h,), dt), "ln_b": jnp.zeros((h,), dt),
        },
    }


def _bert_logits(params, tokens, cfg: BertConfig, token_types=None,
                 padding_mask=None):
    """-> (vocab-sharded MLM logits, MoE aux loss). Under
    ``cfg.megatron_sp`` the embedding's tp-psum becomes a reduce-scatter
    along the sequence (the GPT entry), the LN/dropout-class regions run
    on the (b, s/tp, h) shard, and the MLM head gathers the sequence back
    (its vocab dim is sharded over the same tp axis)."""
    from jax import lax

    from apex_tpu.parallel.mesh import TP_AXIS
    from apex_tpu.transformer.testing.standalone_gpt import embed_tokens

    e = params["embed"]
    # tok + pos (incl. the megatron_sp reduce-scatter entry and the
    # rank-offset pos slice) are the GPT embedding — one source of truth
    x = embed_tokens(e, tokens, megatron_sp=cfg.megatron_sp)
    if token_types is not None:
        if cfg.megatron_sp:
            # same shard coordinates embed_tokens used for pos
            s_shard = tokens.shape[1] // lax.axis_size(TP_AXIS)
            tt = lax.dynamic_slice_in_dim(
                token_types, lax.axis_index(TP_AXIS) * s_shard, s_shard, 1)
        else:
            tt = token_types
        x = x + jnp.take(e["type"], tt, axis=0).astype(x.dtype)
    x = layer_norm(x, e["ln_w"], e["ln_b"])
    attn_mask = None
    if padding_mask is not None:
        # the attention core always sees the gathered sequence (the
        # megatron_sp QKV entry all-gathers), so the mask stays full-seq
        attn_mask = padding_mask[:, None, None, :]
    x, aux = _layer_stack(params["layers"], x, cfg, causal=False,
                          mask=attn_mask)
    h = params["head"]
    # the dense->gelu->LN transform is per-token with replicated weights,
    # so it runs on the (b, s/tp, h) SHARD; the shared tied-head exit
    # gathers the sequence only for the vocab einsum
    x = x @ h["dense_kernel"] + h["dense_bias"]
    x = jax.nn.gelu(x, approximate=True)
    x = layer_norm(x, h["ln_w"], h["ln_b"])
    from apex_tpu.transformer.testing.standalone_gpt import (
        tied_vocab_logits,
    )

    return tied_vocab_logits(x, e["tok"], cfg.megatron_sp), aux


def bert_forward(params, tokens, cfg: BertConfig, token_types=None,
                 padding_mask=None):
    """tokens (b, s) -> vocab-sharded MLM logits (b, s, vocab/tp).

    ``padding_mask``: (b, s) True = pad (masked out of attention both ways).
    Call inside a mesh program. The MoE router aux loss (if any) is
    dropped here — use :func:`bert_mlm_loss` for training.
    """
    logits, _aux = _bert_logits(params, tokens, cfg, token_types,
                                padding_mask)
    return logits


def bert_mlm_loss(params, tokens, targets, loss_mask, cfg: BertConfig,
                  token_types=None, padding_mask=None):
    """Masked-LM loss: vocab-parallel CE on masked positions only (ref
    standalone_bert loss path). ``loss_mask`` (b, s) 1 = predict here.
    With ``cfg.num_experts`` the layer-mean router aux loss is added."""
    logits, aux = _bert_logits(params, tokens, cfg, token_types,
                               padding_mask)
    per_tok = vocab_parallel_cross_entropy(logits, targets)
    m = loss_mask.astype(jnp.float32)
    return jnp.sum(per_tok * m) / jnp.maximum(jnp.sum(m), 1.0) + aux

"""Standalone T5-style encoder-decoder — the enc-dec pipeline's model family.

Reference: ``ModelType.encoder_and_decoder`` consumers —
``apex/transformer/pipeline_parallel/schedules/common.py:72-103`` builds
encoder blocks before ``pipeline_model_parallel_split_rank`` and decoder
blocks (self-attention + cross-attention + MLP) after it; the reference
ships no standalone T5 *fixture* (its tests stop at GPT/BERT), so this
module supplies the missing consumer the schedules are specified against.

TPU design, same contract as ``standalone_gpt``: pure functions over a
global-shape parameter pytree, Megatron TP layout (column-parallel QKV/FC1
and cross-attention Q/KV, row-parallel out-proj/FC2, vocab-parallel shared
embedding + loss), flash-attention cores (causal for decoder self-attn,
rectangular ``s_dec × s_enc`` for cross-attn), pre-LN residual blocks.
Position scheme: learned absolute positions by default, or T5's real
bucketed relative position biases with ``relative_position_bias=True``
(bias added to the logits inside the flash kernel — encoder bidirectional,
decoder causal, none on cross-attention, per-stack tables; rides ring SP
via per-shard bias strips). ``encoder_final_ln=True`` restores T5's
encoder-exit LayerNorm, applied equivalently at the decoder's memory
consumption so the enc pipeline ring keeps its uniform stage function.
With both flags on the fixture is architecturally T5-the-paper (modulo
LayerNorm-with-bias vs T5's bias-free RMSNorm, a config choice the
normalization module supports either way).

Pipeline wiring: :func:`t5_enc_dec_spec` + :func:`t5_pipeline_params`
feed ``schedules.fwd_bwd_enc_dec`` — encoder ring over all pp stages,
memory broadcast, decoder ring (see that module for why this beats the
reference's split-rank device partition).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.parallel.mesh import PP_AXIS, SP_AXIS, TP_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules import EncDecPipelineSpec
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)

Pytree = Any


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    hidden: int = 512
    num_heads: int = 8
    enc_layers: int = 6
    dec_layers: int = 6
    ffn_mult: int = 4
    max_seq_enc: int = 512
    max_seq_dec: int = 512
    dtype: Any = jnp.bfloat16
    remat: bool = True
    fused_loss: bool = True
    attn_block_q: int = 512
    attn_block_k: int = 512
    # Ref attention-/hidden-dropout sites, same RNG policy as
    # standalone_gpt: active only when the caller passes ``dropout_key``;
    # attention dropout runs INSIDE the flash kernel with a TP-rank-folded
    # seed (tp ranks drop independent entries of their own heads), hidden/
    # embedding dropout uses the unfolded key (same across the TP group —
    # the activations are TP-replicated).
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # Megatron-SP over the tp axis (same design as GPTConfig.megatron_sp):
    # LN/residual regions run on (b, s/tp, h) sequence shards, TP blocks
    # gather on entry and reduce-scatter on exit. In the enc-dec pipeline
    # this also shrinks the ring p2p tensors AND the cross-attention
    # memory broadcast by tp.
    megatron_sp: bool = False
    # T5's signature position scheme (opt-in): bucketed relative position
    # biases added to the attention logits INSIDE the flash kernel
    # (ops/attention.py bias path) — bidirectional buckets for encoder
    # self-attention, causal buckets for decoder self-attention, none for
    # cross-attention, one (buckets, heads) table per stack shared across
    # its layers (the T5 layout; heads split over tp). When enabled the
    # learned absolute position tables are skipped (T5 has none).
    relative_position_bias: bool = False
    rel_pos_buckets: int = 32
    rel_pos_max_distance: int = 128
    # T5's encoder-final LayerNorm (opt-in). Applied to the memory at the
    # point of decoder consumption rather than inside the encoder ring:
    # every decoder layer reads the same broadcast memory, so normalizing
    # it once before the decoder stack is EXACTLY the paper's
    # normalize-at-encoder-exit — while the enc pipeline ring keeps its
    # uniform stage function (the reason the trim existed).
    encoder_final_ln: bool = False

    @property
    def ffn_hidden(self) -> int:
        return self.ffn_mult * self.hidden

    @property
    def head_dim(self) -> int:
        return self.hidden // self.num_heads

    def validate(self, tp: int = 1) -> None:
        if self.hidden % self.num_heads:
            raise ValueError("hidden must be divisible by num_heads")
        for name, dim in (("vocab_size", self.vocab_size),
                          ("num_heads", self.num_heads),
                          ("ffn_hidden", self.ffn_hidden)):
            if dim % tp:
                raise ValueError(f"{name} ({dim}) not divisible by tp ({tp})")
        if self.megatron_sp and (self.max_seq_enc % tp
                                 or self.max_seq_dec % tp):
            raise ValueError(
                f"megatron_sp needs max_seq_enc ({self.max_seq_enc}) and "
                f"max_seq_dec ({self.max_seq_dec}) divisible by tp ({tp})")
        if self.relative_position_bias:
            if self.rel_pos_buckets % 2:
                raise ValueError("rel_pos_buckets must be even (half the "
                                 "buckets serve each direction in the "
                                 "bidirectional encoder scheme)")
            if self.rel_pos_max_distance <= self.rel_pos_buckets // 2:
                # the log-spaced range needs max_distance > max_exact for
                # BOTH schemes (decoder max_exact = buckets/2); at or
                # below it the bucket formula divides by log(<=1)
                raise ValueError(
                    f"rel_pos_max_distance ({self.rel_pos_max_distance}) "
                    f"must exceed rel_pos_buckets/2 "
                    f"({self.rel_pos_buckets // 2})")


# ---------------------------------------------------------------------------
# relative position bias (T5 scheme: log-spaced distance buckets)

def _rel_pos_bucket(rel, *, bidirectional: bool, num_buckets: int,
                    max_distance: int):
    """Bucket index for ``rel = k_pos - q_pos`` (int32 array).

    The T5 bucketing (paper §2.1): exact buckets for small distances, one
    log-spaced bucket per range up to ``max_distance``, everything farther
    in the last bucket; bidirectional splits the buckets between the two
    sign halves, unidirectional (decoder) buckets only the past.
    """
    ret = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        ret = ret + (rel > 0).astype(jnp.int32) * num_buckets
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    # log-spaced: position max_exact..max_distance maps onto the remaining
    # buckets; the +1e-6 keeps log finite at rel == 0 (masked by is_small)
    val_large = max_exact + (
        jnp.log(rel.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, rel, val_large)


def t5_relative_bias(table_local, sq: int | None = None,
                     sk: int | None = None, *, bidirectional: bool,
                     cfg: T5Config, qpos=None, kpos=None):
    """(heads_local, sq, sk) fp32 additive logit bias from the local
    (buckets, heads_local) table shard — feeds ``flash_attention(bias=)``.
    Inside shard_map the table param is already the TP head shard, so each
    rank builds exactly its own heads' bias. Pass explicit ``qpos``/
    ``kpos`` (global position arrays) instead of ``sq``/``sk`` to build a
    ring-SP strip — this device's Q rows against all global key columns."""
    if qpos is None:
        qpos = jnp.arange(sq, dtype=jnp.int32)
    if kpos is None:
        kpos = jnp.arange(sk, dtype=jnp.int32)
    buckets = _rel_pos_bucket(
        kpos[None, :] - qpos[:, None], bidirectional=bidirectional,
        num_buckets=cfg.rel_pos_buckets,
        max_distance=cfg.rel_pos_max_distance)
    return table_local.astype(jnp.float32)[buckets].transpose(2, 0, 1)


def _init_rel_tables(rng, cfg: T5Config) -> Pytree:
    dt = cfg.dtype
    kq, kk = jax.random.split(rng)
    shape = (cfg.rel_pos_buckets, cfg.num_heads)
    return {
        "rel_enc": (jax.random.normal(kq, shape) * 0.02).astype(dt),
        "rel_dec": (jax.random.normal(kk, shape) * 0.02).astype(dt),
    }


# ---------------------------------------------------------------------------
# init (global shapes)

def _mlp_params(ks, cfg: T5Config, out_std: float) -> Pytree:
    h, f, dt = cfg.hidden, cfg.ffn_hidden, cfg.dtype
    return {
        "fc1_kernel": (jax.random.normal(ks[0], (h, f)) * 0.02).astype(dt),
        "fc1_bias": jnp.zeros((f,), dt),
        "fc2_kernel": (jax.random.normal(ks[1], (f, h)) * out_std).astype(dt),
        "fc2_bias": jnp.zeros((h,), dt),
    }


def _init_enc_layer(rng, cfg: T5Config) -> Pytree:
    h, dt = cfg.hidden, cfg.dtype
    ks = jax.random.split(rng, 4)
    out_std = 0.02 / math.sqrt(2.0 * cfg.enc_layers)
    return {
        "ln1_w": jnp.ones((h,), dt), "ln1_b": jnp.zeros((h,), dt),
        "qkv_kernel": (jax.random.normal(ks[0], (h, 3 * h)) * 0.02).astype(dt),
        "qkv_bias": jnp.zeros((3 * h,), dt),
        "out_kernel": (jax.random.normal(ks[1], (h, h)) * out_std).astype(dt),
        "out_bias": jnp.zeros((h,), dt),
        "ln2_w": jnp.ones((h,), dt), "ln2_b": jnp.zeros((h,), dt),
        **_mlp_params(ks[2:], cfg, out_std),
    }


def _init_dec_layer(rng, cfg: T5Config) -> Pytree:
    h, dt = cfg.hidden, cfg.dtype
    ks = jax.random.split(rng, 7)
    out_std = 0.02 / math.sqrt(2.0 * (cfg.enc_layers + cfg.dec_layers))
    return {
        "ln1_w": jnp.ones((h,), dt), "ln1_b": jnp.zeros((h,), dt),
        "qkv_kernel": (jax.random.normal(ks[0], (h, 3 * h)) * 0.02).astype(dt),
        "qkv_bias": jnp.zeros((3 * h,), dt),
        "out_kernel": (jax.random.normal(ks[1], (h, h)) * out_std).astype(dt),
        "out_bias": jnp.zeros((h,), dt),
        "ln2_w": jnp.ones((h,), dt), "ln2_b": jnp.zeros((h,), dt),
        # cross-attention: Q from decoder stream, fused KV from memory
        "q_kernel": (jax.random.normal(ks[2], (h, h)) * 0.02).astype(dt),
        "q_bias": jnp.zeros((h,), dt),
        "kv_kernel": (jax.random.normal(ks[3], (h, 2 * h)) * 0.02).astype(dt),
        "kv_bias": jnp.zeros((2 * h,), dt),
        "xout_kernel": (jax.random.normal(ks[4], (h, h)) * out_std).astype(dt),
        "xout_bias": jnp.zeros((h,), dt),
        "ln3_w": jnp.ones((h,), dt), "ln3_b": jnp.zeros((h,), dt),
        **_mlp_params(ks[5:], cfg, out_std),
    }


def init_t5_params(rng, cfg: T5Config) -> Pytree:
    """Global-shape pytree ``{"embed", "enc_layers" [Le], "dec_layers"
    [Ld], "head"}``; shared token table, tied LM head (the T5 convention)."""
    cfg.validate()
    ke, kenc, kdec = jax.random.split(rng, 3)
    enc = jax.tree.map(lambda *xs: jnp.stack(xs), *[
        _init_enc_layer(k, cfg)
        for k in jax.random.split(kenc, cfg.enc_layers)])
    dec = jax.tree.map(lambda *xs: jnp.stack(xs), *[
        _init_dec_layer(k, cfg)
        for k in jax.random.split(kdec, cfg.dec_layers)])
    dt = cfg.dtype
    embed = {
        "tok": (jax.random.normal(ke, (cfg.vocab_size, cfg.hidden))
                * 0.02).astype(dt),
    }
    if cfg.encoder_final_ln:
        embed["enc_ln_w"] = jnp.ones((cfg.hidden,), dt)
        embed["enc_ln_b"] = jnp.zeros((cfg.hidden,), dt)
    if cfg.relative_position_bias:
        # T5 proper: no absolute positions; one rel-bias table per stack
        embed.update(_init_rel_tables(jax.random.fold_in(ke, 3), cfg))
    else:
        embed["pos_enc"] = (jax.random.normal(
            jax.random.fold_in(ke, 1), (cfg.max_seq_enc, cfg.hidden))
            * 0.02).astype(dt)
        embed["pos_dec"] = (jax.random.normal(
            jax.random.fold_in(ke, 2), (cfg.max_seq_dec, cfg.hidden))
            * 0.02).astype(dt)
    return {
        "embed": embed,
        "enc_layers": enc,
        "dec_layers": dec,
        "head": {
            "ln_w": jnp.ones((cfg.hidden,), dt),
            "ln_b": jnp.zeros((cfg.hidden,), dt),
        },
    }


def _layer_specs(keys, lead) -> Pytree:
    tp_cols = {"qkv_kernel", "fc1_kernel", "q_kernel", "kv_kernel"}
    tp_col_bias = {"qkv_bias", "fc1_bias", "q_bias", "kv_bias"}
    tp_rows = {"out_kernel", "fc2_kernel", "xout_kernel"}
    out = {}
    for k in keys:
        if k in tp_cols:
            out[k] = P(*lead, None, TP_AXIS)
        elif k in tp_col_bias:
            out[k] = P(*lead, TP_AXIS)
        elif k in tp_rows:
            out[k] = P(*lead, TP_AXIS, None)
        else:
            out[k] = P(*lead)
    return out


def t5_param_specs(cfg: T5Config, extra_layer_lead=()) -> Pytree:
    """PartitionSpecs matching :func:`init_t5_params` (Megatron TP layout,
    same dims as ``gpt_param_specs``)."""
    lead = tuple(extra_layer_lead) + (None,)
    enc_keys = ("ln1_w", "ln1_b", "qkv_kernel", "qkv_bias", "out_kernel",
                "out_bias", "ln2_w", "ln2_b", "fc1_kernel", "fc1_bias",
                "fc2_kernel", "fc2_bias")
    dec_keys = enc_keys + ("q_kernel", "q_bias", "kv_kernel", "kv_bias",
                           "xout_kernel", "xout_bias", "ln3_w", "ln3_b")
    embed = {"tok": P(TP_AXIS, None)}
    if cfg.encoder_final_ln:
        embed["enc_ln_w"] = P()
        embed["enc_ln_b"] = P()
    if cfg.relative_position_bias:
        # heads axis TP-split: each rank holds its own heads' bias columns
        embed["rel_enc"] = P(None, TP_AXIS)
        embed["rel_dec"] = P(None, TP_AXIS)
    else:
        embed["pos_enc"] = P()
        embed["pos_dec"] = P()
    return {
        "embed": embed,
        "enc_layers": _layer_specs(enc_keys, lead),
        "dec_layers": _layer_specs(dec_keys, lead),
        "head": {"ln_w": P(), "ln_b": P()},
    }


# ---------------------------------------------------------------------------
# forward (local shards, inside shard_map)

def _heads_local(cfg: T5Config) -> int:
    return cfg.num_heads // lax.axis_size(TP_AXIS)


def _sp_size() -> int:
    try:
        return lax.axis_size(SP_AXIS)
    except NameError:
        return 1


def _bhsd(x, heads_local: int, head_dim: int):
    b, s, _ = x.shape
    return x.reshape(b, s, heads_local, head_dim).transpose(0, 2, 1, 3)


def _attn_core(q, k, v, cfg: T5Config, causal: bool, dropout_key,
               bias=None):
    """Shared attention core: ring over sp shards, flash otherwise,
    with in-kernel probability dropout (TP-folded seed) when training
    and an optional additive logit bias (relative position bias) fed to
    the kernel's bias path.
    """
    rate = cfg.attention_dropout if dropout_key is not None else 0.0
    if _sp_size() > 1:
        from apex_tpu.transformer.sequence_parallel import ring_attention

        # bias here is the ring STRIP (heads_local, s_loc, sp*s_loc) built
        # from global positions by t5_encode/t5_decode; each ring step
        # slices the arriving chunk's columns
        if rate > 0.0:
            from apex_tpu.transformer.tensor_parallel.random import (
                attention_dropout_seed,
            )

            return ring_attention(
                q, k, v, causal=causal, bias_strip=bias,
                dropout_rate=rate,
                dropout_seed=attention_dropout_seed(dropout_key))
        return ring_attention(q, k, v, causal=causal, bias_strip=bias)
    if rate > 0.0:
        from apex_tpu.transformer.tensor_parallel.random import (
            attention_dropout_seed,
        )

        seed = attention_dropout_seed(dropout_key)
        return flash_attention(q, k, v, causal=causal,
                               block_q=cfg.attn_block_q,
                               block_k=cfg.attn_block_k,
                               dropout_rate=rate, dropout_seed=seed,
                               bias=bias)
    return flash_attention(q, k, v, causal=causal,
                           block_q=cfg.attn_block_q,
                           block_k=cfg.attn_block_k, bias=bias)


def _self_attention(p, x, cfg: T5Config, causal: bool, dropout_key=None,
                    rel_bias=None):
    b = x.shape[0]
    hl = _heads_local(cfg)
    qkv = column_parallel_linear(x, p["qkv_kernel"], p["qkv_bias"],
                                 gather_output=False,
                                 sequence_parallel=cfg.megatron_sp)
    s = qkv.shape[1]  # full sequence after the SP gather
    # per-head interleaved packing (head, {q,k,v}, head_dim) — TP-degree
    # invariant under contiguous column splits (see standalone_gpt)
    qkv = qkv.reshape(b, s, hl, 3, cfg.head_dim)
    q, k, v = (qkv[:, :, :, i].transpose(0, 2, 1, 3) for i in range(3))
    ctx = _attn_core(q, k, v, cfg, causal, dropout_key, bias=rel_bias)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, hl * cfg.head_dim)
    return row_parallel_linear(ctx, p["out_kernel"], p["out_bias"],
                               input_is_parallel=True,
                               sequence_parallel=cfg.megatron_sp)


def _cross_attention(p, x, mem, cfg: T5Config, dropout_key=None):
    """Decoder cross-attention: rectangular (s_dec × s_enc) flash core,
    Q column-parallel from the decoder stream, fused KV column-parallel
    from the encoder memory, row-parallel output (ref
    ``ParallelAttention(attention_type=cross_attn)``)."""
    b = x.shape[0]
    hl = _heads_local(cfg)
    q = column_parallel_linear(x, p["q_kernel"], p["q_bias"],
                               gather_output=False,
                               sequence_parallel=cfg.megatron_sp)
    kv = column_parallel_linear(mem, p["kv_kernel"], p["kv_bias"],
                                gather_output=False,
                                sequence_parallel=cfg.megatron_sp)
    s = q.shape[1]  # full decoder sequence after the SP gather
    kv = kv.reshape(b, kv.shape[1], hl, 2, cfg.head_dim)
    k, v = (kv[:, :, :, i].transpose(0, 2, 1, 3) for i in range(2))
    # cross-attention rides the rectangular (s_dec x s_enc) ring under sp
    ctx = _attn_core(_bhsd(q, hl, cfg.head_dim), k, v, cfg, False,
                     dropout_key)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, hl * cfg.head_dim)
    return row_parallel_linear(ctx, p["xout_kernel"], p["xout_bias"],
                               input_is_parallel=True,
                               sequence_parallel=cfg.megatron_sp)


def _mlp(p, x, cfg: T5Config):
    y = column_parallel_linear(x, p["fc1_kernel"], p["fc1_bias"],
                               gather_output=False,
                               sequence_parallel=cfg.megatron_sp)
    y = jax.nn.gelu(y, approximate=True)
    return row_parallel_linear(y, p["fc2_kernel"], p["fc2_bias"],
                               input_is_parallel=True,
                               sequence_parallel=cfg.megatron_sp)


def _maybe_hidden_dropout(x, cfg: T5Config, key, salt: int):
    if key is None or cfg.hidden_dropout <= 0.0:
        return x
    from apex_tpu.transformer.testing.standalone_gpt import (
        _hidden_dropout,
        _hidden_key,
    )

    # _hidden_key is the ONE shard-decorrelation site: it folds the SP
    # rank under ring-sp and the TP rank under megatron_sp — each rank
    # holds a DIFFERENT seq shard, so an unfolded key would repeat one
    # mask across the sequence with period s/sp resp. s/tp
    return _hidden_dropout(x, cfg.hidden_dropout,
                           _hidden_key(jax.random.fold_in(key, salt), cfg))


def enc_layer_fn(p, x, cfg: T5Config, dropout_key=None, rel_bias=None):
    k = dropout_key
    a = _self_attention(p, layer_norm(x, p["ln1_w"], p["ln1_b"]), cfg,
                        causal=False,
                        dropout_key=None if k is None
                        else jax.random.fold_in(k, 0),
                        rel_bias=rel_bias)
    x = x + _maybe_hidden_dropout(a, cfg, k, 1)
    m = _mlp(p, layer_norm(x, p["ln2_w"], p["ln2_b"]), cfg)
    return x + _maybe_hidden_dropout(m, cfg, k, 2)


def dec_layer_fn(p, x, mem, cfg: T5Config, dropout_key=None, rel_bias=None):
    k = dropout_key
    a = _self_attention(p, layer_norm(x, p["ln1_w"], p["ln1_b"]), cfg,
                        causal=True,
                        dropout_key=None if k is None
                        else jax.random.fold_in(k, 0),
                        rel_bias=rel_bias)
    x = x + _maybe_hidden_dropout(a, cfg, k, 1)
    # cross-attention carries NO position bias (the T5 scheme)
    c = _cross_attention(p, layer_norm(x, p["ln2_w"], p["ln2_b"]), mem, cfg,
                         dropout_key=None if k is None
                         else jax.random.fold_in(k, 3))
    x = x + _maybe_hidden_dropout(c, cfg, k, 4)
    m = _mlp(p, layer_norm(x, p["ln3_w"], p["ln3_b"]), cfg)
    return x + _maybe_hidden_dropout(m, cfg, k, 2)


def _scan_layers(layer_fn, layer_params, x, cfg, *extra, dropout_key=None):
    """scan the [L]-stacked layer params (remat per layer, the
    standalone_gpt recipe). ``cfg`` is closed over, NOT passed through the
    checkpoint boundary — jax.checkpoint would flatten it as a traced
    argument. With ``dropout_key``, each layer gets a fold_in-derived key
    (the standalone_gpt per-layer stream)."""
    has_drop = dropout_key is not None

    def apply(lp, h, key, *ex):
        return layer_fn(lp, h, *ex, cfg,
                        dropout_key=key if has_drop else None)

    fn = jax.checkpoint(apply) if cfg.remat else apply

    n_layers = jax.tree.leaves(layer_params)[0].shape[0]
    if has_drop:
        keys = jax.vmap(lambda i: jax.random.fold_in(dropout_key, i))(
            jnp.arange(n_layers))
    else:
        keys = jnp.zeros((n_layers, 2), jnp.uint32)

    def body(h, lp_key):
        lp, key = lp_key
        return fn(lp, h, key, *extra), None

    out, _ = lax.scan(body, x, (layer_params, keys))
    return out


def _embed(embed, tokens, pos_table, megatron_sp: bool = False):
    """Token (+ optional absolute position) embedding. ``pos_table`` is
    None under ``relative_position_bias`` — T5 proper has no absolute
    positions; the layers add bucketed logit biases instead."""
    s_loc = tokens.shape[1]
    if megatron_sp:
        tp_size = lax.axis_size(TP_AXIS)
        if s_loc % tp_size:
            # validate() only sees max_seq; check the actual sequence here
            # instead of letting psum_scatter fail deep in the trace (the
            # standalone_gpt.embed_tokens guard)
            raise ValueError(
                f"megatron_sp needs the sequence length ({s_loc}) "
                f"divisible by tp ({tp_size})")
    h = vocab_parallel_embedding(tokens, embed["tok"],
                                 sequence_parallel=megatron_sp)
    if pos_table is None:
        return h
    sp = _sp_size()
    start = lax.axis_index(SP_AXIS) * s_loc if sp > 1 else 0
    pos = lax.dynamic_slice_in_dim(pos_table, start, s_loc, 0) \
        if sp > 1 else pos_table[:s_loc]
    if megatron_sp:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            scatter_to_sequence_parallel_region,
        )

        pos = scatter_to_sequence_parallel_region(pos, seq_axis=0)
    return h + pos[None, :, :].astype(h.dtype)


def _match_vma(x, ref):
    """pcast ``x`` to also vary over ``ref``'s manual axes — a bias passed
    into the layer scan must start with the varying-axis set its cotangent
    will come back with (dp via the attention inputs), or the transposed
    scan's carry check trips. Thin alias over the ring module's helper so
    the vma-alignment logic lives in one place."""
    from apex_tpu.transformer.sequence_parallel import _vary_like_inputs

    return _vary_like_inputs(x, ref)


def _rel_or_strip(table_local, s_tok: int, *, bidirectional: bool,
                  cfg: T5Config):
    """Build the layer-shared rel bias once per stack: the square
    (hl, s, s) bias at sp == 1 (``s_tok`` is the full sequence there —
    Megatron-SP scatters inside the TP layers), or the ring STRIP
    (hl, s_loc, sp*s_loc) from this shard's global positions at sp > 1
    (``s_tok`` is the local shard)."""
    sp = _sp_size()
    if sp == 1:
        return t5_relative_bias(table_local, s_tok, s_tok,
                                bidirectional=bidirectional, cfg=cfg)
    my = lax.axis_index(SP_AXIS)
    qpos = my * s_tok + jnp.arange(s_tok, dtype=jnp.int32)
    kpos = jnp.arange(sp * s_tok, dtype=jnp.int32)
    return t5_relative_bias(table_local, bidirectional=bidirectional,
                            cfg=cfg, qpos=qpos, kpos=kpos)


def t5_encode(params, enc_tokens, cfg: T5Config, dropout_key=None):
    rel_on = cfg.relative_position_bias
    x = _embed(params["embed"], enc_tokens,
               None if rel_on else params["embed"]["pos_enc"],
               cfg.megatron_sp)
    x = _maybe_hidden_dropout(
        x, cfg, None if dropout_key is None
        else jax.random.fold_in(dropout_key, 100), 0)
    rel = (_match_vma(_rel_or_strip(params["embed"]["rel_enc"],
                                    enc_tokens.shape[1],
                                    bidirectional=True, cfg=cfg), x)
           if rel_on else None)
    return _scan_layers(
        lambda lp, h, rel_bias, c, dropout_key=None: enc_layer_fn(
            lp, h, c, dropout_key=dropout_key, rel_bias=rel_bias),
        params["enc_layers"], x, cfg, rel, dropout_key=dropout_key)


def t5_decode(params, dec_tokens, mem, cfg: T5Config, dropout_key=None):
    rel_on = cfg.relative_position_bias
    if cfg.encoder_final_ln:
        # normalize the memory at the point of consumption — exactly the
        # paper's encoder-exit LayerNorm (see T5Config.encoder_final_ln)
        mem = layer_norm(mem, params["embed"]["enc_ln_w"],
                         params["embed"]["enc_ln_b"])
    x = _embed(params["embed"], dec_tokens,
               None if rel_on else params["embed"]["pos_dec"],
               cfg.megatron_sp)
    x = _maybe_hidden_dropout(
        x, cfg, None if dropout_key is None
        else jax.random.fold_in(dropout_key, 101), 0)
    rel = (_match_vma(_rel_or_strip(params["embed"]["rel_dec"],
                                    dec_tokens.shape[1],
                                    bidirectional=False, cfg=cfg), x)
           if rel_on else None)
    return _scan_layers(
        lambda lp, h, m, rel_bias, c, dropout_key=None: dec_layer_fn(
            lp, h, m, c, dropout_key=dropout_key, rel_bias=rel_bias),
        params["dec_layers"], x, cfg, mem, rel, dropout_key=dropout_key)


def t5_loss(params, enc_tokens, dec_tokens, targets, cfg: T5Config,
            dropout_key=None):
    """Sequential (non-pipelined) enc-dec loss; the ground truth the
    pipeline schedule is tested against, and the TP-only training path.
    ``dropout_key`` activates cfg's dropout rates (training mode), with
    distinct per-side/per-layer streams."""
    ke = kd = None
    if dropout_key is not None:
        ke = jax.random.fold_in(dropout_key, 0)
        kd = jax.random.fold_in(dropout_key, 1)
    mem = t5_encode(params, enc_tokens, cfg, dropout_key=ke)
    x = t5_decode(params, dec_tokens, mem, cfg, dropout_key=kd)
    head = params["head"]
    if cfg.fused_loss:
        from apex_tpu.transformer.testing.standalone_gpt import (
            fused_head_loss,
        )

        return fused_head_loss(params["embed"]["tok"], head["ln_w"],
                               head["ln_b"], x, targets,
                               gather_sequence=cfg.megatron_sp)
    from apex_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region,
        gather_from_sequence_parallel_region,
    )

    x = layer_norm(x, head["ln_w"], head["ln_b"])
    if cfg.megatron_sp:
        x = gather_from_sequence_parallel_region(x)
    x = copy_to_tensor_model_parallel_region(x)
    logits = jnp.einsum("bsh,vh->bsv", x, params["embed"]["tok"])
    return jnp.mean(vocab_parallel_cross_entropy(logits, targets))


# ---------------------------------------------------------------------------
# pipeline wiring (EncDecPipelineSpec contract)

def t5_pipeline_params(rng, cfg: T5Config, pp: int) -> Pytree:
    """Regroup :func:`init_t5_params` into the enc-dec driver layout
    ``{"embed", "enc_stages" [pp, Le/pp, ...], "dec_stages"
    [pp, Ld/pp, ...], "head"}`` — every stage holds one encoder AND one
    decoder chunk (two-phase ring, ``fwd_bwd_enc_dec.py``)."""
    if cfg.enc_layers % pp or cfg.dec_layers % pp:
        raise ValueError("enc_layers and dec_layers must be divisible by pp")
    p = init_t5_params(rng, cfg)
    regroup = lambda a, n: a.reshape((pp, n // pp) + a.shape[1:])  # noqa: E731
    head = dict(p["head"])
    # the driver's loss head sees only the "head" group, so the pipeline
    # fixture unties the LM projection (initialized from the shared table —
    # the grads then flow separately, as with GPT's untied pipeline head)
    head["lm_rows"] = p["embed"]["tok"]
    enc_stages = jax.tree.map(
        lambda a: regroup(a, cfg.enc_layers), p["enc_layers"])
    dec_stages = jax.tree.map(
        lambda a: regroup(a, cfg.dec_layers), p["dec_layers"])
    embed = p["embed"]
    # stage functions can't reach the embed group, so stage-consumed
    # extras (rel tables, the encoder-final LN) become per-stage copies
    # (initialized equal) — the same untying the pipeline fixture applies
    # to the LM head: exact forward parity with the sequential model,
    # per-stage gradients. The embed copies are dropped (they would sit
    # in optimizer state and checkpoints as frozen dead weights).
    tile = lambda a: jnp.broadcast_to(  # noqa: E731
        a[None], (pp,) + a.shape).copy()
    drop = []
    if cfg.relative_position_bias:
        enc_stages = {"layers": enc_stages, "rel": tile(embed["rel_enc"])}
        dec_stages = {"layers": dec_stages, "rel": tile(embed["rel_dec"])}
        drop += ["rel_enc", "rel_dec"]
    if cfg.encoder_final_ln:
        if not cfg.relative_position_bias:  # not already {"layers", ...}
            dec_stages = {"layers": dec_stages}
        dec_stages["enc_ln_w"] = tile(embed["enc_ln_w"])
        dec_stages["enc_ln_b"] = tile(embed["enc_ln_b"])
        drop += ["enc_ln_w", "enc_ln_b"]
    if drop:
        embed = {k: v for k, v in embed.items() if k not in drop}
    return {
        "embed": embed,
        "enc_stages": enc_stages,
        "dec_stages": dec_stages,
        "head": head,
    }


def t5_pipeline_specs_tree(cfg: T5Config) -> Pytree:
    specs = t5_param_specs(cfg, extra_layer_lead=(PP_AXIS,))
    head = dict(specs["head"])
    head["lm_rows"] = P(TP_AXIS, None)
    enc_stages, dec_stages = specs["enc_layers"], specs["dec_layers"]
    embed = specs["embed"]
    drop = []
    if cfg.relative_position_bias:
        rel_spec = P(PP_AXIS, None, TP_AXIS)
        enc_stages = {"layers": enc_stages, "rel": rel_spec}
        dec_stages = {"layers": dec_stages, "rel": rel_spec}
        drop += ["rel_enc", "rel_dec"]
    if cfg.encoder_final_ln:
        if not cfg.relative_position_bias:  # not already {"layers", ...}
            dec_stages = {"layers": dec_stages}
        dec_stages["enc_ln_w"] = P(PP_AXIS, None)
        dec_stages["enc_ln_b"] = P(PP_AXIS, None)
        drop += ["enc_ln_w", "enc_ln_b"]
    if drop:
        embed = {k: v for k, v in embed.items() if k not in drop}
    return {
        "embed": embed,
        "enc_stages": enc_stages,
        "dec_stages": dec_stages,
        "head": head,
    }


def t5_enc_dec_spec(cfg: T5Config, dropout: bool = False) \
        -> EncDecPipelineSpec:
    """With ``dropout`` the stage functions take the schedule's
    per-microbatch key (``takes_dropout_key``): the side salt (enc 0 /
    dec 1, mirroring ``t5_loss``) and the PP rank are folded here —
    encoder and decoder chunks share a stage's pp rank, and stage-local
    layer indices restart at 0 per stage."""
    rel_on = cfg.relative_position_bias

    def _stage_key(key, side_salt: int):
        key = jax.random.fold_in(key, side_salt)
        return jax.random.fold_in(key, lax.axis_index(PP_AXIS))

    def _enc_embed(embed, enc_tokens, key=None):
        x = _embed(embed, enc_tokens,
                   None if rel_on else embed["pos_enc"], cfg.megatron_sp)
        # same embedding-dropout stream as the sequential path
        # (t5_encode, salt 100)
        return _maybe_hidden_dropout(
            x, cfg, None if key is None
            else jax.random.fold_in(key, 100), 0)

    def _enc_stage(stage_params, h, key=None):
        dk = None if key is None else _stage_key(key, 0)
        if rel_on:
            s = h.shape[1] * (lax.axis_size(TP_AXIS) if cfg.megatron_sp
                              else 1)
            rel = _match_vma(_rel_or_strip(stage_params["rel"], s,
                                           bidirectional=True, cfg=cfg), h)
            return _scan_layers(
                lambda lp, x, rb, c, dropout_key=None: enc_layer_fn(
                    lp, x, c, dropout_key=dropout_key, rel_bias=rb),
                stage_params["layers"], h, cfg, rel, dropout_key=dk)
        return _scan_layers(
            lambda lp, x, c, dropout_key=None: enc_layer_fn(
                lp, x, c, dropout_key=dropout_key),
            stage_params, h, cfg, dropout_key=dk)

    def _dec_embed(embed, dec_tokens, key=None):
        x = _embed(embed, dec_tokens,
                   None if rel_on else embed["pos_dec"], cfg.megatron_sp)
        # t5_decode's embedding-dropout stream (salt 101)
        return _maybe_hidden_dropout(
            x, cfg, None if key is None
            else jax.random.fold_in(key, 101), 0)

    def _dec_stage(stage_params, h, mem, key=None):
        dk = None if key is None else _stage_key(key, 1)
        if cfg.encoder_final_ln:
            # every stage normalizes the same broadcast memory with its
            # copy of the encoder-final LN — identical to normalizing
            # once at encoder exit (see T5Config.encoder_final_ln)
            mem = layer_norm(mem, stage_params["enc_ln_w"],
                             stage_params["enc_ln_b"])
        if rel_on:
            s = h.shape[1] * (lax.axis_size(TP_AXIS) if cfg.megatron_sp
                              else 1)
            rel = _match_vma(_rel_or_strip(stage_params["rel"], s,
                                           bidirectional=False, cfg=cfg), h)
            return _scan_layers(
                lambda lp, x, m, rb, c, dropout_key=None: dec_layer_fn(
                    lp, x, m, c, dropout_key=dropout_key, rel_bias=rb),
                stage_params["layers"], h, cfg, mem, rel, dropout_key=dk)
        layers = (stage_params["layers"] if cfg.encoder_final_ln
                  else stage_params)
        return _scan_layers(
            lambda lp, x, m, c, dropout_key=None: dec_layer_fn(
                lp, x, m, c, dropout_key=dropout_key),
            layers, h, cfg, mem, dropout_key=dk)

    if dropout:
        enc_embed_fn, dec_embed_fn = _enc_embed, _dec_embed
        enc_stage_fn, dec_stage_fn = _enc_stage, _dec_stage
    else:
        def enc_embed_fn(embed, enc_tokens):
            return _enc_embed(embed, enc_tokens)

        def dec_embed_fn(embed, dec_tokens):
            return _dec_embed(embed, dec_tokens)

        def enc_stage_fn(stage_params, h):
            return _enc_stage(stage_params, h)

        def dec_stage_fn(stage_params, h, mem):
            return _dec_stage(stage_params, h, mem)

    def loss_fn(head, h, targets):
        # per-microbatch mean vocab-parallel CE over the untied head rows
        # (see t5_pipeline_params for why the pipeline fixture unties)
        from apex_tpu.transformer.tensor_parallel.mappings import (
            copy_to_tensor_model_parallel_region,
            gather_from_sequence_parallel_region,
        )

        x = layer_norm(h, head["ln_w"], head["ln_b"])
        if cfg.megatron_sp:
            x = gather_from_sequence_parallel_region(x)
        x = copy_to_tensor_model_parallel_region(x)
        logits = jnp.einsum("bsh,vh->bsv", x, head["lm_rows"])
        return jnp.mean(vocab_parallel_cross_entropy(logits, targets))

    return EncDecPipelineSpec(enc_embed_fn, enc_stage_fn, dec_embed_fn,
                              dec_stage_fn, loss_fn,
                              takes_dropout_key=dropout)

"""Standalone Megatron-style GPT — the TP+PP-parallel test/flagship model.

Reference: ``apex/transformer/testing/standalone_gpt.py`` — ``GPTModel``
(:1440) over ``ParallelTransformer(Layer)`` (:713,577), ``ParallelAttention``
(:285), ``ParallelMLP`` (:236), vocab-parallel embedding + tied LM head +
``vocab_parallel_cross_entropy`` loss.

TPU re-design: pure functions over an explicit parameter pytree. Parameters
are created at their **global** shapes and laid onto the mesh by
:func:`gpt_param_specs` (GSPMD-style PartitionSpecs); inside ``shard_map``
each function sees its local shard and uses the explicit TP collectives
(``tensor_parallel.layers``) — column-parallel QKV/FC1, row-parallel
out-proj/FC2, vocab-parallel embedding and loss, flash-attention core.
The layer stack is a ``lax.scan`` over stacked layer params (one compiled
layer body regardless of depth), rematerialized per layer — the analogue of
the reference's activation checkpointing (``tensor_parallel/random.py:224``).

Layout contract (local shapes inside shard_map, ``tp`` = TP world size):

==============================  ==========================
``embed.tok``                   (vocab/tp, hidden)
``embed.pos``                   (max_seq, hidden)
``layers.*`` (leading [L])      see ``_init_layer``
``layers.qkv_kernel``           (hidden, 3·hidden/tp)
``layers.out_kernel``           (hidden/tp, hidden)
``layers.fc1_kernel``           (hidden, ffn/tp)
``layers.fc2_kernel``           (ffn/tp, hidden)
``head.ln_w/ln_b``              (hidden,)
``head.lm`` (untied head)       (hidden, vocab/tp)
==============================  ==========================
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.parallel.mesh import SP_AXIS, TP_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules import PipelineSpec
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)

Pytree = Any


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Ref ``testing/arguments.py`` essentials, as one dataclass (SURVEY §5
    config unification)."""

    vocab_size: int = 50304
    max_seq: int = 1024
    hidden: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_mult: int = 4
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = True
    remat: bool = True
    # "full": recompute the whole layer in backward (the reference's
    # activation-checkpointing default, tensor_parallel/random.py:224).
    # "dots": selective policy — save matmul outputs, recompute only
    # elementwise (LN/gelu/adds); ~25% fewer recompute FLOPs for ~5-6 GB
    # of residuals at the 124M bench shape.
    # "dots_attn": dots PLUS the flash-attention custom_vjp residuals
    # (o + lse, named inside the kernels' fwd rules) — backward skips the
    # O(s^2) attention forward replay entirely (dense, ring and varlen)
    # for one extra (b, s, h_local) + lse activation per layer.
    remat_policy: str = "full"
    # Fuse the LM head matmul into the CE loss (ops/lm_head_loss.py) —
    # never materializes the (tokens, vocab) logits.
    fused_loss: bool = True
    # Ref standalone_gpt.py attention-/hidden-dropout sites (:285-735).
    # Active only when the caller passes ``dropout_key`` (training); the
    # attention dropout runs INSIDE the flash kernel with a TP-rank-folded
    # seed (tensor_parallel/random.py stream semantics), hidden/embedding
    # dropout on the replicated activations with the unfolded key (same
    # across the TP group).
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # lax.scan unroll factor for the layer stack: 1 = one compiled layer
    # body (fast compiles); num_layers = straight-line HLO (cross-layer
    # fusion, and XLA cost analysis then counts every layer — see
    # benchmarks/check_mfu_accounting.py).
    scan_unroll: int = 1
    # Megatron-style sequence parallelism over the tp axis (Korthikanti;
    # NOT in the reference): LN/dropout/residual regions run on (b, s/tp, h)
    # shards, TP blocks all_gather on entry and reduce-scatter on exit,
    # the embedding exit is a reduce-scatter and the LM head entry a
    # gather. Composes with the ring-attention sp axis (the tp split is
    # within each sp shard). Cuts the non-TP activation memory by tp× and
    # shrinks pipeline p2p tensors the same way.
    megatron_sp: bool = False
    # Decompose the layers' TP-boundary collectives into ppermute rings
    # interleaved with partial GEMMs (apex_tpu.comm.overlap): under
    # megatron_sp the QKV/FC1 entry all-gathers become all_gather_matmul
    # and the out-proj/FC2 exit reduce-scatters matmul_reduce_scatter;
    # without it the row-parallel exit psums become matmul_all_reduce.
    # Custom VJPs keep backward overlapped too. XLA cannot hide a
    # DEPENDENT collective→matmul chain on its own — this flag is the
    # reference's async-allreduce capability (tensor_parallel/layers.py:
    # 217-269) rebuilt for the TPU ring. Numerics: all-gather side exact;
    # reduce side equal up to fp addition reorder (ring association).
    # Needs the (sp-local) sequence divisible by tp. The MoE FFN and the
    # LM head keep their monolithic collectives.
    overlap_comm: bool = False
    # num_experts > 0 replaces every layer's MLP with a mixture-of-experts
    # FFN (transformer.moe): top-k capacity routing, experts sharded over
    # the dp(=ep) mesh axis with all_to_all dispatch, expert FFN weights
    # TP-split. The router aux loss is averaged over layers and added to
    # gpt_loss. Composes with megatron_sp (the MoE region gathers the
    # sequence and slices the shard back out) and with the pipeline
    # schedules (PipelineSpec.stage_aux carries the router aux per stage).
    # COST of the default megatron_sp composition: every TP rank gathers
    # the full sequence and runs the whole router+dispatch block
    # redundantly (tp-fold duplicate compute), forfeiting the SP
    # activation saving inside the MoE region. Set ``moe_seq_dispatch``
    # to use the sequence-sharded dispatch instead; see PERF.md
    # "MoE under Megatron-SP".
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Pallas kernel block sizes (benchmarks/tune_blocks.py sweeps these on
    # hardware; 0 = the kernel's own default). Attention blocks trade VMEM
    # residency vs grid parallelism; LM-head blocks trade the vocab-tile
    # streaming pattern.
    attn_block_q: int = 512
    attn_block_k: int = 512
    lm_block_n: int = 0
    lm_block_v: int = 0
    # Under megatron_sp, dispatch from the LOCAL sequence shard instead of
    # gathering the full sequence per TP rank: tp-fold less router/dispatch
    # compute, SP activation saving kept. Capacity becomes per-shard, so
    # tight-capacity drop patterns differ from the gathered path (exact
    # match when capacity is ample — see moe_mlp docstring).
    moe_seq_dispatch: bool = False
    # LayerNorm implementation override: None = layer_norm's own auto
    # (Pallas kernel on TPU when shapes allow), True/False forces it.
    # benchmarks/tune_blocks.py A/Bs the full step both ways — a Pallas
    # call is an XLA fusion barrier, so at small hidden the fused XLA LN
    # can win despite the kernel's fewer HBM passes.
    ln_pallas: Optional[bool] = None

    @property
    def ffn_hidden(self) -> int:
        return self.ffn_mult * self.hidden

    @property
    def head_dim(self) -> int:
        return self.hidden // self.num_heads

    def validate(self, tp: int = 1, sp: int = 1) -> None:
        if self.hidden % self.num_heads:
            raise ValueError("hidden must be divisible by num_heads")
        for name, dim in (("vocab_size", self.vocab_size),
                          ("num_heads", self.num_heads),
                          ("ffn_hidden", self.ffn_hidden)):
            if dim % tp:
                raise ValueError(f"{name} ({dim}) not divisible by tp ({tp})")
        if self.remat_policy not in ("full", "dots", "dots_attn"):
            raise ValueError(
                f"remat_policy must be 'full', 'dots' or 'dots_attn', "
                f"got {self.remat_policy!r}")
        if self.megatron_sp and self.max_seq % tp:
            raise ValueError(
                f"megatron_sp needs max_seq ({self.max_seq}) divisible by "
                f"tp ({tp})")
        if self.overlap_comm and self.max_seq % (tp * sp):
            # the rings shard the SP-LOCAL sequence by tp, so the full
            # sequence must split across both axes (validate(tp) alone
            # cannot see ring-sp; callers composing with sp pass it)
            raise ValueError(
                f"overlap_comm rings shard the sp-local sequence by tp: "
                f"max_seq ({self.max_seq}) must be divisible by "
                f"tp*sp ({tp}*{sp})")
        if self.num_experts:
            self.moe_config  # MoEConfig.__post_init__ owns the MoE checks

    @property
    def moe_config(self):
        from apex_tpu.transformer.moe import MoEConfig

        return MoEConfig(num_experts=self.num_experts, hidden=self.hidden,
                         ffn_hidden=self.ffn_hidden, top_k=self.moe_top_k,
                         capacity_factor=self.moe_capacity_factor,
                         dtype=self.dtype)


# ---------------------------------------------------------------------------
# init (global shapes)

def _init_layer(rng, cfg: GPTConfig) -> Pytree:
    h, f = cfg.hidden, cfg.ffn_hidden
    ks = jax.random.split(rng, 5)
    # Megatron init: normal(0.02) for input projections, output projections
    # scaled by 1/sqrt(2L) (ref standalone_gpt scaled_init_method)
    out_std = 0.02 / math.sqrt(2.0 * cfg.num_layers)
    dt = cfg.dtype
    layer = {
        "ln1_w": jnp.ones((h,), dt), "ln1_b": jnp.zeros((h,), dt),
        "qkv_kernel": (jax.random.normal(ks[0], (h, 3 * h)) * 0.02).astype(dt),
        "qkv_bias": jnp.zeros((3 * h,), dt),
        "out_kernel": (jax.random.normal(ks[1], (h, h)) * out_std).astype(dt),
        "out_bias": jnp.zeros((h,), dt),
        "ln2_w": jnp.ones((h,), dt), "ln2_b": jnp.zeros((h,), dt),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        layer.update({
            "router": jax.random.normal(ks[4], (h, e), jnp.float32) * 0.02,
            "fc1_kernel": (jax.random.normal(ks[2], (e, h, f))
                           * 0.02).astype(dt),
            "fc1_bias": jnp.zeros((e, f), dt),
            "fc2_kernel": (jax.random.normal(ks[3], (e, f, h))
                           * out_std).astype(dt),
            "fc2_bias": jnp.zeros((e, h), dt),
        })
    else:
        layer.update({
            "fc1_kernel": (jax.random.normal(ks[2], (h, f)) * 0.02).astype(dt),
            "fc1_bias": jnp.zeros((f,), dt),
            "fc2_kernel": (jax.random.normal(ks[3], (f, h)) * out_std).astype(dt),
            "fc2_bias": jnp.zeros((h,), dt),
        })
    return layer


def init_gpt_params(rng, cfg: GPTConfig) -> Pytree:
    """Global-shape parameter pytree: ``{"embed", "layers" ([L, ...]), "head"}``."""
    cfg.validate()
    ke, kl, kh = jax.random.split(rng, 3)
    layer_rngs = jax.random.split(kl, cfg.num_layers)
    layers = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[_init_layer(k, cfg) for k in layer_rngs])
    dt = cfg.dtype
    params = {
        "embed": {
            "tok": (jax.random.normal(ke, (cfg.vocab_size, cfg.hidden))
                    * 0.02).astype(dt),
            "pos": (jax.random.normal(jax.random.fold_in(ke, 1),
                                      (cfg.max_seq, cfg.hidden))
                    * 0.02).astype(dt),
        },
        "layers": layers,
        "head": {
            "ln_w": jnp.ones((cfg.hidden,), dt),
            "ln_b": jnp.zeros((cfg.hidden,), dt),
        },
    }
    if not cfg.tie_embeddings:
        params["head"]["lm"] = (
            jax.random.normal(kh, (cfg.hidden, cfg.vocab_size)) * 0.02
        ).astype(dt)
    return params


def gpt_param_specs(cfg: GPTConfig, extra_layer_lead=()) -> Pytree:
    """PartitionSpecs matching :func:`init_gpt_params`: TP sharding on the
    Megatron dims, everything else replicated. ``extra_layer_lead`` prepends
    axes for stacked layer params (e.g. ``("pp",)`` for pipeline stages)."""
    lead = tuple(extra_layer_lead) + (None,)  # [(pp,)] + [L]
    layer = {
        "ln1_w": P(*lead), "ln1_b": P(*lead),
        "qkv_kernel": P(*lead, None, TP_AXIS),
        "qkv_bias": P(*lead, TP_AXIS),
        "out_kernel": P(*lead, TP_AXIS, None),
        "out_bias": P(*lead),
        "ln2_w": P(*lead), "ln2_b": P(*lead),
    }
    if cfg.num_experts:
        from apex_tpu.parallel.mesh import DP_AXIS
        from apex_tpu.transformer.moe import moe_param_specs

        # experts sharded over dp(=ep): each rank OWNS E/dp experts — their
        # grads are per-rank, not dp-reduced (DeepSpeed-MoE layout). The
        # layout is moe_param_specs' — one source of truth — with the
        # stacked-layer lead axes prepended.
        layer.update({k: P(*lead, *s)
                      for k, s in moe_param_specs(DP_AXIS).items()})
    else:
        layer.update({
            "fc1_kernel": P(*lead, None, TP_AXIS),
            "fc1_bias": P(*lead, TP_AXIS),
            "fc2_kernel": P(*lead, TP_AXIS, None),
            "fc2_bias": P(*lead),
        })
    specs = {
        "embed": {"tok": P(TP_AXIS, None), "pos": P()},
        "layers": layer,
        "head": {"ln_w": P(), "ln_b": P()},
    }
    if not cfg.tie_embeddings:
        specs["head"]["lm"] = P(None, TP_AXIS)
    return specs


# ---------------------------------------------------------------------------
# forward (local shards, inside shard_map)

def _hidden_dropout(x, rate: float, key):
    """Dropout on replicated activations (ref hidden-dropout sites): applied
    with the UNFOLDED key so every TP rank drops the same positions — the
    activations are TP-replicated, diverging them would break the region."""
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x * (1.0 / (1.0 - rate)),
                     jnp.zeros_like(x)).astype(x.dtype)


def _attention(p, x, cfg, heads_local: int, causal: bool = True, mask=None,
               dropout_key=None):
    """Ref ParallelAttention (:285): column-parallel fused QKV, flash core
    (with in-kernel probability dropout when training), row-parallel
    out-proj. Under ``cfg.megatron_sp`` ``x`` is the (b, s/tp, h) sequence
    shard: the QKV entry all-gathers seq, the out-proj exit reduce-scatters
    it (attention itself always sees the full sp-local sequence)."""
    b, s, h = x.shape
    if cfg.megatron_sp:
        s = s * lax.axis_size(TP_AXIS)
    qkv = column_parallel_linear(x, p["qkv_kernel"], p["qkv_bias"],
                                 gather_output=False,
                                 sequence_parallel=cfg.megatron_sp,
                                 overlap_comm=cfg.overlap_comm)
    # per-head interleaved packing — column c of the global qkv kernel is
    # (head, {q,k,v}, head_dim): a contiguous TP column split then assigns
    # whole heads with their q, k, v together, so the computed function is
    # EXACTLY invariant to the TP degree. The flat (3, heads, head_dim)
    # order would make a tp split hand rank 0 "q of heads 0..H/2 but k of
    # heads H/2..H", silently mixing regions across degrees.
    qkv = qkv.reshape(b, s, heads_local, 3, cfg.head_dim)
    q, k, v = (qkv[:, :, :, i].transpose(0, 2, 1, 3) for i in range(3))
    try:
        sp = lax.axis_size(SP_AXIS)
    except NameError:
        sp = 1
    rate = cfg.attention_dropout if dropout_key is not None else 0.0
    if sp > 1:
        # sequence sharded over sp: exact attention via the K/V ring
        if mask is not None:
            raise NotImplementedError(
                "explicit attention masks are not supported with sp > 1; "
                "use causal or full attention")
        from apex_tpu.transformer.sequence_parallel import ring_attention

        if rate > 0.0:
            from apex_tpu.transformer.tensor_parallel.random import (
                attention_dropout_seed,
            )

            ctx = ring_attention(
                q, k, v, causal=causal, dropout_rate=rate,
                dropout_seed=attention_dropout_seed(dropout_key))
        else:
            ctx = ring_attention(q, k, v, causal=causal)
    elif rate > 0.0:
        from apex_tpu.transformer.tensor_parallel.random import (
            attention_dropout_seed,
        )

        seed = attention_dropout_seed(dropout_key)
        ctx = flash_attention(q, k, v, causal=causal, mask=mask,
                              block_q=cfg.attn_block_q,
                              block_k=cfg.attn_block_k,
                              dropout_rate=rate, dropout_seed=seed)
    else:
        ctx = flash_attention(q, k, v, causal=causal, mask=mask,
                              block_q=cfg.attn_block_q,
                              block_k=cfg.attn_block_k)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, heads_local * cfg.head_dim)
    # (the dots_attn remat names live INSIDE the flash custom_vjp forward
    # — ops/attention.py tags o and lse, the exact backward residuals;
    # tagging here would save the output without lse and the kernel would
    # replay anyway)
    return row_parallel_linear(ctx, p["out_kernel"], p["out_bias"],
                               input_is_parallel=True,
                               sequence_parallel=cfg.megatron_sp,
                               overlap_comm=cfg.overlap_comm)


def _mlp(p, x, cfg):
    """Ref ParallelMLP (:236): column-parallel FC1 + gelu, row-parallel FC2.
    Under ``cfg.megatron_sp`` the FC1 entry gathers seq, the FC2 exit
    reduce-scatters it. With ``cfg.num_experts`` the FFN is the MoE layer
    (experts over dp, router aux loss returned alongside)."""
    if cfg.num_experts:
        from apex_tpu.parallel.mesh import DP_AXIS
        from apex_tpu.transformer.moe import moe_mlp

        if cfg.megatron_sp and cfg.moe_seq_dispatch:
            # sequence-sharded dispatch: route only the local s/tp tokens,
            # all-gather the kept expert SLOTS (the TP-split expert FFN
            # still needs replicated inputs for its psum), combine locally.
            # Removes the tp-fold router/dispatch duplication and keeps the
            # SP activation saving; capacity is per shard (see moe_mlp).
            from apex_tpu.parallel.mesh import TP_AXIS

            out, aux = moe_mlp(p, x, cfg.moe_config, ep_axis=DP_AXIS,
                               seq_shard_axis=TP_AXIS)
        elif cfg.megatron_sp:
            # the TP-split expert FFN psums partial outputs over tp, which
            # requires every tp rank to hold the SAME tokens: gather the
            # sequence for the MoE region, then take the own shard back out
            # (the scatter mapping's transpose restores the full per-token
            # cotangent on every rank — see its docstring).
            from apex_tpu.transformer.tensor_parallel.mappings import (
                gather_from_sequence_parallel_region,
                scatter_to_sequence_parallel_region,
            )

            x = gather_from_sequence_parallel_region(x)
            out, aux = moe_mlp(p, x, cfg.moe_config, ep_axis=DP_AXIS)
            out = scatter_to_sequence_parallel_region(out)
        else:
            out, aux = moe_mlp(p, x, cfg.moe_config, ep_axis=DP_AXIS)
        return out, aux["loss"]
    y = column_parallel_linear(x, p["fc1_kernel"], p["fc1_bias"],
                               gather_output=False,
                               sequence_parallel=cfg.megatron_sp,
                               overlap_comm=cfg.overlap_comm)
    y = jax.nn.gelu(y, approximate=True)
    out = row_parallel_linear(y, p["fc2_kernel"], p["fc2_bias"],
                              input_is_parallel=True,
                              sequence_parallel=cfg.megatron_sp,
                              overlap_comm=cfg.overlap_comm)
    return out, jnp.zeros((), jnp.float32)


def _hidden_key(key, cfg):
    """Hidden-dropout key policy: replicated activations share the unfolded
    key across the TP group; under megatron_sp each tp rank holds DIFFERENT
    tokens, so the rank must be folded in (tensor_parallel/random.py
    model-parallel stream), and under ring-sp the SP rank likewise — or
    shards would reuse one mask. The folds live HERE, at the hidden-dropout
    sites only: the per-layer base keys stay sp-invariant so the attention
    dropout stream (global-position-keyed in the ring) is identical across
    sharding layouts."""
    if key is None:
        return key
    try:
        sp = lax.axis_size(SP_AXIS)
    except NameError:
        sp = 1
    if sp > 1:
        key = jax.random.fold_in(key, lax.axis_index(SP_AXIS))
    if not cfg.megatron_sp:
        return key
    from apex_tpu.transformer.tensor_parallel.random import (
        model_parallel_key,
    )

    return model_parallel_key(key)


def _layer(p, x, cfg, heads_local: int, causal: bool = True, mask=None,
           dropout_key=None):
    """Pre-LN transformer layer (ref ParallelTransformerLayer :577):
    attention (+in-kernel attention dropout) -> hidden dropout -> residual;
    MLP -> hidden dropout -> residual."""
    if dropout_key is not None:
        k_attn, k_h1, k_h2 = jax.random.split(dropout_key, 3)
        k_h1, k_h2 = _hidden_key(k_h1, cfg), _hidden_key(k_h2, cfg)
    else:
        k_attn = k_h1 = k_h2 = None
    a = _attention(p, layer_norm(x, p["ln1_w"], p["ln1_b"],
                             use_pallas=cfg.ln_pallas), cfg,
                   heads_local, causal, mask, dropout_key=k_attn)
    if k_h1 is not None and cfg.hidden_dropout > 0.0:
        a = _hidden_dropout(a, cfg.hidden_dropout, k_h1)
    x = x + a
    m, aux = _mlp(p, layer_norm(x, p["ln2_w"], p["ln2_b"],
                            use_pallas=cfg.ln_pallas), cfg)
    if k_h2 is not None and cfg.hidden_dropout > 0.0:
        m = _hidden_dropout(m, cfg.hidden_dropout, k_h2)
    return x + m, aux


def dots_attn_policy():
    """The 'dots_attn' remat policy object: dots PLUS the flash-attention
    custom_vjp residuals (o AND lse — named inside the kernels' fwd
    rules; naming the public output alone would still replay the forward
    kernel to rebuild lse). With both saved, backward skips the O(s^2)
    attention forward replay — dense, ring and varlen alike — for one
    extra (b, s, h_local) + (b*h, s, 1) activation per layer."""
    return jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse"))


def _layer_stack(layers, x, cfg, causal: bool = True, mask=None,
                 dropout_key=None):
    """scan the stacked layer params over the hidden state."""
    tp = lax.axis_size(TP_AXIS)
    if cfg.num_heads % tp:
        # init_gpt_params can't see tp (global shapes); check here at trace
        # time instead of failing with a QKV reshape error mid-layer
        raise ValueError(
            f"num_heads ({cfg.num_heads}) not divisible by tp ({tp}); "
            f"see GPTConfig.validate(tp=...)")
    if cfg.overlap_comm and not cfg.megatron_sp and x.shape[1] % tp:
        # validate() only fires when the caller passes tp/sp; the flagship
        # path calls it bare (init_gpt_params) — same trace-time guard as
        # num_heads above, where the mesh is finally visible. (Under
        # megatron_sp the embed exit already enforces divisibility and
        # the exit rings scatter the gathered — always-divisible — seq.)
        raise ValueError(
            f"overlap_comm rings shard the sequence: local sequence "
            f"({x.shape[1]}) not divisible by tp ({tp}); see "
            f"GPTConfig.validate(tp=..., sp=...)")
    heads_local = cfg.num_heads // tp

    def one(lp, h, key):
        return _layer(lp, h, cfg, heads_local, causal, mask,
                      dropout_key=key)

    if cfg.remat:
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "dots_attn":
            policy = dots_attn_policy()
        else:
            policy = None
        one = jax.checkpoint(one, policy=policy)

    n_layers = jax.tree_util.tree_leaves(layers)[0].shape[0]
    if dropout_key is not None:
        # per-layer keys; under pipelining each stage holds different layer
        # params but the same local indices — decorrelate by stage rank
        # (folding axis_index makes the keys pp-varying, so the carry must
        # be cast to match or scan rejects the type change)
        try:
            from apex_tpu.parallel.mesh import PP_AXIS

            pp = lax.axis_size(PP_AXIS)
        except NameError:
            pp = 1
        try:
            sp = lax.axis_size(SP_AXIS)
        except NameError:
            sp = 1
        base = dropout_key
        if pp > 1:
            base = jax.random.fold_in(base, lax.axis_index(PP_AXIS))
            if PP_AXIS not in jax.typeof(x).vma:
                x = lax.pcast(x, PP_AXIS, to="varying")
        if sp > 1:
            # the SP-rank fold itself lives in _hidden_key (hidden-dropout
            # sites only — folding it here would leak into the attention
            # seed and break the attention stream's layout invariance);
            # the hidden masks still make the carry sp-varying, so cast it
            if SP_AXIS not in jax.typeof(x).vma:
                x = lax.pcast(x, SP_AXIS, to="varying")
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(n_layers))
    else:
        keys = jnp.zeros((n_layers, 2), jnp.uint32)

    if cfg.num_experts:
        # the dp(=ep)-sharded expert weights make the MoE output dp-varying;
        # cast the carry up front so scan's carry types match
        from apex_tpu.parallel.mesh import DP_AXIS

        if DP_AXIS not in jax.typeof(x).vma:
            x = lax.pcast(x, DP_AXIS, to="varying")

    if cfg.overlap_comm and TP_AXIS not in jax.typeof(x).vma:
        # the decomposed row-parallel exit (matmul_all_reduce) returns
        # equal VALUES with tp-varying TYPE, so the scan carry must enter
        # varying; the pcast's transpose is the psum that folds each
        # rank's partial cotangents back together on the residual path —
        # exactly where the monolithic program's invariant-input
        # reduction fires
        x = lax.pcast(x, TP_AXIS, to="varying")

    def body(h, lp_key):
        lp, key = lp_key
        h, aux = one(lp, h, key if dropout_key is not None else None)
        return h, aux

    out, aux_per_layer = lax.scan(body, x, (layers, keys),
                                  unroll=min(cfg.scan_unroll, n_layers))
    return out, jnp.mean(aux_per_layer)


def embed_tokens(embed, tokens, megatron_sp: bool = False):
    """Token + position embedding (ref GPT Embedding module). ``tokens`` may
    be the sp-local sequence shard; positions are offset by the sp rank.
    With ``megatron_sp`` the embedding's tp-psum becomes a reduce-scatter
    along seq and the result is the (b, s/(sp·tp), h) shard."""
    s_loc = tokens.shape[1]
    if megatron_sp:
        tp_size = lax.axis_size(TP_AXIS)
        if s_loc % tp_size:
            # validate() can only see max_seq; with a ring-sp axis the
            # per-rank requirement is (max_seq/sp) % tp — check the actual
            # shard here where both are known, instead of letting
            # psum_scatter fail deep in the trace
            raise ValueError(
                f"megatron_sp needs the sp-local sequence ({s_loc}) "
                f"divisible by tp ({tp_size})")
    h = vocab_parallel_embedding(tokens, embed["tok"],
                                 sequence_parallel=megatron_sp)
    try:
        sp = lax.axis_size(SP_AXIS)
    except NameError:
        sp = 1
    start = lax.axis_index(SP_AXIS) * s_loc if sp > 1 else 0
    if megatron_sp:
        s_shard = s_loc // lax.axis_size(TP_AXIS)
        start = start + lax.axis_index(TP_AXIS) * s_shard
        s_loc = s_shard
    if sp > 1 or megatron_sp:
        pos = lax.dynamic_slice_in_dim(embed["pos"], start, s_loc, 0)
    else:
        pos = embed["pos"][:s_loc]
    return h + pos[None].astype(h.dtype)


def _embed_with_dropout(embed, tokens, cfg: GPTConfig, dropout_key):
    x = embed_tokens(embed, tokens, megatron_sp=cfg.megatron_sp)
    if dropout_key is not None and cfg.hidden_dropout > 0.0:
        try:
            sp = lax.axis_size(SP_AXIS)
        except NameError:
            sp = 1
        # ref GPT embedding dropout: same hidden_dropout rate on the
        # embedding output; distinct stream from the per-layer keys. The
        # SP/TP shard decorrelation is _hidden_key's fold.
        if sp > 1 and SP_AXIS not in jax.typeof(x).vma:
            x = lax.pcast(x, SP_AXIS, to="varying")
        x = _hidden_dropout(x, cfg.hidden_dropout,
                            _hidden_key(jax.random.fold_in(dropout_key,
                                                           0x0E0B), cfg))
    return x


def gpt_forward(params, tokens, cfg: GPTConfig, dropout_key=None):
    """tokens (b, s) -> vocab-sharded logits (b, s, vocab/tp). Call inside a
    mesh program (tp axis bound; tp=1 is the degenerate single-chip case).
    ``dropout_key`` activates cfg's dropout rates (training mode). The MoE
    router aux loss (if any) is dropped here — use :func:`gpt_loss` for
    training."""
    x = _embed_with_dropout(params["embed"], tokens, cfg, dropout_key)
    x, _aux = _layer_stack(params["layers"], x, cfg, dropout_key=dropout_key)
    return gpt_head(params, x, cfg)


def tied_vocab_logits(x, tok_embed, megatron_sp: bool):
    """The tied-embedding LM-head exit shared by GPT and BERT: gather the
    sequence under megatron_sp (the vocab dim is sharded over the same tp
    axis, so the einsum needs the full sequence), mark the TP region, and
    contract against each rank's vocab shard (the reference's
    parallel_output=True path)."""
    from apex_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region,
        gather_from_sequence_parallel_region,
    )

    if megatron_sp:
        x = gather_from_sequence_parallel_region(x)
    x = copy_to_tensor_model_parallel_region(x)
    return jnp.einsum("bsh,vh->bsv", x, tok_embed)


def gpt_head(params, x, cfg: GPTConfig):
    """Final LN + LM head -> vocab-sharded logits. Tied: logits_i = h @ tok_iᵀ
    (each rank's vocab shard). Under ``cfg.megatron_sp`` the final LN runs
    on the sequence shard; :func:`tied_vocab_logits` gathers at the exit."""
    head = params["head"]
    x = layer_norm(x, head["ln_w"], head["ln_b"],
                   use_pallas=cfg.ln_pallas)
    if cfg.tie_embeddings:
        return tied_vocab_logits(x, params["embed"]["tok"], cfg.megatron_sp)
    if cfg.megatron_sp:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            gather_from_sequence_parallel_region,
        )

        x = gather_from_sequence_parallel_region(x)
    return column_parallel_linear(x, head["lm"], gather_output=False)


def _use_fused_loss(cfg: GPTConfig, n_rows: int) -> bool:
    """Fused path only when the kernel grid actually covers the shapes —
    otherwise the op's shape fallback (dense fp32 logits) would be slower
    than the unfused bf16 logits + CE path."""
    if not cfg.fused_loss:
        return False
    from apex_tpu.ops._pallas_util import compiled_backend
    from apex_tpu.ops.lm_head_loss import pallas_fits

    if compiled_backend():
        return pallas_fits(n_rows, cfg.hidden)
    return True  # CPU/virtual mesh: dense impl, exercised for coverage


def fused_head_loss(head_rows_w, ln_w, ln_b, x, targets,
                    gather_sequence: bool = False,
                    block_n: int = 0, block_v: int = 0,
                    ln_use_pallas=None):
    """Shared fused LM-head + CE block: final LN -> copy-to-TP-region ->
    pvary (so dw reduces over the data axes) -> fused loss kernel.
    ``head_rows_w``: (vocab/tp, hidden) projection rows. With
    ``gather_sequence`` (megatron_sp) the LN runs on the sequence shard
    and seq is gathered before the head."""
    from apex_tpu.ops.lm_head_loss import lm_head_loss
    from apex_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region,
        gather_from_sequence_parallel_region,
        pvary_like,
    )

    x = layer_norm(x, ln_w, ln_b, use_pallas=ln_use_pallas)
    if gather_sequence:
        x = gather_from_sequence_parallel_region(x)
    x = copy_to_tensor_model_parallel_region(x)
    # the loss kernel's custom_vjp hides w's linearity from shard_map's
    # invariant-input reduction; vary it explicitly over the activations'
    # axes so dw is psum'd over the data axes at the pvary transpose
    w = pvary_like(head_rows_w, x)
    kw = {}
    if block_n:
        kw["block_n"] = block_n
    if block_v:
        kw["block_v"] = block_v
    return jnp.mean(lm_head_loss(x, w, targets, axis_name=TP_AXIS, **kw))


def gpt_loss(params, tokens, targets, cfg: GPTConfig, dropout_key=None):
    """Mean vocab-parallel cross-entropy (ref vocab_parallel_cross_entropy).

    With ``cfg.fused_loss`` the head matmul is fused into the loss kernel
    (``ops/lm_head_loss.py``) and the logits are never materialized; the
    unfused path is kept for logits-consuming callers and parity tests.
    ``dropout_key`` activates cfg's dropout rates (training mode). With
    ``cfg.num_experts`` the layer-mean MoE router aux loss is added.
    """
    x = _embed_with_dropout(params["embed"], tokens, cfg, dropout_key)
    x, aux = _layer_stack(params["layers"], x, cfg, dropout_key=dropout_key)
    head = params["head"]
    if not _use_fused_loss(cfg, tokens.shape[0] * tokens.shape[1]):
        logits = gpt_head(params, x, cfg)
        # logits stay in model dtype; CE upcasts internally (fused by XLA)
        return jnp.mean(vocab_parallel_cross_entropy(logits, targets)) + aux
    w = (params["embed"]["tok"] if cfg.tie_embeddings
         else head["lm"].T)  # (vocab/tp, hidden) rows
    return fused_head_loss(w, head["ln_w"], head["ln_b"], x, targets,
                           gather_sequence=cfg.megatron_sp,
                           block_n=cfg.lm_block_n,
                           block_v=cfg.lm_block_v,
                           ln_use_pallas=cfg.ln_pallas) + aux


# ---------------------------------------------------------------------------
# pipeline wiring (PipelineSpec contract, schedules/common.py)

def gpt_pipeline_params(rng, cfg: GPTConfig, pp: int,
                        vp: Optional[int] = None) -> Pytree:
    """Re-group :func:`init_gpt_params` into the pipeline driver's
    ``{"embed", "stages" [pp, L/pp, ...], "head"}`` layout — or
    ``[vp, pp, L/(vp·pp), ...]`` for the interleaved schedule (chunk ``v`` on
    stage ``s`` holds depth block ``v·pp + s``, the Megatron interleaved
    assignment). The LM head is untied across stages (ref: the
    embedding-group grad allreduce; see schedules/common.py docstring for why
    tying is a non-issue here only when embed and head share a param — across
    stages they cannot)."""
    chunks = pp * (vp or 1)
    if cfg.num_layers % chunks:
        raise ValueError("num_layers must be divisible by pp * vp")
    cfg_untied = dataclasses.replace(cfg, tie_embeddings=False)
    flat = init_gpt_params(rng, cfg_untied)
    per = cfg.num_layers // chunks
    if vp is None:
        stages = jax.tree.map(
            lambda x: x.reshape((pp, per) + x.shape[1:]), flat["layers"])
    else:
        stages = jax.tree.map(
            lambda x: x.reshape((vp, pp, per) + x.shape[1:]), flat["layers"])
    return {"embed": flat["embed"], "stages": stages, "head": flat["head"]}


def gpt_pipeline_specs_tree(cfg: GPTConfig, interleaved: bool = False
                            ) -> Pytree:
    """PartitionSpecs for :func:`gpt_pipeline_params`."""
    from apex_tpu.parallel.mesh import PP_AXIS

    lead = (None, PP_AXIS) if interleaved else (PP_AXIS,)
    base = gpt_param_specs(
        dataclasses.replace(cfg, tie_embeddings=False),
        extra_layer_lead=lead)
    return {"embed": base["embed"], "stages": base["layers"],
            "head": base["head"]}


def gpt_pipeline_spec(cfg: GPTConfig, dropout: bool = False) -> PipelineSpec:
    """The three pipeline functions (PipelineSpec contract). With
    ``cfg.num_experts`` the stage function also yields its layers' router
    aux loss (``stage_aux=True``) — the schedules accumulate and add it.
    With ``dropout`` the embed/stage functions take the schedules'
    per-microbatch PRNG key (``takes_dropout_key``) and apply cfg's
    dropout rates — the ref ParallelTransformerLayer trains with dropout
    under every schedule; pass ``dropout_key=`` to the schedule driver."""

    if dropout:
        def embed_fn(embed, tokens, key):
            return _embed_with_dropout(embed, tokens, cfg, key)

        def stage_fn(stage_layers, h, key):
            out, aux = _layer_stack(stage_layers, h, cfg, dropout_key=key)
            if cfg.num_experts:
                return out, aux
            return out
    else:
        def embed_fn(embed, tokens):
            return embed_tokens(embed, tokens, megatron_sp=cfg.megatron_sp)

        def stage_fn(stage_layers, h):
            out, aux = _layer_stack(stage_layers, h, cfg)
            if cfg.num_experts:
                return out, aux
            return out

    def loss_fn(head, h, targets):
        # h is the seq shard under megatron_sp; the fused-loss gate needs
        # the gathered row count (what the kernel will actually see)
        rows = h.shape[0] * h.shape[1]
        if cfg.megatron_sp:
            rows *= lax.axis_size(TP_AXIS)
        if _use_fused_loss(cfg, rows):
            return fused_head_loss(head["lm"].T, head["ln_w"], head["ln_b"],
                                   h, targets,
                                   gather_sequence=cfg.megatron_sp,
                                   block_n=cfg.lm_block_n,
                                   block_v=cfg.lm_block_v,
                                   ln_use_pallas=cfg.ln_pallas)
        logits = gpt_head({"head": head}, h, cfg=dataclasses.replace(
            cfg, tie_embeddings=False))
        return jnp.mean(vocab_parallel_cross_entropy(logits, targets))

    return PipelineSpec(embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
                        stage_aux=bool(cfg.num_experts),
                        takes_dropout_key=dropout)

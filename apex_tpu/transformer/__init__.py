"""Megatron-style model-parallel transformer runtime (L5).

Ref ``apex/transformer/__init__.py:1-23``: exports ``parallel_state``,
``tensor_parallel``, ``pipeline_parallel``, the fused softmax module, and the
model-parallel-aware grad scaler.
"""

from apex_tpu.transformer import parallel_state  # noqa: F401

__all__ = [
    "parallel_state",
    "tensor_parallel",
    "pipeline_parallel",
    "functional",
    "amp",
    "moe",
    "sequence_parallel",
]


def __getattr__(name):
    if name in __all__:
        import importlib

        try:
            return importlib.import_module(f"apex_tpu.transformer.{name}")
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module 'apex_tpu.transformer' has no attribute {name!r} ({e})"
            ) from e
    raise AttributeError(f"module 'apex_tpu.transformer' has no attribute {name!r}")

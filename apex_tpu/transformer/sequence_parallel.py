"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Reference status (SURVEY.md §2.3 "SP" row): the reference has only *partial*
sequence-length tooling — activation-checkpoint sharding across TP ranks
(``apex/transformer/tensor_parallel/random.py:244-263``) and a scatter/gather
option in pipeline p2p (``p2p_communication.py:70-186``). It has **no ring
attention, no context parallelism, no Ulysses**. This module is the new
first-class capability the TPU build adds on top of reference parity.

Two TPU-native strategies over the ``sp`` mesh axis:

* :func:`ring_attention` — K/V shards rotate around the sp ring via
  ``lax.ppermute`` while each device's Q shard accumulates blockwise
  (online-softmax) partial attention. Peak memory per device is O(s_local²)
  scores per step; sequence length scales linearly with the ring size. The
  rotation rides ICI neighbor links — the same property the reference's NCCL
  p2p exploits for pipeline stages.
* :func:`ulysses_attention` — ``lax.all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs dense local attention (the Pallas
  flash kernel) on full-length sequences for h/sp heads, and re-shards back.
  Cheaper collectives for moderate sequence lengths; requires
  ``num_heads % sp == 0``.

Both are pure functions usable inside ``shard_map`` over the global mesh and
differentiable (the VJP of ``ppermute``/``all_to_all`` is the inverse
collective, so the backward pass rotates the opposite way automatically).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops.attention import NEG_INF, flash_attention
from apex_tpu.parallel.mesh import SP_AXIS


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_attention(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    remat_steps: bool = True,
):
    """Exact attention over a sequence sharded on ``axis_name``.

    ``q``/``k``/``v``: (batch, heads, s_local, head_dim) — this device's
    sequence shard; global sequence = sp_size × s_local, shard order = ring
    index order. Must run inside a mesh program. Returns this device's
    (batch, heads, s_local, head_dim) output shard, equal to the
    corresponding slice of dense attention over the gathered sequence.

    Online-softmax accumulation across ring steps: masked score entries are
    zeroed explicitly (not via exp of -inf) so fully-masked future chunks
    contribute exactly nothing, keeping finite arithmetic throughout.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q32 = q.astype(jnp.float32)

    qpos = my * s_loc + jnp.arange(s_loc)  # global positions of my Q rows

    def step(carry, t):
        k_c, v_c, m, l, acc = carry
        origin = (my - t) % n  # ring index the current K/V chunk came from
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32)) * scale
        if causal:
            kpos = origin * s_loc + jnp.arange(s_loc)
            masked = kpos[None, :] > qpos[:, None]
            s = jnp.where(masked, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # exp(NEG_INF - NEG_INF) == 1 would resurrect masked rows; zero the
        # contributions by value instead of relying on the exponent.
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
        k_next = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_next = lax.ppermute(v_c, axis_name, _ring_perm(n))
        return (k_next, v_next, m_new, l_new, acc_new), None

    if remat_steps:
        step = jax.checkpoint(step)

    # the accumulators become varying after one step over every axis q/k/v
    # vary over (plus the ring axis itself), so the scan carry must start
    # with the same varying-axis set
    try:
        want_vma = (set(jax.typeof(q).vma) | set(jax.typeof(k).vma)
                    | {axis_name})
    except (AttributeError, TypeError):
        want_vma = set()

    def _vary(x):
        missing = tuple(a for a in want_vma if a not in jax.typeof(x).vma)
        return lax.pcast(x, missing, to="varying") if missing else x

    m0 = _vary(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, s_loc, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    (_, _, _, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def ulysses_attention(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
):
    """All-to-all ("Ulysses") sequence parallelism.

    Input shards (batch, heads, s_local, head_dim) sequence-sharded on
    ``axis_name``; internally re-sharded to (batch, heads/sp, seq_global,
    head_dim) so each device runs *dense* local attention (the flash kernel)
    over the full sequence for its head slice, then re-sharded back.
    Requires ``heads % sp_size == 0``.
    """
    n = lax.axis_size(axis_name)
    b, h, s_loc, d = q.shape
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) % sp ({n}) == 0")
    if n == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               use_pallas=use_pallas)

    def to_heads(x):
        # [b, h, s_loc, d] -> [b, h/n, n*s_loc, d]: split heads across the
        # axis, concatenate the sequence shards.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    o = flash_attention(to_heads(q), to_heads(k), to_heads(v),
                        causal=causal, scale=scale, use_pallas=use_pallas)
    return to_seq(o)

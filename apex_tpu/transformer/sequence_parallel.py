"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Reference status (SURVEY.md §2.3 "SP" row): the reference has only *partial*
sequence-length tooling — activation-checkpoint sharding across TP ranks
(``apex/transformer/tensor_parallel/random.py:244-263``) and a scatter/gather
option in pipeline p2p (``p2p_communication.py:70-186``). It has **no ring
attention, no context parallelism, no Ulysses**. This module is the new
first-class capability the TPU build adds on top of reference parity.

Two TPU-native strategies over the ``sp`` mesh axis:

* :func:`ring_attention` — K/V shards rotate around the sp ring via
  ``lax.ppermute`` while each device's Q shard accumulates blockwise
  (online-softmax) partial attention. Peak memory per device is O(s_local²)
  scores per step; sequence length scales linearly with the ring size. The
  rotation rides ICI neighbor links — the same property the reference's NCCL
  p2p exploits for pipeline stages.
* :func:`ulysses_attention` — ``lax.all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs dense local attention (the Pallas
  flash kernel) on full-length sequences for h/sp heads, and re-shards back.
  Cheaper collectives for moderate sequence lengths; requires
  ``num_heads % sp == 0``.

Both are pure functions usable inside ``shard_map`` over the global mesh and
differentiable (the VJP of ``ppermute``/``all_to_all`` is the inverse
collective, so the backward pass rotates the opposite way automatically).

The ring here is also the repo's comm/compute-overlap archetype: each
K/V hop is data-independent of the attention block computed while it is
in flight, so the scheduler hides the rotation behind the math.
:mod:`apex_tpu.comm.overlap` applies the same decomposition to the
TP-boundary collective→matmul chains (Megatron-SP entry/exit and the
row-parallel psum — ``GPTConfig.overlap_comm``), and
``comm.accounting.overlap_report`` proves the hiding from compiled HLO
for both rings.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax import lax

from apex_tpu.comm.collectives import fold_seed
from apex_tpu.ops.attention import (
    NEG_INF,
    _fa_bwd,
    _fa_fwd,
    _pallas_ok,
    _pick_block,
    attention_dropout_mask,
    flash_attention,
)
from apex_tpu.parallel.mesh import SP_AXIS


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_attention(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    remat_steps: bool = True,
    impl: str = "auto",
    bias_strip=None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
):
    """Exact attention over a sequence sharded on ``axis_name``.

    ``q``/``k``/``v``: (batch, heads, s_local, head_dim) — this device's
    sequence shard; global sequence = sp_size × s_local, shard order = ring
    index order. Must run inside a mesh program. Returns this device's
    (batch, heads, s_local, head_dim) output shard, equal to the
    corresponding slice of dense attention over the gathered sequence.

    ``bias_strip``: optional batch-shared additive logit bias for THIS
    device's Q rows against ALL global key columns — shape (heads,
    s_local, sp × sk_local), e.g. a T5 relative-position-bias strip. Each
    ring step slices the arriving chunk's columns; the strip is
    differentiable (its grad flows back into the table that built it).

    ``impl``:

    * ``"auto"`` (default) — the chunked-flash ring: a ``custom_vjp`` whose
      forward merges per-chunk flash attention results by log-sum-exp and
      whose backward makes a second ring pass, running the flash backward
      per chunk against the saved *global* lse (so per-chunk probabilities
      are exact global softmax columns). Causal runs skip entirely-future
      chunks via ``lax.switch`` — ~2x fewer FLOPs at scale. Chunk math runs
      in the Pallas kernels on TPU and as einsum elsewhere (same structure,
      so the mesh tests exercise the real collectives + VJP).
    * ``"scan"`` — the original einsum online-softmax scan, differentiated
      by jax AD through the ring (reference implementation).

    ``dropout_rate`` > 0 (requires ``dropout_seed`` and ``impl='auto'``)
    applies probability dropout to the normalized attention weights with
    the flash kernels' GLOBAL-position-keyed counter hash: every chunk
    regenerates the slice of the dense mask its global (q, k) coordinates
    select, so the ring result equals a dense ``flash_attention`` call
    with the same seed — sharding is invisible to the dropout stream, and
    the mask is identical in forward and the second (backward) ring pass.
    Pass the same seed on every sp rank (positions decorrelate shards).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs dropout_seed")
    if impl == "auto":
        from apex_tpu.ops._pallas_util import compiled_backend

        b, h, s_loc, d = q.shape
        use_pallas = (compiled_backend()
                      and _pallas_ok(s_loc, s_loc, d, causal=False,
                                     allow_interpret=False))
        seed = (jnp.zeros((), jnp.int32) if dropout_seed is None
                else jnp.asarray(dropout_seed, jnp.int32).reshape(()))
        if bias_strip is not None:
            n = lax.axis_size(axis_name)
            want = (h, s_loc, n * k.shape[2])
            if bias_strip.shape != want:
                raise ValueError(
                    f"bias_strip must be (heads, s_local, sp*sk_local) = "
                    f"{want}, got {bias_strip.shape}")
            return _ring_flash_biased(q, k, v, bias_strip, seed, axis_name,
                                      causal, scale, use_pallas,
                                      float(dropout_rate))
        return _ring_flash(q, k, v, seed, axis_name, causal, scale,
                           use_pallas, float(dropout_rate))
    if bias_strip is not None:
        raise NotImplementedError("bias_strip needs impl='auto'")
    if dropout_rate > 0.0:
        raise NotImplementedError("attention dropout needs impl='auto'")
    return _ring_scan(q, k, v, axis_name, causal, scale, remat_steps)


def _ring_scan(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    remat_steps: bool = True,
):
    """Online-softmax einsum ring (AD-differentiated reference).

    Masked score entries are zeroed explicitly (not via exp of -inf) so
    fully-masked future chunks contribute exactly nothing, keeping finite
    arithmetic throughout.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q32 = q.astype(jnp.float32)

    qpos = my * s_loc + jnp.arange(s_loc)  # global positions of my Q rows

    def step(carry, t):
        k_c, v_c, m, l, acc = carry
        origin = (my - t) % n  # ring index the current K/V chunk came from
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32)) * scale
        if causal:
            kpos = origin * s_loc + jnp.arange(s_loc)
            masked = kpos[None, :] > qpos[:, None]
            s = jnp.where(masked, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # exp(NEG_INF - NEG_INF) == 1 would resurrect masked rows; zero the
        # contributions by value instead of relying on the exponent.
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
        k_next = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_next = lax.ppermute(v_c, axis_name, _ring_perm(n))
        return (k_next, v_next, m_new, l_new, acc_new), None

    if remat_steps:
        step = jax.checkpoint(step)

    # the accumulators become varying after one step over every axis q/k/v
    # vary over (plus the ring axis itself), so the scan carry must start
    # with the same varying-axis set
    try:
        want_vma = (set(jax.typeof(q).vma) | set(jax.typeof(k).vma)
                    | {axis_name})
    except (AttributeError, TypeError):
        want_vma = set()

    def _vary(x):
        missing = tuple(a for a in want_vma if a not in jax.typeof(x).vma)
        return lax.pcast(x, missing, to="varying") if missing else x

    m0 = _vary(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, s_loc, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    (_, _, _, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Chunked-flash ring: custom_vjp, per-chunk kernels, global-lse backward.

def _vary_like_inputs(x, *refs, extra=()):
    """pcast ``x`` to the union of the refs' varying axes plus ``extra`` —
    scan carries must start with the vma they will acquire."""
    try:
        want = set(extra)
        for r in refs:
            want |= set(jax.typeof(r).vma)
        missing = tuple(a for a in want if a not in jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return x
    return lax.pcast(x, missing, to="varying") if missing else x


def _chunk_keep(dropout, b, h, s, sk):
    """(b, h, s, sk) keep mask for one ring chunk — the kernels' global
    hash at this chunk's offsets, so the einsum path and a dense global
    call drop identical entries. ``dropout = (rate, seed, q_off, k_off)``
    or None."""
    rate, seed, q_off, k_off = dropout
    return attention_dropout_mask(seed, rate, b * h, s, sk, q_off,
                                  k_off).reshape(b, h, s, sk)


def _chunk_seed3(dropout):
    rate, seed, q_off, k_off = dropout
    return jnp.stack([jnp.asarray(seed, jnp.int32).reshape(()),
                      jnp.asarray(q_off, jnp.int32).reshape(()),
                      jnp.asarray(k_off, jnp.int32).reshape(())])


def _chunk_fwd(q, k_c, v_c, scale, causal, use_pallas, bias_c=None,
               dropout=None):
    """One Q-shard x K/V-chunk attention -> (o [q.dtype], lse fp32).
    ``k_c``/``v_c`` may have a different sequence length than ``q``
    (cross-attention rings); the causal mask is only meaningful square.
    ``bias_c``: optional batch-shared (h, s, sk) additive logit bias for
    this chunk's columns (T5 relative position bias under ring SP).
    ``dropout``: optional ``(rate, seed, q_off, k_off)`` — probability
    dropout on the normalized weights with the kernels' global-position
    hash, offsets mapping this chunk into the global mask."""
    b, h, s, d = q.shape
    sk = k_c.shape[2]
    rate = dropout[0] if dropout is not None else 0.0
    if use_pallas:
        q3 = q.reshape(b * h, s, d)
        o3, lse3 = _fa_fwd(q3, k_c.reshape(b * h, sk, d),
                           v_c.reshape(b * h, sk, d), scale, causal,
                           _pick_block(s, 128), _pick_block(sk, 128),
                           interpret=False, bias=bias_c,
                           dropout_rate=rate,
                           seed=None if dropout is None
                           else _chunk_seed3(dropout))
        return o3.reshape(b, h, s, d), lse3[..., 0].reshape(b, h, s)
    q32 = q.astype(jnp.float32)
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32)) * scale
    if bias_c is not None:
        s_ = s_ + bias_c.astype(jnp.float32)
    if causal:
        s_ = jnp.where(jnp.arange(sk)[None, :] > jnp.arange(s)[:, None],
                       NEG_INF, s_)
    m = jnp.max(s_, axis=-1, keepdims=True)
    p = jnp.exp(s_ - m)
    p = jnp.where(s_ <= NEG_INF / 2, 0.0, p)
    # l accumulates the UNdropped p (normalization precedes dropout) —
    # identical to the kernel's accumulation order
    l = jnp.sum(p, axis=-1, keepdims=True)
    if rate > 0.0:
        keep = _chunk_keep(dropout, b, h, s, sk)
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
    o = o / jnp.where(l == 0.0, 1.0, l)
    lse = jnp.where(l[..., 0] == 0.0, NEG_INF, m[..., 0] + jnp.log(
        jnp.where(l[..., 0] == 0.0, 1.0, l[..., 0])))
    return o.astype(q.dtype), lse


def _chunk_bwd(q, k_c, v_c, o, lse, do, delta, scale, causal, use_pallas,
               bias_c=None, want_dbias=False, dropout=None):
    """Per-chunk flash backward against the *global* lse -> (dq, dk, dv[,
    dbias]) fp32. ``p = exp(s - lse_global)`` is the exact global softmax
    restricted to this chunk's columns, so summing chunk contributions
    reproduces the dense backward; dbias (batch-reduced, no q·kᵀ scale)
    is returned when ``want_dbias``. ``dropout`` as in :func:`_chunk_fwd`
    — the mask regenerates from the same global hash."""
    b, h, s, d = q.shape
    sk = k_c.shape[2]
    rate = dropout[0] if dropout is not None else 0.0
    if use_pallas:
        sh = (b * h, s, d)
        shk = (b * h, sk, d)
        dq3, dk3, dv3, db = _fa_bwd(
            q.reshape(sh), k_c.reshape(shk), v_c.reshape(shk), o.reshape(sh),
            lse.reshape(b * h, s, 1), do.reshape(sh), scale, causal,
            _pick_block(s, 128), _pick_block(sk, 128), interpret=False,
            bias=bias_c, dropout_rate=rate,
            seed=None if dropout is None else _chunk_seed3(dropout))
        out = (dq3.reshape(b, h, s, d).astype(jnp.float32),
               dk3.reshape(b, h, sk, d).astype(jnp.float32),
               dv3.reshape(b, h, sk, d).astype(jnp.float32))
        return out + (db,) if want_dbias else out
    q32 = q.astype(jnp.float32)
    k32 = k_c.astype(jnp.float32)
    v32 = v_c.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    if bias_c is not None:
        s_ = s_ + bias_c.astype(jnp.float32)
    if causal:
        s_ = jnp.where(jnp.arange(sk)[None, :] > jnp.arange(s)[:, None],
                       NEG_INF, s_)
    p = jnp.exp(s_ - lse[..., None])
    p = jnp.where(s_ <= NEG_INF / 2, 0.0, p)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v32)
    if rate > 0.0:
        # mirror the kernels exactly: dv from the DROPPED+rescaled p, dp
        # masked+rescaled before the ds chain (dropout is elementwise on
        # the normalized weights, so its transpose masks the cotangent)
        keep = _chunk_keep(dropout, b, h, s, sk)
        inv = 1.0 / (1.0 - rate)
        p_v = jnp.where(keep, p * inv, 0.0)
        dp = jnp.where(keep, dp * inv, 0.0)
    else:
        p_v = p
    dv = jnp.einsum("bhqk,bhqd->bhkd", p_v, do32)
    ds_pre = p * (dp - delta)  # dL/ds before the q·kᵀ scale chain
    ds = ds_pre * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k32)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
    if want_dbias:
        return dq, dk, dv, jnp.sum(ds_pre, axis=0)
    return dq, dk, dv


def _branch_idx(origin, my, causal):
    # 0 = full chunk, 1 = diagonal (in-chunk causal), 2 = entirely future
    if not causal:
        return jnp.int32(0)
    return jnp.where(origin == my, jnp.int32(1),
                     jnp.where(origin < my, jnp.int32(0), jnp.int32(2)))


def _bias_chunk(bias_strip, origin, sk_loc):
    return lax.dynamic_slice_in_dim(bias_strip, origin * sk_loc, sk_loc,
                                    axis=2)


# One shared fwd/bwd ring implementation, parameterized by an optional
# per-device bias STRIP — this device's Q rows against ALL global key
# columns, shape (heads, s_loc, n * sk_loc) — sliced per ring step at the
# chunk origin. Two thin custom_vjp entry points wrap it: the strip must
# be an explicit custom_vjp argument when present (a closure over the T5
# rel table would be an illegal captured tracer), and the unbiased path
# must not carry a dummy strip (it would cost O(s²/n) memory for nothing).

def _ring_fwd_impl(q, k, v, bias_strip, axis_name, causal, scale,
                   use_pallas, dropout_rate=0.0, seed=None):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    sk_loc = k.shape[2]
    has_bias = bias_strip is not None
    q_off = my * s_loc  # global row offset of this device's Q shard

    def _dropout(k_off):
        if dropout_rate <= 0.0:
            return None
        return (dropout_rate, seed, q_off, k_off)

    def full_f(q, k_c, v_c, k_off, bias_c=None):
        return _chunk_fwd(q, k_c, v_c, scale, False, use_pallas, bias_c,
                          dropout=_dropout(k_off))

    def diag_f(q, k_c, v_c, k_off, bias_c=None):
        return _chunk_fwd(q, k_c, v_c, scale, True, use_pallas, bias_c,
                          dropout=_dropout(k_off))

    def skip_f(q, k_c, v_c, k_off, bias_c=None):
        # match the compute branches' varying axes (switch unifies types)
        return (_vary_like_inputs(jnp.zeros_like(q), q, k_c),
                _vary_like_inputs(
                    jnp.full((b, h, s_loc), NEG_INF, jnp.float32), q, k_c))

    def step(carry, t):
        k_c, v_c, o_bar, lse_run = carry
        origin = (my - t) % n
        args = (q, k_c, v_c, origin * sk_loc)
        if has_bias:
            args += (_bias_chunk(bias_strip, origin, sk_loc),)
        o_c, lse_c = lax.switch(_branch_idx(origin, my, causal),
                                (full_f, diag_f, skip_f), *args)
        lse_new = jnp.logaddexp(lse_run, lse_c)
        w_old = jnp.exp(lse_run - lse_new)[..., None]
        w_new = jnp.exp(lse_c - lse_new)[..., None]
        o_bar = o_bar * w_old + o_c.astype(jnp.float32) * w_new
        k_c = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_c = lax.ppermute(v_c, axis_name, _ring_perm(n))
        return (k_c, v_c, o_bar, lse_new), None

    o0 = _vary_like_inputs(jnp.zeros((b, h, s_loc, d), jnp.float32),
                           q, k, extra=(axis_name,))
    lse0 = _vary_like_inputs(jnp.full((b, h, s_loc), NEG_INF, jnp.float32),
                             q, k, extra=(axis_name,))
    (_, _, o_bar, lse), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    return o_bar.astype(q.dtype), lse


def _ring_bwd_impl(q, k, v, bias_strip, o, lse, do, axis_name, causal,
                   scale, use_pallas, dropout_rate=0.0, seed=None):
    """-> (dq, dk, dv[, dbias_strip]) — the last only when biased."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    sk_loc = k.shape[2]
    has_bias = bias_strip is not None
    q_off = my * s_loc
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def _dropout(k_off):
        if dropout_rate <= 0.0:
            return None
        return (dropout_rate, seed, q_off, k_off)

    def full_f(q, k_c, v_c, k_off, bias_c=None):
        return _chunk_bwd(q, k_c, v_c, o, lse, do, delta, scale, False,
                          use_pallas, bias_c, want_dbias=has_bias,
                          dropout=_dropout(k_off))

    def diag_f(q, k_c, v_c, k_off, bias_c=None):
        return _chunk_bwd(q, k_c, v_c, o, lse, do, delta, scale, True,
                          use_pallas, bias_c, want_dbias=has_bias,
                          dropout=_dropout(k_off))

    def skip_f(q, k_c, v_c, k_off, bias_c=None):
        zq = _vary_like_inputs(jnp.zeros((b, h, s_loc, d), jnp.float32),
                               q, k_c, do)
        zk = _vary_like_inputs(
            jnp.zeros((b, h, sk_loc, d), jnp.float32), q, k_c, do)
        if not has_bias:
            return zq, zk, zk
        zb = _vary_like_inputs(
            jnp.zeros((h, s_loc, sk_loc), jnp.float32), q, k_c, do)
        return zq, zk, zk, zb

    def step(carry, t):
        if has_bias:
            k_c, v_c, dq_acc, dk_acc, dv_acc, db_strip = carry
        else:
            k_c, v_c, dq_acc, dk_acc, dv_acc = carry
        origin = (my - t) % n
        args = (q, k_c, v_c, origin * sk_loc)
        if has_bias:
            args += (_bias_chunk(bias_strip, origin, sk_loc),)
        out = lax.switch(_branch_idx(origin, my, causal),
                         (full_f, diag_f, skip_f), *args)
        dq_acc = dq_acc + out[0]
        if has_bias:
            # each origin is visited exactly once, so the strip columns
            # are written once (zeros elsewhere)
            db_strip = lax.dynamic_update_slice_in_dim(
                db_strip, out[3].astype(jnp.float32), origin * sk_loc,
                axis=2)
        # dk/dv accumulators ride the same rotation as their K/V chunk, so
        # after n steps each lands back on its owner fully accumulated
        dk_acc = lax.ppermute(dk_acc + out[1], axis_name, _ring_perm(n))
        dv_acc = lax.ppermute(dv_acc + out[2], axis_name, _ring_perm(n))
        k_c = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_c = lax.ppermute(v_c, axis_name, _ring_perm(n))
        new = (k_c, v_c, dq_acc, dk_acc, dv_acc)
        return (new + (db_strip,) if has_bias else new), None

    def z0(seq_len):
        return _vary_like_inputs(
            jnp.zeros((b, h, seq_len, d), jnp.float32),
            q, k, do, extra=(axis_name,))

    carry0 = (k, v, z0(s_loc), z0(sk_loc), z0(sk_loc))
    if has_bias:
        carry0 += (_vary_like_inputs(
            jnp.zeros((h, s_loc, n * sk_loc), jnp.float32),
            q, k, do, extra=(axis_name,)),)
    carry, _ = lax.scan(step, carry0, jnp.arange(n))
    dq, dk, dv = carry[2], carry[3], carry[4]
    out = (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))
    if has_bias:
        out += (carry[5].astype(bias_strip.dtype),)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ring_flash(q, k, v, seed, axis_name, causal, scale, use_pallas,
                dropout_rate):
    o, _ = _ring_flash_fwd(q, k, v, seed, axis_name, causal, scale,
                           use_pallas, dropout_rate)
    return o


def _ring_flash_fwd(q, k, v, seed, axis_name, causal, scale, use_pallas,
                    dropout_rate):
    o, lse = _ring_fwd_impl(q, k, v, None, axis_name, causal, scale,
                            use_pallas, dropout_rate, seed)
    # named like the dense flash residuals (ops/attention.py): under the
    # dots_attn remat policy the backward ring then starts from the saved
    # (o, lse) instead of replaying the ENTIRE forward ring — n chunk
    # kernels plus the ppermute rotation per layer
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, seed, o, lse)


def _ring_flash_bwd(axis_name, causal, scale, use_pallas, dropout_rate,
                    res, do):
    q, k, v, seed, o, lse = res
    dq, dk, dv = _ring_bwd_impl(q, k, v, None, o, lse, do, axis_name,
                                causal, scale, use_pallas, dropout_rate,
                                seed)
    return dq, dk, dv, None


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ring_flash_biased(q, k, v, bias_strip, seed, axis_name, causal, scale,
                       use_pallas, dropout_rate):
    o, _ = _ring_flash_biased_fwd(q, k, v, bias_strip, seed, axis_name,
                                  causal, scale, use_pallas, dropout_rate)
    return o


def _ring_flash_biased_fwd(q, k, v, bias_strip, seed, axis_name, causal,
                           scale, use_pallas, dropout_rate):
    o, lse = _ring_fwd_impl(q, k, v, bias_strip, axis_name, causal, scale,
                            use_pallas, dropout_rate, seed)
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, bias_strip, seed, o, lse)


def _ring_flash_biased_bwd(axis_name, causal, scale, use_pallas,
                           dropout_rate, res, do):
    q, k, v, bias_strip, seed, o, lse = res
    dq, dk, dv, db = _ring_bwd_impl(q, k, v, bias_strip, o, lse, do,
                                    axis_name, causal, scale, use_pallas,
                                    dropout_rate, seed)
    return dq, dk, dv, db, None


_ring_flash_biased.defvjp(_ring_flash_biased_fwd, _ring_flash_biased_bwd)


def ulysses_attention(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
):
    """All-to-all ("Ulysses") sequence parallelism.

    Input shards (batch, heads, s_local, head_dim) sequence-sharded on
    ``axis_name``; internally re-sharded to (batch, heads/sp, seq_global,
    head_dim) so each device runs *dense* local attention (the flash kernel)
    over the full sequence for its head slice, then re-sharded back.
    Requires ``heads % sp_size == 0``.

    ``dropout_rate`` > 0 (requires ``dropout_seed``) drops on the local
    head slice with the sp RANK folded into the seed: each rank's heads
    draw an independent stream (the Megatron-TP decorrelation model).
    Unlike :func:`ring_attention` the masks are NOT layout-invariant —
    the head->device assignment enters the stream; use the ring when
    bitwise sp-invariance matters.
    """
    n = lax.axis_size(axis_name)
    b, h, s_loc, d = q.shape
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) % sp ({n}) == 0")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs dropout_seed")
    if n == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               use_pallas=use_pallas,
                               dropout_rate=dropout_rate,
                               dropout_seed=dropout_seed)

    def to_heads(x):
        # [b, h, s_loc, d] -> [b, h/n, n*s_loc, d]: split heads across the
        # axis, concatenate the sequence shards.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    seed = dropout_seed
    if dropout_rate > 0.0:
        # decorrelate the per-rank head slices (local bh indices repeat
        # on every rank; an unfolded seed would reuse one mask per slot).
        # The fold must be NON-linear: a linear ``seed + C*rank`` aliases —
        # two runs whose seeds differ by a multiple of C replay another
        # rank's mask stream. fold_seed's full-avalanche fmix32 combine
        # makes stream collisions require an exact 32-bit hash collision.
        seed = fold_seed(dropout_seed, lax.axis_index(axis_name))
    o = flash_attention(to_heads(q), to_heads(k), to_heads(v),
                        causal=causal, scale=scale, use_pallas=use_pallas,
                        dropout_rate=dropout_rate, dropout_seed=seed)
    return to_seq(o)

"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Reference status (SURVEY.md §2.3 "SP" row): the reference has only *partial*
sequence-length tooling — activation-checkpoint sharding across TP ranks
(``apex/transformer/tensor_parallel/random.py:244-263``) and a scatter/gather
option in pipeline p2p (``p2p_communication.py:70-186``). It has **no ring
attention, no context parallelism, no Ulysses**. This module is the new
first-class capability the TPU build adds on top of reference parity.

Two TPU-native strategies over the ``sp`` mesh axis:

* :func:`ring_attention` — K/V shards rotate around the sp ring via
  ``lax.ppermute`` while each device's Q shard accumulates blockwise
  (online-softmax) partial attention. Peak memory per device is O(s_local²)
  scores per step; sequence length scales linearly with the ring size. The
  rotation rides ICI neighbor links — the same property the reference's NCCL
  p2p exploits for pipeline stages.
* :func:`ulysses_attention` — ``lax.all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs dense local attention (the Pallas
  flash kernel) on full-length sequences for h/sp heads, and re-shards back.
  Cheaper collectives for moderate sequence lengths; requires
  ``num_heads % sp == 0``.

Both are pure functions usable inside ``shard_map`` over the global mesh and
differentiable (the VJP of ``ppermute``/``all_to_all`` is the inverse
collective, so the backward pass rotates the opposite way automatically).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops.attention import (
    NEG_INF,
    _fa_bwd,
    _fa_fwd,
    _pallas_ok,
    _pick_block,
    flash_attention,
)
from apex_tpu.parallel.mesh import SP_AXIS


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_attention(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    remat_steps: bool = True,
    impl: str = "auto",
):
    """Exact attention over a sequence sharded on ``axis_name``.

    ``q``/``k``/``v``: (batch, heads, s_local, head_dim) — this device's
    sequence shard; global sequence = sp_size × s_local, shard order = ring
    index order. Must run inside a mesh program. Returns this device's
    (batch, heads, s_local, head_dim) output shard, equal to the
    corresponding slice of dense attention over the gathered sequence.

    ``impl``:

    * ``"auto"`` (default) — the chunked-flash ring: a ``custom_vjp`` whose
      forward merges per-chunk flash attention results by log-sum-exp and
      whose backward makes a second ring pass, running the flash backward
      per chunk against the saved *global* lse (so per-chunk probabilities
      are exact global softmax columns). Causal runs skip entirely-future
      chunks via ``lax.switch`` — ~2x fewer FLOPs at scale. Chunk math runs
      in the Pallas kernels on TPU and as einsum elsewhere (same structure,
      so the mesh tests exercise the real collectives + VJP).
    * ``"scan"`` — the original einsum online-softmax scan, differentiated
      by jax AD through the ring (reference implementation).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "auto":
        b, h, s_loc, d = q.shape
        use_pallas = (jax.default_backend() == "tpu"
                      and _pallas_ok(s_loc, s_loc, d, causal=False,
                                     allow_interpret=False))
        return _ring_flash(q, k, v, axis_name, causal, scale, use_pallas)
    return _ring_scan(q, k, v, axis_name, causal, scale, remat_steps)


def _ring_scan(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    remat_steps: bool = True,
):
    """Online-softmax einsum ring (AD-differentiated reference).

    Masked score entries are zeroed explicitly (not via exp of -inf) so
    fully-masked future chunks contribute exactly nothing, keeping finite
    arithmetic throughout.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q32 = q.astype(jnp.float32)

    qpos = my * s_loc + jnp.arange(s_loc)  # global positions of my Q rows

    def step(carry, t):
        k_c, v_c, m, l, acc = carry
        origin = (my - t) % n  # ring index the current K/V chunk came from
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32)) * scale
        if causal:
            kpos = origin * s_loc + jnp.arange(s_loc)
            masked = kpos[None, :] > qpos[:, None]
            s = jnp.where(masked, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # exp(NEG_INF - NEG_INF) == 1 would resurrect masked rows; zero the
        # contributions by value instead of relying on the exponent.
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
        k_next = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_next = lax.ppermute(v_c, axis_name, _ring_perm(n))
        return (k_next, v_next, m_new, l_new, acc_new), None

    if remat_steps:
        step = jax.checkpoint(step)

    # the accumulators become varying after one step over every axis q/k/v
    # vary over (plus the ring axis itself), so the scan carry must start
    # with the same varying-axis set
    try:
        want_vma = (set(jax.typeof(q).vma) | set(jax.typeof(k).vma)
                    | {axis_name})
    except (AttributeError, TypeError):
        want_vma = set()

    def _vary(x):
        missing = tuple(a for a in want_vma if a not in jax.typeof(x).vma)
        return lax.pcast(x, missing, to="varying") if missing else x

    m0 = _vary(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, s_loc, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    (_, _, _, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Chunked-flash ring: custom_vjp, per-chunk kernels, global-lse backward.

def _vary_like_inputs(x, *refs, extra=()):
    """pcast ``x`` to the union of the refs' varying axes plus ``extra`` —
    scan carries must start with the vma they will acquire."""
    try:
        want = set(extra)
        for r in refs:
            want |= set(jax.typeof(r).vma)
        missing = tuple(a for a in want if a not in jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return x
    return lax.pcast(x, missing, to="varying") if missing else x


def _chunk_fwd(q, k_c, v_c, scale, causal, use_pallas):
    """One Q-shard x K/V-chunk attention -> (o [q.dtype], lse fp32).
    ``k_c``/``v_c`` may have a different sequence length than ``q``
    (cross-attention rings); the causal mask is only meaningful square."""
    b, h, s, d = q.shape
    sk = k_c.shape[2]
    if use_pallas:
        q3 = q.reshape(b * h, s, d)
        o3, lse3 = _fa_fwd(q3, k_c.reshape(b * h, sk, d),
                           v_c.reshape(b * h, sk, d), scale, causal,
                           _pick_block(s, 128), _pick_block(sk, 128),
                           interpret=False)
        return o3.reshape(b, h, s, d), lse3[..., 0].reshape(b, h, s)
    q32 = q.astype(jnp.float32)
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32)) * scale
    if causal:
        s_ = jnp.where(jnp.arange(sk)[None, :] > jnp.arange(s)[:, None],
                       NEG_INF, s_)
    m = jnp.max(s_, axis=-1, keepdims=True)
    p = jnp.exp(s_ - m)
    p = jnp.where(s_ <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
    o = o / jnp.where(l == 0.0, 1.0, l)
    lse = jnp.where(l[..., 0] == 0.0, NEG_INF, m[..., 0] + jnp.log(
        jnp.where(l[..., 0] == 0.0, 1.0, l[..., 0])))
    return o.astype(q.dtype), lse


def _chunk_bwd(q, k_c, v_c, o, lse, do, delta, scale, causal, use_pallas):
    """Per-chunk flash backward against the *global* lse -> (dq, dk, dv)
    fp32. ``p = exp(s - lse_global)`` is the exact global softmax restricted
    to this chunk's columns, so summing chunk contributions reproduces the
    dense backward."""
    b, h, s, d = q.shape
    sk = k_c.shape[2]
    if use_pallas:
        sh = (b * h, s, d)
        shk = (b * h, sk, d)
        dq3, dk3, dv3, _ = _fa_bwd(
            q.reshape(sh), k_c.reshape(shk), v_c.reshape(shk), o.reshape(sh),
            lse.reshape(b * h, s, 1), do.reshape(sh), scale, causal,
            _pick_block(s, 128), _pick_block(sk, 128), interpret=False)
        return (dq3.reshape(b, h, s, d).astype(jnp.float32),
                dk3.reshape(b, h, sk, d).astype(jnp.float32),
                dv3.reshape(b, h, sk, d).astype(jnp.float32))
    q32 = q.astype(jnp.float32)
    k32 = k_c.astype(jnp.float32)
    v32 = v_c.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    if causal:
        s_ = jnp.where(jnp.arange(sk)[None, :] > jnp.arange(s)[:, None],
                       NEG_INF, s_)
    p = jnp.exp(s_ - lse[..., None])
    p = jnp.where(s_ <= NEG_INF / 2, 0.0, p)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v32)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k32)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, scale, use_pallas):
    o, _ = _ring_flash_fwd(q, k, v, axis_name, causal, scale, use_pallas)
    return o


def _branch_idx(origin, my, causal):
    # 0 = full chunk, 1 = diagonal (in-chunk causal), 2 = entirely future
    if not causal:
        return jnp.int32(0)
    return jnp.where(origin == my, jnp.int32(1),
                     jnp.where(origin < my, jnp.int32(0), jnp.int32(2)))


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, use_pallas):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    def full_f(q, k_c, v_c):
        return _chunk_fwd(q, k_c, v_c, scale, False, use_pallas)

    def diag_f(q, k_c, v_c):
        return _chunk_fwd(q, k_c, v_c, scale, True, use_pallas)

    def skip_f(q, k_c, v_c):
        # match the compute branches' varying axes (switch unifies types)
        return (_vary_like_inputs(jnp.zeros_like(q), q, k_c),
                _vary_like_inputs(
                    jnp.full((b, h, s_loc), NEG_INF, jnp.float32), q, k_c))

    def step(carry, t):
        k_c, v_c, o_bar, lse_run = carry
        origin = (my - t) % n
        o_c, lse_c = lax.switch(_branch_idx(origin, my, causal),
                                (full_f, diag_f, skip_f), q, k_c, v_c)
        lse_new = jnp.logaddexp(lse_run, lse_c)
        w_old = jnp.exp(lse_run - lse_new)[..., None]
        w_new = jnp.exp(lse_c - lse_new)[..., None]
        o_bar = o_bar * w_old + o_c.astype(jnp.float32) * w_new
        k_c = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_c = lax.ppermute(v_c, axis_name, _ring_perm(n))
        return (k_c, v_c, o_bar, lse_new), None

    o0 = _vary_like_inputs(jnp.zeros((b, h, s_loc, d), jnp.float32),
                           q, k, extra=(axis_name,))
    lse0 = _vary_like_inputs(jnp.full((b, h, s_loc), NEG_INF, jnp.float32),
                             q, k, extra=(axis_name,))
    (_, _, o_bar, lse), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    o = o_bar.astype(q.dtype)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, scale, use_pallas, res, do):
    q, k, v, o, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def full_f(q, k_c, v_c):
        return _chunk_bwd(q, k_c, v_c, o, lse, do, delta, scale, False,
                          use_pallas)

    def diag_f(q, k_c, v_c):
        return _chunk_bwd(q, k_c, v_c, o, lse, do, delta, scale, True,
                          use_pallas)

    def skip_f(q, k_c, v_c):
        zq = _vary_like_inputs(jnp.zeros((b, h, s_loc, d), jnp.float32),
                               q, k_c, do)
        zk = _vary_like_inputs(
            jnp.zeros((b, h, k_c.shape[2], d), jnp.float32), q, k_c, do)
        return zq, zk, zk

    def step(carry, t):
        k_c, v_c, dq_acc, dk_acc, dv_acc = carry
        origin = (my - t) % n
        dq_c, dk_c, dv_c = lax.switch(_branch_idx(origin, my, causal),
                                      (full_f, diag_f, skip_f), q, k_c, v_c)
        dq_acc = dq_acc + dq_c
        # dk/dv accumulators ride the same rotation as their K/V chunk, so
        # after n steps each lands back on its owner fully accumulated
        dk_acc = lax.ppermute(dk_acc + dk_c, axis_name, _ring_perm(n))
        dv_acc = lax.ppermute(dv_acc + dv_c, axis_name, _ring_perm(n))
        k_c = lax.ppermute(k_c, axis_name, _ring_perm(n))
        v_c = lax.ppermute(v_c, axis_name, _ring_perm(n))
        return (k_c, v_c, dq_acc, dk_acc, dv_acc), None

    def z0(seq_len):
        return _vary_like_inputs(
            jnp.zeros((b, h, seq_len, d), jnp.float32),
            q, k, do, extra=(axis_name,))

    sk_loc = k.shape[2]
    (_, _, dq, dk, dv), _ = lax.scan(
        step, (k, v, z0(s_loc), z0(sk_loc), z0(sk_loc)), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ulysses_attention(
    q, k, v,
    axis_name: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
):
    """All-to-all ("Ulysses") sequence parallelism.

    Input shards (batch, heads, s_local, head_dim) sequence-sharded on
    ``axis_name``; internally re-sharded to (batch, heads/sp, seq_global,
    head_dim) so each device runs *dense* local attention (the flash kernel)
    over the full sequence for its head slice, then re-sharded back.
    Requires ``heads % sp_size == 0``.
    """
    n = lax.axis_size(axis_name)
    b, h, s_loc, d = q.shape
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) % sp ({n}) == 0")
    if n == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               use_pallas=use_pallas)

    def to_heads(x):
        # [b, h, s_loc, d] -> [b, h/n, n*s_loc, d]: split heads across the
        # axis, concatenate the sequence shards.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    o = flash_attention(to_heads(q), to_heads(k), to_heads(v),
                        causal=causal, scale=scale, use_pallas=use_pallas)
    return to_seq(o)

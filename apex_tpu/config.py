"""Unified configuration dataclasses.

The reference has three separate flag systems (SURVEY.md §5): amp's
``Properties`` policy object with consistency checks (``apex/amp/frontend.py:7-193``),
setup.py build flags, and the Megatron global-args singleton
(``apex/transformer/testing/arguments.py``). Here they are unified into plain
frozen dataclasses: a :class:`MeshConfig` describing the device mesh, a
:class:`PrecisionConfig` describing the mixed-precision policy (the O0-O3
presets live in :mod:`apex_tpu.amp` and *produce* one of these), and a
:class:`TransformerParallelConfig` for the Megatron-style runtime. No build
flags exist: every subsystem is importable always, with runtime fallbacks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device-mesh shape. Axes use the scaling-book convention:

    - ``dp``: data parallel (outermost; rides DCN across slices, ICI within)
    - ``pp``: pipeline stages (collective-permute neighbours over ICI)
    - ``tp``: tensor/model parallel (innermost — highest-bandwidth ICI ring)
    - ``sp``: sequence/context parallel (ring attention axis)

    ``dp=-1`` means "all remaining devices" (resolved at mesh build time).
    Reference analogue: the four process-group families built by
    ``apex/transformer/parallel_state.py:57-185``.
    """

    dp: int = -1
    pp: int = 1
    tp: int = 1
    sp: int = 1

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.pp, self.sp, self.tp)


@dataclasses.dataclass(frozen=True)
class PrecisionConfig:
    """Declarative mixed-precision policy — the trace-time equivalent of amp's
    ``Properties`` (ref ``apex/amp/frontend.py:7-100``).

    ``cast_model_type``      — dtype model params are cast to before forward
                               (None = leave fp32; ref Properties.cast_model_type)
    ``compute_dtype``        — dtype whitelisted ops (matmul/conv) run in under
                               the O1-style autocast interpreter (None = off;
                               ref "patch_torch_functions")
    ``keep_batchnorm_fp32``  — keep normalization layers' math + params fp32
                               (ref Properties.keep_batchnorm_fp32)
    ``master_weights``       — hold an fp32 master copy of params and run the
                               optimizer on it (ref Properties.master_weights)
    ``loss_scale``           — float for static scale, "dynamic" for dynamic
                               (ref Properties.loss_scale)
    """

    opt_level: str = "O0"
    cast_model_type: Optional[jnp.dtype] = None
    compute_dtype: Optional[jnp.dtype] = None
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: object = 1.0  # float | "dynamic"

    def __post_init__(self):
        self._check({})

    def replace(self, **kw) -> "PrecisionConfig":
        self._check(kw)
        return dataclasses.replace(self, **kw)

    def _check(self, kw) -> None:
        # Consistency checks mirroring Properties.__setattr__ guards
        # (apex/amp/frontend.py:40-100): O1-style per-op casting manages its
        # own casts, so cast_model_type conflicts with compute_dtype != None.
        compute = kw.get("compute_dtype", self.compute_dtype)
        cast_model = kw.get("cast_model_type", self.cast_model_type)
        if compute is not None and cast_model is not None:
            raise ValueError(
                "compute_dtype (O1-style per-op autocast) and cast_model_type "
                "(O2/O3-style whole-model cast) are mutually exclusive"
            )
        ls = kw.get("loss_scale", self.loss_scale)
        if not (ls == "dynamic" or isinstance(ls, (int, float))):
            raise ValueError(f"loss_scale must be a number or 'dynamic', got {ls!r}")


@dataclasses.dataclass(frozen=True)
class TransformerParallelConfig:
    """Megatron-runtime knobs (subset of ``apex/transformer/testing/arguments.py``
    that affects the library rather than the test fixture)."""

    tensor_model_parallel_size: int = 1
    pipeline_model_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: Optional[int] = None
    sequence_parallel_size: int = 1
    micro_batch_size: int = 1
    global_batch_size: int = 1
    params_dtype: jnp.dtype = jnp.float32
    # decompose TP-boundary collectives into ppermute rings overlapped
    # with partial GEMMs (apex_tpu.comm.overlap) — the analogue of the
    # reference DDP's overlap_reductions / the async-allreduce linears;
    # forwarded to GPTConfig.overlap_comm / the *ParallelLinear layers
    overlap_comm: bool = False

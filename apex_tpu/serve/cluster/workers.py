"""Prefill and decode workers — the two halves of a disaggregated host.

Splitting prefill from decode (the DistServe/Splitwise argument, applied
to the PR-5/7/8 engine) exists because the two phases fight each other in
one grid: a long prompt's chunk steps steal iterations from every running
decode (inflating TPOT), while decode-only steps leave the prefill
backlog — and TTFT — to rot. Give each phase its own mesh slice and each
runs its own optimal loop; all that crosses the boundary is one KV-block
payload and a first token per request (``cluster.transfer``).

* :class:`PrefillWorker` — wraps the engine's chunked-prefill machinery
  (the SAME :func:`~apex_tpu.serve.decode.gpt_prefill_chunk` program and
  first-token sampling closure, so cluster streams stay bitwise the
  single-engine ones) around a small **staging** KV pool sized for one
  max-context prompt. FCFS-to-completion, one fixed-size chunk per
  :meth:`PrefillWorker.step`; a finished prompt is packed into a
  :class:`KVHandoff` (blocks + first token + timeline) and its staging
  blocks are freed immediately — the staging pool never holds a request
  longer than its prefill.
* :class:`DecodeWorker` — owns the big paged pool through a full
  :class:`~apex_tpu.serve.engine.InferenceEngine` (speculative decode and
  the megakernel knob ride along untouched) whose prefill path is simply
  never used: :meth:`DecodeWorker.admit` lands transferred blocks into
  freshly allocated pool blocks via the ``insert_blocks`` /
  ``copy_block``-style set, installs the slot exactly as the engine's own
  prefill completion would (same seq_lens/last_token/key bookkeeping),
  and decode steps take it from there. A handoff that does not fit yet
  (no free slot / blocks) waits in the worker's pending queue and is
  retried every step — admission defers, it never deadlocks.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.slo import SloSpec
from apex_tpu.monitor.trace import span
from apex_tpu.resilience.preemption import PreemptionHandler
from apex_tpu.serve.cluster.transfer import (
    insert_blocks,
    pack_blocks,
    payload_crc32,
    payload_nbytes,
    transfer_wire_bytes,
    validate_wire_mode,
)
from apex_tpu.serve.adapters import (
    AdapterRegistry,
    init_adapter_pool,
    write_adapter,
)
from apex_tpu.serve.decode import gpt_prefill_chunk
from apex_tpu.serve.engine import (
    InferenceEngine,
    Request,
    ServeConfig,
)
from apex_tpu.serve.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    init_kv_cache,
)
from apex_tpu.serve.sampling import request_key, sample

Pytree = Any


@dataclasses.dataclass
class KVHandoff:
    """Everything a decode host needs to continue a prefilled request:
    the packed KV payload (host numpy, trimmed to ``n_blocks`` valid
    blocks), the first sampled token, and the request's timeline so far
    (ms on the cluster's one clock — retirement folds these into the
    decode engine's histograms/SLO tracker unchanged).

    The elastic tier ships a second kind over the same wire:
    ``kind="migration"`` carries a LIVE request mid-decode off a dying
    or draining worker — ``seq_len`` context tokens already written
    (``n_blocks`` holds exactly those), the ``generated`` stream so far
    and the ``last_token`` to feed next, so the destination resumes the
    stream bitwise. ``acked_tokens`` is the client-delivered watermark:
    tokens past it are re-emitted on arrival (the ``replay`` event) so a
    mid-flight failure never loses the unacked tail. ``crc32``
    (:func:`~apex_tpu.serve.cluster.transfer.payload_crc32`) guards BOTH
    kinds: a transfer that rots on the wire is detected at delivery and
    re-requested instead of silently diverging the stream."""

    request: Request
    payload: Dict[str, np.ndarray]
    n_blocks: int
    prompt_len: int
    first_token: int
    wire_bytes: int
    t_submit_ms: float
    queue_ms: float
    t_first_ms: float
    ttft_ms: float
    kind: str = "prefill"              # "prefill" | "migration"
    seq_len: Optional[int] = None      # migration: context tokens written
    last_token: Optional[int] = None   # migration: next token to feed
    generated: Optional[List[int]] = None   # migration: stream so far
    acked_tokens: Optional[int] = None      # migration: delivered watermark
    crc32: Optional[int] = None
    # the adapter BINDING travels with the KV blocks (by NAME — pool slot
    # ids are per-worker; the destination re-resolves against its own
    # registry, loading from the cluster catalog first if cold)
    adapter: Optional[str] = None


def _cache_size_of(jitted) -> Optional[int]:
    fn = getattr(jitted, "_cache_size", None)
    return fn() if callable(fn) else None


class PrefillWorker:
    """One prefill host: staging pool + the engine's chunk program.

    ``queue_limit`` bounds accepted-but-unstarted requests (the router
    holds the rest — that is what makes weighted fair queueing and
    TTFT-feasibility shedding observable at the router instead of inside
    an unbounded worker queue)."""

    def __init__(self, params: Pytree, cfg, serve_cfg: ServeConfig, *,
                 base_key=None, wire_mode: str = "raw",
                 events: Optional[EventLog] = None,
                 now_ms: Optional[Callable[[], float]] = None,
                 queue_limit: int = 1, use_pallas: Optional[bool] = None,
                 preemption: Optional[PreemptionHandler] = None,
                 name: str = "prefill0"):
        serve_cfg.validate()
        validate_wire_mode(wire_mode)
        if queue_limit < 0:
            raise ValueError("queue_limit must be >= 0")
        # every worker owns a PreemptionHandler: a real deployment
        # installs it on SIGTERM (install=True in the worker process);
        # the in-process cluster polls the flag each tick and the chaos
        # harness fires trigger() — the same code path either way
        self.preemption = (preemption if preemption is not None
                           else PreemptionHandler(install=False))
        self.cfg = cfg
        self.serve_cfg = serve_cfg
        self.wire_mode = wire_mode
        self.name = name
        self.max_context = serve_cfg.max_context or cfg.max_seq
        bs = serve_cfg.block_size
        self._blocks_per_prompt = -(-self.max_context // bs)
        # staging pool: exactly one max-context prompt (FCFS-to-completion
        # means at most one request is mid-prefill at a time)
        self.kv_cfg = KVCacheConfig(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim, num_blocks=self._blocks_per_prompt,
            block_size=bs, dtype=cfg.dtype,
            quantized=serve_cfg.kv_quant != "none",
            bits=4 if serve_cfg.kv_quant == "int4" else 8,
            group_size=serve_cfg.kv_group)
        self.allocator = BlockAllocator(self._blocks_per_prompt)
        self.cache = init_kv_cache(self.kv_cfg)
        self._base_key = (base_key if base_key is not None
                          else jax.random.PRNGKey(0))
        self._events = events
        self._anchor = time.perf_counter()
        self._now_ms = now_ms or (
            lambda: (time.perf_counter() - self._anchor) * 1e3)
        self.queue_limit = int(queue_limit)
        # (request, t_submit_ms) accepted but not started
        self._queue: collections.deque = collections.deque()
        self._current: Optional[Dict[str, Any]] = None
        self.chunks_run = 0
        self.prefills_done = 0
        self.last_chunk_tokens = 0
        self.last_chunk_ms = 0.0
        kv_cfg, scfg = self.kv_cfg, serve_cfg
        # per-tenant LoRA: the prefill host owns its own paged pool +
        # registry (the prompt's K/V must be written with the SAME adapted
        # projections decode will read — an unadapted prefill would
        # silently corrupt every adapter stream)
        self._lora_pool = None
        self.adapters: Optional[AdapterRegistry] = None
        if scfg.lora_rank > 0:
            self._lora_pool = init_adapter_pool(
                cfg, scfg.lora_rank, scfg.max_adapters)
            self.adapters = AdapterRegistry(scfg.max_adapters)

        if scfg.lora_rank > 0:
            def chunk_prefill(params, cache, lora, tokens, start, n_valid,
                              block_row, key, aid):
                # the engine's LoRA chunk closure verbatim: the pool rides
                # as its own donated leaf set and is returned untouched
                cache, logits = gpt_prefill_chunk(
                    params, tokens, start, n_valid, cache, block_row, cfg,
                    kv_cfg, use_pallas=use_pallas, adapters=lora,
                    adapter_id=aid)
                tok = sample(logits[None], key[None],
                             jnp.reshape(start + n_valid, (1,)),
                             scfg.sampling)
                return cache, lora, tok[0]

            self._chunk_prefill = jax.jit(chunk_prefill,
                                          donate_argnums=(1, 2))
        else:
            def chunk_prefill(params, cache, tokens, start, n_valid,
                              block_row, key):
                # the engine's chunk closure verbatim — same program, same
                # first-token draw, which is why cluster streams are
                # bitwise the single-engine ones
                cache, logits = gpt_prefill_chunk(
                    params, tokens, start, n_valid, cache, block_row, cfg,
                    kv_cfg, use_pallas=use_pallas)
                tok = sample(logits[None], key[None],
                             jnp.reshape(start + n_valid, (1,)),
                             scfg.sampling)
                return cache, tok[0]

            self._chunk_prefill = jax.jit(chunk_prefill,
                                          donate_argnums=(1,))

        def extract(cache, ids):
            return pack_blocks(cache, kv_cfg, ids, wire_mode=wire_mode)

        self.params = params
        self._extract = jax.jit(extract)

    # -- adapter lifecycle -------------------------------------------------
    def load_adapter(self, name: str, weights: Dict[str, Any], *,
                     scale: float = 1.0) -> int:
        """Install a named adapter into this prefill host's paged pool
        (host-side eager write — never traces). The cluster loads the
        whole catalog eagerly into every prefill worker: prompts are
        placed by feasibility, not adapter warmth."""
        if self.adapters is None:
            raise RuntimeError(
                f"{self.name}: adapters are disabled "
                "(ServeConfig.lora_rank == 0)")
        slot = self.adapters.load(name)
        self._lora_pool = write_adapter(self._lora_pool, slot, weights,
                                        scale=scale)
        return slot

    def unload_adapter(self, name: str) -> None:
        if self.adapters is None:
            raise RuntimeError(f"{self.name}: adapters are disabled")
        self.adapters.unload(name)

    # -- capacity / submission --------------------------------------------
    @property
    def can_accept(self) -> bool:
        return len(self._queue) < self.queue_limit or (
            self._current is None and not self._queue)

    def accept(self, request: Request, t_submit_ms: float) -> None:
        if not self.can_accept:
            raise RuntimeError(f"{self.name}: queue full")
        self._queue.append((request, float(t_submit_ms)))

    @property
    def backlog_tokens(self) -> int:
        """Prompt tokens accepted but not yet chunk-prefilled — the
        router's feasibility signal."""
        n = sum(len(r.tokens) for r, _ in self._queue)
        if self._current is not None:
            n += self._current["prompt_len"] - self._current["pos"]
        return n

    @property
    def busy(self) -> bool:
        return self._current is not None or bool(self._queue)

    def compile_counts(self) -> Dict[str, Optional[int]]:
        return {"chunk_prefill": _cache_size_of(self._chunk_prefill),
                "extract": _cache_size_of(self._extract)}

    def scrape(self) -> Dict[str, Any]:
        """FleetScraper target: this host's live series as one registry
        snapshot (``worker=``/``kind="prefill"`` labeled)."""
        from apex_tpu.monitor.registry import MetricsRegistry

        reg = MetricsRegistry()
        t = self._now_ms()
        L = {"worker": self.name, "kind": "prefill"}
        reg.gauge("worker_up", 1.0, t_ms=t, **L)
        reg.gauge("backlog_tokens", float(self.backlog_tokens), t_ms=t,
                  **L)
        reg.counter("prefill_chunks_total", self.chunks_run, **L)
        reg.counter("prefills_done_total", self.prefills_done, **L)
        return reg.snapshot(t)

    # -- drain / failure (the elastic tier) --------------------------------
    def drain_queued(self) -> List:
        """Hand back every accepted-but-unstarted ``(request,
        t_submit_ms)`` — the drain protocol's re-enqueue-at-the-router
        half. The mid-prefill request (if any) is NOT included: a
        draining worker finishes it (cheap, and its staging state is
        useless anywhere else)."""
        out = list(self._queue)
        self._queue.clear()
        return out

    def abort_current(self) -> Optional[Any]:
        """Abandon the mid-prefill request (the KILL path — no grace to
        finish): frees its staging blocks and returns its ``(request,
        t_submit_ms)`` for router re-enqueue, or None when idle. Prefill
        is deterministic, so a restart from scratch on another host
        reproduces the same stream."""
        cur = self._current
        if cur is None:
            return None
        self.allocator.free(cur["blocks"])
        if cur["aid"] and self.adapters is not None:
            self.adapters.release(cur["request"].adapter)
        self._current = None
        return (cur["request"], cur["t_submit_ms"])

    # -- stepping ----------------------------------------------------------
    def _start_next(self) -> None:
        request, t_submit = self._queue.popleft()
        aid = 0
        if request.adapter is not None:
            if self.adapters is None:
                raise RuntimeError(
                    f"{self.name}: {request.uid} is bound to adapter "
                    f"{request.adapter!r} but this prefill host has "
                    "adapters disabled")
            aid = self.adapters.acquire(request.adapter)
            if aid is None:
                # the cluster loads the catalog eagerly into every
                # prefill worker — a miss here is a routing bug, not a
                # recoverable condition
                raise RuntimeError(
                    f"{self.name}: adapter {request.adapter!r} is not "
                    f"resident (catalog load missed this host?)")
        p = len(request.tokens)
        blocks = self.allocator.alloc(self.kv_cfg.blocks_for_tokens(p))
        assert blocks is not None  # staging pool fits any valid prompt
        row = np.zeros((self._blocks_per_prompt,), np.int32)
        row[:len(blocks)] = blocks
        t = self._now_ms()
        if self._events is not None:
            # the request's CURRENT host: every event it emits from here
            # (incl. the cluster's transfer_start, stamped while it
            # still belongs to this host) defaults to this host track
            # until the decode side rebinds — the distributed-tracing
            # contract
            self._events.bind(request.uid, host=self.name)
            self._events.emit("prefill_start", request.uid, t_ms=t,
                              host=self.name, prompt_tokens=p,
                              chunk=self.serve_cfg.prefill_chunk)
        self._current = {
            "request": request, "prompt_len": p, "pos": 0,
            "blocks": blocks, "row": jnp.asarray(row),
            "key": jnp.asarray(
                request_key(self._base_key, request.sampling_seed())),
            "t_submit_ms": t_submit, "queue_ms": t - t_submit,
            "aid": aid,
        }

    def step(self) -> Optional[KVHandoff]:
        """Run one fixed-size chunk of the current prompt (starting the
        next queued request if idle); returns the finished request's
        :class:`KVHandoff` on its final chunk, else None."""
        if self._current is None:
            if not self._queue:
                return None
            self._start_next()
        cur = self._current
        assert cur is not None
        C = self.serve_cfg.prefill_chunk
        c, p = cur["pos"], cur["prompt_len"]
        n_valid = min(C, p - c)
        tokens = np.zeros((C,), np.int32)
        tokens[:n_valid] = np.asarray(
            cur["request"].tokens[c:c + n_valid], np.int32)
        t0 = time.perf_counter()
        with span("prefill"):
            if self._lora_pool is not None:
                self.cache, self._lora_pool, tok = self._chunk_prefill(
                    self.params, self.cache, self._lora_pool,
                    jnp.asarray(tokens), jnp.int32(c), jnp.int32(n_valid),
                    cur["row"], cur["key"], jnp.int32(cur["aid"]))
            else:
                self.cache, tok = self._chunk_prefill(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.int32(c), jnp.int32(n_valid), cur["row"],
                    cur["key"])
            cur["pos"] = c + n_valid
            done = cur["pos"] >= p
            if done:
                first = int(tok)  # fence: TTFT includes the round-trip
            else:
                # fence EVERY chunk before reading the timer — async
                # dispatch would otherwise stamp ~0 ms on non-final
                # chunks and poison the router's ms/token calibration
                jax.block_until_ready(self.cache)
        self.last_chunk_tokens = n_valid
        self.last_chunk_ms = (time.perf_counter() - t0) * 1e3
        self.chunks_run += 1
        if not done:
            return None
        t_first = self._now_ms()
        if self._events is not None:
            self._events.emit("prefill_end", cur["request"].uid,
                              t_ms=t_first, host=self.name)
            self._events.emit("first_token", cur["request"].uid,
                              t_ms=t_first, host=self.name,
                              ttft_ms=round(t_first - cur["t_submit_ms"],
                                            3))
        # pack the written prompt blocks (padded to the fixed extract
        # shape by repeating the first block — insert drops the padding)
        n_blocks = self.kv_cfg.blocks_for_tokens(p)
        ids = np.full((self._blocks_per_prompt,), cur["blocks"][0],
                      np.int32)
        ids[:n_blocks] = cur["blocks"][:n_blocks]
        payload_dev = self._extract(self.cache, jnp.asarray(ids))
        payload = {k: np.asarray(v)[:, :, :n_blocks]
                   for k, v in payload_dev.items()}
        wire = transfer_wire_bytes(self.kv_cfg, n_blocks, self.wire_mode)
        assert payload_nbytes(payload, n_blocks) == wire
        self.allocator.free(cur["blocks"])
        if cur["aid"] and self.adapters is not None:
            self.adapters.release(cur["request"].adapter)
        self._current = None
        self.prefills_done += 1
        return KVHandoff(
            request=cur["request"], payload=payload, n_blocks=n_blocks,
            prompt_len=p, first_token=first, wire_bytes=wire,
            t_submit_ms=cur["t_submit_ms"], queue_ms=cur["queue_ms"],
            t_first_ms=t_first, ttft_ms=t_first - cur["t_submit_ms"],
            crc32=payload_crc32(payload),
            adapter=cur["request"].adapter)


class DecodeWorker:
    """One decode host: a full :class:`InferenceEngine` admitted into via
    KV handoffs instead of prompts. ``serve_cfg`` shapes the engine
    (slots, pool, kv_quant, spec_k, megakernel); the engine's own
    submit/prefill path stays unused."""

    def __init__(self, params: Pytree, cfg, serve_cfg: ServeConfig, *,
                 base_key=None, wire_mode: str = "raw", sink=None,
                 events: Optional[EventLog] = None,
                 slo: Optional[SloSpec] = None,
                 retain_streams: bool = True,
                 on_retire: Optional[Callable[[str, List[int]], None]] = None,
                 use_pallas: Optional[bool] = None,
                 peak_flops_per_s: Optional[float] = None,
                 preemption: Optional[PreemptionHandler] = None,
                 meter=None, meter_worker: Optional[str] = None,
                 name: str = "decode0"):
        validate_wire_mode(wire_mode)
        self.name = name
        self.wire_mode = wire_mode
        self.preemption = (preemption if preemption is not None
                           else PreemptionHandler(install=False))
        self.engine = InferenceEngine(
            params, cfg, serve_cfg, base_key=base_key, sink=sink,
            events=events, slo=slo, retain_streams=retain_streams,
            on_retire=on_retire, use_pallas=use_pallas,
            peak_flops_per_s=peak_flops_per_s,
            # tier-4 metering: the cluster shares ONE ledger across
            # decode hosts; each charge is stamped with this worker's
            # name so per-worker cost rates fall out of the same pool
            meter=meter, meter_worker=meter_worker or name)
        self._events = events
        self._pending: collections.deque = collections.deque()
        self.admitted = 0
        self.migrations_in = 0
        self.migrations_out = 0
        self.replayed_tokens = 0
        kv_cfg = self.engine.kv_cfg

        def insert(cache, payload, dst_ids):
            return insert_blocks(cache, kv_cfg, payload, dst_ids,
                                 wire_mode=wire_mode)

        self._insert = jax.jit(insert, donate_argnums=(0,))

    # -- admission of transferred blocks ----------------------------------
    @property
    def load(self) -> int:
        """Occupied slots + handoffs waiting — the cluster's least-loaded
        placement key."""
        eng = self.engine
        return (sum(s is not None for s in eng._slots) + len(self._pending))

    def admit(self, handoff: KVHandoff) -> None:
        self._pending.append(handoff)

    def compile_counts(self) -> Dict[str, Optional[int]]:
        out = self.engine.compile_counts()
        out["insert"] = _cache_size_of(self._insert)
        return out

    # -- adapter lifecycle (lazy: loaded on first warm-miss placement) -----
    def load_adapter(self, name: str, weights: Dict[str, Any], *,
                     scale: float = 1.0) -> int:
        return self.engine.load_adapter(name, weights, scale=scale)

    def unload_adapter(self, name: str) -> None:
        self.engine.unload_adapter(name)

    def resident_adapters(self) -> List[str]:
        """Adapter names resident in this worker's pool — the membership
        heartbeat advertisement (what the router's warm-preference
        placement reads)."""
        if self.engine.adapters is None:
            return []
        return sorted(self.engine.adapters.resident())

    def scrape(self) -> Dict[str, Any]:
        """FleetScraper target: the engine's series plus this worker's
        handoff/migration counters, one registry snapshot."""
        from apex_tpu.monitor.registry import MetricsRegistry

        reg = MetricsRegistry()
        t = self.engine._now_ms()
        self.engine.collect_registry(reg, worker=self.name, t_ms=t)
        L = {"worker": self.name, "kind": "decode"}
        reg.gauge("handoffs_pending", float(len(self._pending)), t_ms=t,
                  **L)
        reg.counter("handoffs_admitted_total", self.admitted, **L)
        reg.counter("migrations_in_total", self.migrations_in, **L)
        reg.counter("migrations_out_total", self.migrations_out, **L)
        reg.counter("replayed_tokens_total", self.replayed_tokens, **L)
        return reg.snapshot(t)

    def _land_payload(self, h: KVHandoff, blocks: List[int]) -> None:
        """Run the ONE compiled insert: destination ids padded out of
        range (insert drops them), payload zero-padded to the fixed
        shape."""
        eng = self.engine
        nbp = h.n_blocks
        bpp = eng._blocks_per_slot
        dst = np.full((bpp,), eng.kv_cfg.num_blocks, np.int32)
        dst[:nbp] = blocks[:nbp]
        payload = {}
        for k, arr in h.payload.items():
            pad = np.zeros(arr.shape[:2] + (bpp - nbp,) + arr.shape[3:],
                           arr.dtype)
            payload[k] = jnp.asarray(np.concatenate([arr, pad], axis=2))
        eng.cache = self._insert(eng.cache, payload, jnp.asarray(dst))

    def _install(self, h: KVHandoff) -> bool:
        if h.kind == "migration":
            return self._install_migration(h)
        eng = self.engine
        slot = eng._free_slot()
        if slot is None:
            return False
        total = min(h.prompt_len + h.request.max_new_tokens,
                    eng.max_context)
        n_blocks = eng.kv_cfg.blocks_for_tokens(total)
        blocks = eng.allocator.alloc(n_blocks)
        if blocks is None:
            return False
        if self._events is not None:
            # the request now lives HERE: engine-emitted events
            # (decode_chunk, retired) default to this host track
            self._events.bind(h.request.uid, host=self.name)
        self._land_payload(h, blocks)
        # ONE slot-install implementation: the engine's restore_slot is
        # the canonical grid-state writer for handoff admission AND
        # migration — a prefill handoff is just a restore whose stream
        # is one token long
        record = {
            "request": h.request, "blocks": blocks,
            "generated": [h.first_token],
            "history": [int(t) for t in h.request.tokens] + [h.first_token],
            "prompt_len": h.prompt_len, "cached_tokens": 0,
            "seq_len": h.prompt_len, "last_token": h.first_token,
            "t_submit_ms": h.t_submit_ms, "t_first_ms": h.t_first_ms,
            "queue_ms": h.queue_ms, "ttft_ms": h.ttft_ms,
            "adapter": h.adapter,
        }
        slot = eng.restore_slot(record, blocks=blocks)
        eng._tokens_generated += 1  # the first token rode the handoff
        self.admitted += 1
        if self._events is not None:
            self._events.emit("admitted", h.request.uid,
                              t_ms=self.engine._now_ms(), host=self.name,
                              slot=slot, queue_ms=round(h.queue_ms, 3))
        # a 1-token request (or an immediate EOS) retires without ever
        # reaching the decode grid — same as the engine's prefill tail
        state = eng._slots[slot]
        if eng._should_retire(state, h.first_token):
            eng._retire(slot)
        return True

    # -- migration (the elastic tier) --------------------------------------
    def _install_migration(self, h: KVHandoff) -> bool:
        """Land a migrated LIVE request: transferred blocks into fresh
        pool blocks, the slot reinstalled exactly as
        :meth:`~apex_tpu.serve.engine.InferenceEngine.restore_slot`
        would locally, and the unacked tail of the stream re-emitted
        (the ``replay`` event) so the client never loses a token to the
        failure. Bitwise resumption for free: the blocks are the pool
        representation (verbatim for quantized pools), the sampling key
        is request-intrinsic, and every draw is position-keyed."""
        eng = self.engine
        if eng._free_slot() is None:
            return False
        total = min(h.prompt_len + h.request.max_new_tokens,
                    eng.max_context)
        blocks = eng.allocator.alloc(eng.kv_cfg.blocks_for_tokens(total))
        if blocks is None:
            return False
        if self._events is not None:
            # migration landed: rebind the trace's host so the resumed
            # stream's events sit on the NEW host track
            self._events.bind(h.request.uid, host=self.name)
        self._land_payload(h, blocks)
        generated = list(h.generated or [])
        record = {
            "request": h.request, "blocks": blocks,
            "generated": generated,
            "history": [int(t) for t in h.request.tokens] + generated,
            "prompt_len": h.prompt_len, "cached_tokens": 0,
            "seq_len": h.seq_len, "last_token": h.last_token,
            "t_submit_ms": h.t_submit_ms, "t_first_ms": h.t_first_ms,
            "queue_ms": h.queue_ms, "ttft_ms": h.ttft_ms,
            "adapter": h.adapter,
        }
        slot = eng.restore_slot(record, blocks=blocks)
        self.admitted += 1
        self.migrations_in += 1
        acked = (h.acked_tokens if h.acked_tokens is not None
                 else max(0, len(generated) - 1))
        replayed = len(generated) - acked
        self.replayed_tokens += replayed
        if self._events is not None:
            now = eng._now_ms()
            self._events.emit("migrate_end", h.request.uid, t_ms=now,
                              host=self.name, slot=slot,
                              n_blocks=h.n_blocks, seq_len=h.seq_len)
            if replayed > 0:
                self._events.emit("replay", h.request.uid, t_ms=now,
                                  host=self.name, n_tokens=replayed)
            # re-admitted on the new host: the slot-residency track gets
            # the fresh slot; request_spans anchors on the FIRST
            # admitted, so the queued span is untouched
            self._events.emit("admitted", h.request.uid, t_ms=now,
                              host=self.name, slot=slot, migrated=True,
                              queue_ms=round(h.queue_ms, 3))
            self._events.gauge("occupancy", eng.occupancy())
        return True

    def evict_to_handoff(self, uid: str, extract_fn) -> KVHandoff:
        """Evict one live slot and pack it as a ``kind="migration"``
        handoff: the written-context blocks through ``extract_fn`` (the
        cluster's ONE jitted extract program — migration mints no new
        compilations), trimmed, CRC-stamped, blocks freed back to this
        worker's pool. The caller ships it over the same wire a prefill
        handoff takes."""
        eng = self.engine
        rec = eng.evict_slot(uid)
        kv = eng.kv_cfg
        n_blocks = kv.blocks_for_tokens(rec["seq_len"])
        bpp = eng._blocks_per_slot
        ids = np.full((bpp,), rec["blocks"][0], np.int32)
        ids[:n_blocks] = rec["blocks"][:n_blocks]
        payload_dev = extract_fn(eng.cache, jnp.asarray(ids))
        payload = {k: np.asarray(v)[:, :, :n_blocks]
                   for k, v in payload_dev.items()}
        eng.allocator.free(rec["blocks"])
        wire = transfer_wire_bytes(kv, n_blocks, self.wire_mode)
        assert payload_nbytes(payload, n_blocks) == wire
        gen = rec["generated"]
        self.migrations_out += 1
        return KVHandoff(
            request=rec["request"], payload=payload, n_blocks=n_blocks,
            prompt_len=rec["prompt_len"],
            first_token=gen[0] if gen else rec["last_token"],
            wire_bytes=wire, t_submit_ms=rec["t_submit_ms"],
            queue_ms=rec["queue_ms"], t_first_ms=rec["t_first_ms"],
            ttft_ms=rec["ttft_ms"], kind="migration",
            seq_len=rec["seq_len"], last_token=rec["last_token"],
            generated=gen, acked_tokens=max(0, len(gen) - 1),
            crc32=payload_crc32(payload),
            adapter=rec.get("adapter"))

    def live_uids(self) -> List[str]:
        """Requests currently occupying slots (the migration worklist)."""
        return [s.request.uid for s in self.engine._slots if s is not None]

    def drain_pending(self) -> List[KVHandoff]:
        """Hand back every not-yet-installed handoff (re-dispatched to a
        surviving worker by the cluster)."""
        out = list(self._pending)
        self._pending.clear()
        return out

    def try_admit(self) -> int:
        """Install as many pending handoffs as currently fit (in arrival
        order — a blocked head defers the rest so streams stay FCFS)."""
        n = 0
        while self._pending:
            if not self._install(self._pending[0]):
                break
            self._pending.popleft()
            n += 1
        return n

    def step(self) -> bool:
        """Admit what fits, then advance the decode grid one step."""
        admitted = self.try_admit()
        stepped = self.engine.step()
        return stepped or admitted > 0

    @property
    def active(self) -> bool:
        return bool(self.engine._active.any()) or bool(self._pending)

    def stats(self) -> Dict[str, Any]:
        out = self.engine.stats()
        out["host"] = self.name
        out["handoffs_admitted"] = self.admitted
        out["handoffs_pending"] = len(self._pending)
        out["migrations_in"] = self.migrations_in
        out["migrations_out"] = self.migrations_out
        out["replayed_tokens"] = self.replayed_tokens
        return out

"""Deterministic cluster fault injection — the chaos harness the elastic
serving claims are proven against.

``resilience.chaos`` gave the TRAINING recovery paths their failures
(NaN at step k, torn checkpoints, preempt at step k); this module is the
same discipline for the serving cluster. Every fault is step-keyed on
the cluster tick counter — no randomness, no wall time — so a chaos run
is exactly reproducible and its streams can be pinned BITWISE against
the fault-free run:

* :class:`KillWorker` — fail-stop a worker at tick k: immediately dead
  (no drain), its in-flight requests migrate (decode) or re-enqueue at
  the router (prefill). Models a host crash with a reachable HBM / a
  reclaim with a grace window.
* :class:`PreemptWorker` — deliver a preemption at tick k THROUGH the
  worker's :class:`~apex_tpu.resilience.preemption.PreemptionHandler`
  (the exact code path a real SIGTERM takes, minus the kernel): the
  worker drains — prefill finishes or re-enqueues its staged prompts,
  decode proactively migrates — then leaves.
* :class:`StallWorker` — the worker stops making progress (and beating)
  for N ticks: the heartbeat-miss detector (or a per-worker
  :class:`~apex_tpu.resilience.preemption.StallWatchdog`) must notice
  and declare it dead so its requests migrate.
* :class:`DropTransfer` / :class:`StallLink` / :class:`CorruptTransfer`
  — the link faults, injected into the cluster's
  :class:`~apex_tpu.serve.cluster.transfer.SimTransport` at tick k: the
  next ``count`` sends are eaten / delayed ``stall_ms`` / bit-rotted.
  Detection is the receiver's job (CRC + timeout), retry with backoff
  is the cluster's; the stream must still land bitwise.

``ServeCluster(chaos=ClusterChaos([...]))`` consults the plan at the
top of every tick; ``benchmarks/bench_serve_mh.py --chaos`` uses the
same plan objects for the goodput-under-chaos record.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple

__all__ = ["ClusterChaos", "CorruptTransfer", "DropTransfer",
           "KillWorker", "PreemptWorker", "StallLink", "StallWorker"]


@dataclasses.dataclass(frozen=True)
class KillWorker:
    """Fail-stop ``worker`` at cluster tick ``at_step``."""

    at_step: int
    worker: str


@dataclasses.dataclass(frozen=True)
class PreemptWorker:
    """Trigger ``worker``'s PreemptionHandler at tick ``at_step`` (the
    SIGTERM path → drain protocol)."""

    at_step: int
    worker: str


@dataclasses.dataclass(frozen=True)
class StallWorker:
    """``worker`` makes no progress (and sends no heartbeat) for
    ``for_steps`` ticks starting at ``at_step`` (forever when 0) — the
    wedged-host failure the heartbeat/watchdog path must catch."""

    at_step: int
    worker: str
    for_steps: int = 0


@dataclasses.dataclass(frozen=True)
class DropTransfer:
    """The next ``count`` link sends after tick ``at_step`` are eaten."""

    at_step: int
    count: int = 1


@dataclasses.dataclass(frozen=True)
class StallLink:
    """The next ``count`` link sends are delayed ``stall_ms``."""

    at_step: int
    stall_ms: float
    count: int = 1


@dataclasses.dataclass(frozen=True)
class CorruptTransfer:
    """The next ``count`` link sends arrive bit-rotted (CRC must catch
    them)."""

    at_step: int
    count: int = 1


_FAULT_TYPES = (KillWorker, PreemptWorker, StallWorker, DropTransfer,
                StallLink, CorruptTransfer)


class ClusterChaos:
    """An ordered, deterministic fault plan. The cluster calls
    :meth:`apply` once per tick; each fault fires exactly once, at the
    first tick >= its ``at_step``. ``fired`` keeps the (tick, fault)
    ledger for the chaos record."""

    def __init__(self, faults: Sequence[Any]):
        for f in faults:
            if not isinstance(f, _FAULT_TYPES):
                raise TypeError(f"not a cluster fault: {f!r}")
            if f.at_step < 0:
                raise ValueError(f"at_step must be >= 0: {f!r}")
        self._pending: List[Any] = sorted(faults, key=lambda f: f.at_step)
        self.fired: List[Tuple[int, Any]] = []

    @property
    def pending(self) -> int:
        return len(self._pending)

    def apply(self, cluster, step_idx: int) -> List[Any]:
        """Fire every not-yet-fired fault whose ``at_step`` has arrived;
        returns the faults fired this tick."""
        fired_now: List[Any] = []
        while self._pending and self._pending[0].at_step <= step_idx:
            f = self._pending.pop(0)
            self._fire(cluster, f, step_idx)
            self.fired.append((step_idx, f))
            fired_now.append(f)
        return fired_now

    def _fire(self, cluster, f: Any, step_idx: int) -> None:
        if isinstance(f, KillWorker):
            cluster.kill_worker(f.worker)
        elif isinstance(f, PreemptWorker):
            cluster.preempt_worker(f.worker)
        elif isinstance(f, StallWorker):
            if f.for_steps == 0 and (
                    cluster.cluster_cfg.heartbeat_timeout_ms is None
                    and cluster.cluster_cfg.watchdog_timeout_ms is None):
                # a forever-stall is only DETECTABLE by heartbeat or
                # watchdog; with neither armed, the worker's requests
                # would hang forever — fail the configuration loudly
                raise ValueError(
                    "StallWorker(for_steps=0) needs heartbeat_timeout_ms "
                    "or watchdog_timeout_ms set: a wedged worker is only "
                    "detected by those paths")
            cluster.stall_worker(f.worker, f.for_steps)
        elif isinstance(f, DropTransfer):
            if cluster.cluster_cfg.transfer_timeout_ms is None:
                # a drop is only DETECTABLE through the timeout path —
                # injecting one into a cluster that cannot notice would
                # hang the stream forever; fail the configuration loudly
                raise ValueError(
                    "DropTransfer needs ClusterConfig.transfer_timeout_ms "
                    "set: a dropped send is only detected by timeout")
            cluster.transport.inject_fault("drop", count=f.count)
        elif isinstance(f, StallLink):
            cluster.transport.inject_fault("stall", count=f.count,
                                           stall_ms=f.stall_ms)
        elif isinstance(f, CorruptTransfer):
            cluster.transport.inject_fault("corrupt", count=f.count)

    def summary(self) -> List[Dict[str, Any]]:
        """JSON-ready ledger of fired faults (for the bench record)."""
        return [{"step": step, "fault": type(f).__name__,
                 **dataclasses.asdict(f)} for step, f in self.fired]

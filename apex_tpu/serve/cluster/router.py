"""SLO-aware admission router — feasibility, fairness, explicit shedding.

The front door of the disaggregated cluster. Three jobs, in the order a
request meets them:

* **feasibility** (admission control): a request whose TTFT budget cannot
  be met *given the measured prefill backlog* is shed at dispatch instead
  of queued into a guaranteed violation. Prediction reuses the PR-6
  telemetry primitives rather than inventing new ones: the router feeds a
  streaming :class:`~apex_tpu.monitor.hist.Histogram` with measured
  per-token prefill chunk times and predicts
  ``waited + (backlog_tokens + prompt_len) · ms_per_token_p50`` against
  the :class:`~apex_tpu.monitor.slo.SloSpec` ``ttft_ms`` budget. Cold
  start (no measurements yet) admits — the first requests calibrate the
  estimator.
* **per-tenant weighted fair queueing**: each tenant owns a FIFO and a
  virtual-time counter (service in prompt tokens / weight); dispatch
  always serves the non-empty tenant with the least virtual time, so a
  tenant flooding the queue cannot starve the others beyond its weight
  share — under saturation, admitted work converges to the weight ratio
  (``tests/test_serve_cluster.py`` pins it).
* **explicit shedding, never deadlock**: a shed is a *terminal state* — a
  :class:`ShedDecision` with the reason and prediction, a ``shed``
  lifecycle event, and per-tenant counters — not an exception. Overload
  degrades to "fewer requests, each still inside its SLO" (the
  goodput-under-SLO currency) instead of an unbounded queue or the
  engine's pool-exhaustion ``RuntimeError``. Requests too large to EVER
  fit the decode pool shed immediately at submit (``unservable``).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Tuple

from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, HistSpec, Histogram
from apex_tpu.monitor.slo import SloSpec
from apex_tpu.serve.engine import Request

__all__ = ["Router", "RouterConfig", "ShedDecision"]


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Admission policy. ``slo.ttft_ms`` drives the feasibility check
    (None: admit everything); ``tenant_weights`` the WFQ shares (missing
    tenants weigh 1.0); ``shed_headroom`` scales the budget the predictor
    is held to (< 1 sheds earlier, > 1 tolerates predicted overshoot)."""

    slo: SloSpec = dataclasses.field(default_factory=SloSpec)
    tenant_weights: Optional[Mapping[str, float]] = None
    shed_headroom: float = 1.0
    hist_spec: Optional[HistSpec] = None
    # bound on per-tenant state (vtime + counters) the router retains.
    # IDLE tenants (empty queue) beyond the bound are garbage-collected
    # least-recently-seen first — WFQ-safe, because a re-activating
    # tenant restarts at the global virtual clock either way. Without
    # the bound, a tenant whose every request was shed leaves its vtime
    # and counter entries behind forever (millions of one-shot tenants
    # = an unbounded host-side leak). None disables.
    max_tenant_states: Optional[int] = 1024

    def validate(self) -> None:
        self.slo.validate()
        if self.shed_headroom <= 0:
            raise ValueError("shed_headroom must be positive")
        if self.max_tenant_states is not None and self.max_tenant_states < 1:
            raise ValueError("max_tenant_states must be >= 1 when given")
        for t, w in (self.tenant_weights or {}).items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be positive, "
                                 f"got {w}")


@dataclasses.dataclass
class ShedDecision:
    """One shed request — the terminal record the cluster reports and
    events record."""

    request: Request
    reason: str                      # "infeasible" | "unservable"
    predicted_ttft_ms: Optional[float]
    budget_ms: Optional[float]
    t_ms: float


class Router:
    """Per-tenant WFQ + TTFT feasibility in front of the prefill hosts.

    Host side only — no device work. The cluster calls :meth:`submit` on
    arrival, :meth:`observe_chunk` after every measured prefill chunk,
    and :meth:`next_request` whenever a prefill worker can accept."""

    def __init__(self, cfg: Optional[RouterConfig] = None):
        self.cfg = cfg or RouterConfig()
        self.cfg.validate()
        self._queues: Dict[str, collections.deque] = {}
        self._vtime: Dict[str, float] = {}
        # monotone global virtual clock = vtime of the last tenant
        # served; new or re-activating tenants start here, so an idle
        # spell can never be replayed as a burst of catch-up service
        self._vclock = 0.0
        self.prefill_ms_per_token = Histogram(
            self.cfg.hist_spec or DEFAULT_LATENCY_SPEC)
        self.submitted = 0
        self.admitted = 0
        self.shed = 0
        self.tenants: Dict[str, Dict[str, int]] = {}
        # recent shed decisions, bounded like the tenant tables (the
        # cluster keeps its own terminal-state map; this is a debugging
        # window, not the ledger — self.shed is the count)
        self.sheds: collections.deque = collections.deque(
            maxlen=self.cfg.max_tenant_states)
        # tenant-state GC bookkeeping: last time each tenant was seen,
        # plus an aggregate bucket the evicted tenants' counters fold
        # into (top-level submitted/admitted/shed totals never lose
        # requests to eviction)
        self._last_seen: Dict[str, float] = {}
        self.tenants_evicted = 0
        self._evicted_totals = {"submitted": 0, "admitted": 0, "shed": 0}
        self.requeued = 0
        # adapter-aware decode placement (the fleet-mix seed): how often
        # an adapter-bound handoff landed on a worker already holding its
        # adapter vs forced a cold adapter_load. Base (adapter-less)
        # traffic does not touch these.
        self.adapter_warm_dispatches = 0
        self.adapter_cold_dispatches = 0

    # -- accounting --------------------------------------------------------
    def _tenant(self, name: str) -> Dict[str, int]:
        return self.tenants.setdefault(
            name, {"submitted": 0, "admitted": 0, "shed": 0})

    def _gc_tenants(self) -> None:
        """Bound the per-tenant state tables: beyond
        ``cfg.max_tenant_states``, IDLE tenants (no queued requests) are
        evicted least-recently-seen first — their counters fold into the
        aggregate eviction bucket, their vtime is dropped (safe: a
        returning tenant restarts at the global virtual clock, exactly
        like any newly-seen tenant). Tenants with queued work are never
        evicted."""
        limit = self.cfg.max_tenant_states
        if limit is None:
            return
        known = set(self.tenants) | set(self._vtime)
        if len(known) <= limit:
            return
        idle = [t for t in known if not self._queues.get(t)]
        idle.sort(key=lambda t: self._last_seen.get(t, 0.0))
        for t in idle[: len(known) - limit]:
            self._vtime.pop(t, None)
            self._queues.pop(t, None)
            self._last_seen.pop(t, None)
            rec = self.tenants.pop(t, None)
            if rec is not None:
                for k in self._evicted_totals:
                    self._evicted_totals[k] += rec.get(k, 0)
            self.tenants_evicted += 1

    def _weight(self, tenant: str) -> float:
        if self.cfg.tenant_weights is None:
            return 1.0
        return float(self.cfg.tenant_weights.get(tenant, 1.0))

    def observe_chunk(self, tokens: int, ms: float) -> None:
        """Feed one measured prefill chunk (the estimator's only input)."""
        if tokens > 0 and ms >= 0:
            self.prefill_ms_per_token.add([ms / tokens])

    def ms_per_token(self) -> Optional[float]:
        """Median measured prefill ms/token (None until calibrated)."""
        return self.prefill_ms_per_token.quantile(0.5)

    # -- submission --------------------------------------------------------
    def submit(self, request: Request, t_ms: float,
               total_tokens: Optional[int] = None,
               max_servable_tokens: Optional[int] = None
               ) -> Optional[ShedDecision]:
        """Enqueue a request (returns a :class:`ShedDecision` instead when
        it can NEVER be served: its full KV footprint ``total_tokens``
        — prompt + generation budget, context-clamped — exceeds
        ``max_servable_tokens``, the decode pool's hard capacity; the
        engine's deadlock-loud ``RuntimeError`` becomes a terminal shed)."""
        tenant = getattr(request, "tenant", "default")
        self.submitted += 1
        rec = self._tenant(tenant)
        rec["submitted"] += 1
        self._last_seen[tenant] = float(t_ms)
        if (max_servable_tokens is not None and total_tokens is not None
                and total_tokens > max_servable_tokens):
            d = self._shed(request, tenant, "unservable", None, t_ms)
            self._gc_tenants()
            return d
        q = self._queues.setdefault(tenant, collections.deque())
        if not q:
            # tenant is (re-)activating: start at the global virtual
            # clock (never below its own history) so it cannot replay
            # the service it missed while idle — WFQ's standard
            # max(own finish time, system vtime) rule
            self._vtime[tenant] = max(self._vtime.get(tenant, 0.0),
                                      self._vclock)
        q.append((request, float(t_ms)))
        self._gc_tenants()
        return None

    def requeue(self, request: Request, t_submit_ms: float) -> None:
        """Put an already-admitted request BACK at the head of its
        tenant's queue with its original submit time (the drain /
        worker-death path: a prompt staged on a dying prefill host
        re-enters dispatch without double-counting submission, and its
        queue-wait keeps accruing from the true arrival — SLO accounting
        stays honest)."""
        tenant = getattr(request, "tenant", "default")
        q = self._queues.setdefault(tenant, collections.deque())
        q.appendleft((request, float(t_submit_ms)))
        # the earlier dispatch is void (its prefill never finished): undo
        # its admitted counts so submitted == admitted + shed + queued
        # stays an invariant across worker deaths, and REFUND its WFQ
        # vtime charge — re-dispatch will charge again, and without the
        # refund the tenant would pay twice for one request and fall
        # under its weighted share. No vclock floor on the refund: this
        # is a voided dispatch, not a tenant re-activating after idling.
        # A tenant GC-evicted while its request was in flight has no
        # vtime left to refund — it re-activates at the global clock
        # like any newly-seen tenant (no queue jumping), and counters
        # are floored (its history already folded into the eviction
        # bucket).
        if tenant in self._vtime:
            self._vtime[tenant] = max(
                0.0, self._vtime[tenant]
                - len(request.tokens) / self._weight(tenant))
        else:
            self._vtime[tenant] = self._vclock
        self._last_seen[tenant] = max(
            self._last_seen.get(tenant, 0.0), float(t_submit_ms))
        self.admitted = max(0, self.admitted - 1)
        rec = self._tenant(tenant)
        rec["admitted"] = max(0, rec["admitted"] - 1)
        self.requeued += 1

    def shed_submitted(self, request: Request, reason: str,
                       t_ms: float) -> ShedDecision:
        """Terminal shed AT the front door, before the request ever
        queues (the cluster's unknown-adapter path: a tenant bound to an
        adapter nobody has loaded can never be served correctly — shed
        explicitly, with full per-tenant accounting, never served on the
        base model by accident)."""
        tenant = getattr(request, "tenant", "default")
        self.submitted += 1
        self._tenant(tenant)["submitted"] += 1
        self._last_seen[tenant] = float(t_ms)
        d = self._shed(request, tenant, reason, None, t_ms)
        self._gc_tenants()
        return d

    def shed_admitted(self, request: Request, reason: str,
                      t_ms: float) -> ShedDecision:
        """Terminal failure of an ADMITTED request downstream of the
        router (transfer retry ladder ran dry, no decode worker left to
        serve it): move it from the admitted column to the shed column
        so the ledger stays exact — ``submitted == admitted + shed +
        queued`` holds across every failure mode, and ``shed_rate``
        (the regress-gated headline) reflects the loss."""
        tenant = getattr(request, "tenant", "default")
        self.admitted = max(0, self.admitted - 1)
        rec = self._tenant(tenant)
        rec["admitted"] = max(0, rec["admitted"] - 1)
        return self._shed(request, tenant, reason, None, t_ms)

    def shed_queued(self, reason: str, t_ms: float) -> List[ShedDecision]:
        """Shed EVERY queued request (the cluster's fatal-by-config
        path: no decode worker can ever serve them) through the normal
        shed accounting; returns the decisions, queues left empty."""
        out: List[ShedDecision] = []
        for tenant, q in self._queues.items():
            while q:
                request, _ = q.popleft()
                out.append(self._shed(request, tenant, reason, t_ms=t_ms,
                                      predicted=None))
        return out

    def _shed(self, request: Request, tenant: str, reason: str,
              predicted: Optional[float], t_ms: float) -> ShedDecision:
        self.shed += 1
        self._tenant(tenant)["shed"] += 1
        d = ShedDecision(request=request, reason=reason,
                         predicted_ttft_ms=predicted,
                         budget_ms=self.cfg.slo.ttft_ms, t_ms=t_ms)
        self.sheds.append(d)
        return d

    # -- dispatch ----------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def queued_tokens(self) -> int:
        return sum(len(r.tokens) for q in self._queues.values()
                   for r, _ in q)

    def _pick_tenant(self) -> Optional[str]:
        best = None
        for t, q in self._queues.items():
            if not q:
                continue
            key = (self._vtime[t], t)  # name breaks ties deterministically
            if best is None or key < best[0]:
                best = (key, t)
        return best[1] if best else None

    def feasible(self, prompt_len: int, waited_ms: float,
                 backlog_tokens: int) -> Tuple[bool, Optional[float]]:
        """Can this request's first token still make its TTFT budget?
        Returns ``(feasible, predicted_ttft_ms)`` — predicted is None
        when no budget or no calibration constrains the answer."""
        budget = self.cfg.slo.ttft_ms
        if budget is None:
            return True, None
        mpt = self.ms_per_token()
        if mpt is None:
            return True, None  # cold start: calibrate on real traffic
        predicted = waited_ms + (backlog_tokens + prompt_len) * mpt
        return predicted <= budget * self.cfg.shed_headroom, predicted

    def next_request(self, backlog_tokens: int, t_ms: float
                     ) -> Tuple[Optional[Tuple[Request, float]],
                                List[ShedDecision]]:
        """Dispatch the WFQ-next feasible request; infeasible heads shed
        (terminal) and dispatch moves on. Returns ``((request,
        t_submit_ms) | None, sheds_made_now)``."""
        sheds: List[ShedDecision] = []
        while True:
            tenant = self._pick_tenant()
            if tenant is None:
                return None, sheds
            request, t_submit = self._queues[tenant].popleft()
            ok, predicted = self.feasible(
                len(request.tokens), t_ms - t_submit, backlog_tokens)
            if not ok:
                sheds.append(self._shed(request, tenant, "infeasible",
                                        predicted, t_ms))
                continue
            self.admitted += 1
            self._tenant(tenant)["admitted"] += 1
            self._last_seen[tenant] = max(
                self._last_seen.get(tenant, 0.0), float(t_ms))
            self._vtime[tenant] += len(request.tokens) / self._weight(tenant)
            # the served tenant had the MINIMUM vtime, so tracking it
            # keeps the clock monotone
            self._vclock = max(self._vclock, self._vtime[tenant])
            return (request, t_submit), sheds

    # -- adapter-aware decode placement ------------------------------------
    def select_worker(self, candidates: List[Tuple[str, int, Any]],
                      adapter: Optional[str] = None,
                      cost_rates: Optional[Mapping[str, float]] = None
                      ) -> Optional[str]:
        """Pick the decode worker for one handoff over a heterogeneous
        fleet. ``candidates``: ``(name, load, resident_adapters)`` rows
        built from the membership advertisements. An adapter-bound
        handoff prefers the least-loaded ADAPTER-WARM worker (its pool
        already holds the adapter — dispatch costs nothing extra); only
        when no warm worker exists does it fall back to the least-loaded
        cold one, which the cluster then loads explicitly (the
        ``adapter_load`` lifecycle event). Base traffic and the
        no-candidates case keep the classic least-loaded rule.

        ``cost_rates`` (tier 4, opt-in): the membership-advertised
        per-worker cost rates (``WorkerRecord.cost_rate``). When given,
        load ties break toward the CHEAPER worker — the SLO-vs-cost
        placement hook of ROADMAP 5c; omitted, placement is exactly the
        pre-metering least-loaded rule. Returns the chosen name (None
        when ``candidates`` is empty)."""
        cands = list(candidates)
        if not cands:
            return None

        def key(c):
            if cost_rates is None:
                return c[1]
            return (c[1], cost_rates.get(c[0]) or 0.0)

        if adapter is not None:
            warm = [c for c in cands if adapter in (c[2] or ())]
            if warm:
                self.adapter_warm_dispatches += 1
                return min(warm, key=key)[0]
            self.adapter_cold_dispatches += 1
        return min(cands, key=key)[0]

    # -- stats -------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        mpt = self.ms_per_token()
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "shed": self.shed,
            "shed_rate": (round(self.shed / self.submitted, 4)
                          if self.submitted else None),
            "requeued": self.requeued,
            "queue_depth": self.queue_depth,
            "queued_tokens": self.queued_tokens(),
            "prefill_ms_per_token_p50": (round(mpt, 4)
                                         if mpt is not None else None),
            "tenants": {t: dict(v) for t, v in sorted(self.tenants.items())},
            "tenants_evicted": self.tenants_evicted,
            "evicted_totals": dict(self._evicted_totals),
            "adapter_warm_dispatches": self.adapter_warm_dispatches,
            "adapter_cold_dispatches": self.adapter_cold_dispatches,
            "adapter_warm_dispatch_rate": (
                round(self.adapter_warm_dispatches
                      / (self.adapter_warm_dispatches
                         + self.adapter_cold_dispatches), 4)
                if (self.adapter_warm_dispatches
                    + self.adapter_cold_dispatches) else None),
        }

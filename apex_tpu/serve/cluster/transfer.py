"""KV-block transfer between serving hosts — pack, ship, unpack, account.

Disaggregated prefill/decode serving (the ROADMAP item-2 split) moves a
request's cached K/V from the prefill host's staging pool into the decode
host's paged pool exactly once, at the prefill→decode handoff. This module
is that wire:

* **pack/unpack** — :func:`extract_blocks` slices whole pool blocks out of
  a :func:`~apex_tpu.serve.kv_cache.init_kv_cache` pytree (every layer,
  K+V, + the int8 scales when the pool is quantized) and
  :func:`insert_blocks` lands them in the destination pool with the same
  ``.at[].set(mode="drop")`` indexing :func:`~apex_tpu.serve.kv_cache.
  copy_block` uses — padded destination ids route out of bounds and drop,
  so both programs compile ONCE per worker for a fixed padded block count.
* **wire modes** — ``"raw"`` ships the pool representation verbatim; on an
  int8 pool that is ALREADY codes+scales, so the two modes coincide and a
  transferred block lands **bitwise identical** in the decode pool
  (dequant→requant never happens — the property
  ``tests/test_serve_cluster.py`` pins). ``"int8"`` on an fp16/fp32 pool
  quantizes each ``(token, head)`` ``head_dim`` vector through the
  ``comm.quantize`` blockwise codec (codec block = head_dim, the
  ``kv_cache`` int8-pool layout) before shipping — ~3.6× fewer wire bytes
  at fp32, within the codec's proven round-trip tolerance.
* **accounting** — :func:`transfer_wire_bytes` models bytes-on-wire per
  handoff with the ``comm.accounting`` convention (whole transfers priced
  from shapes, scale overhead amortized per element exactly like
  ``kv_cache._elem_bytes``); the packed payload's measured ``nbytes``
  agrees with the model to the byte, and ``benchmarks/bench_serve_mh.py``
  asserts that agreement into its record.
* **transports** — :class:`SimTransport` is the host-simulated in-process
  link (modeled latency = fixed + bytes/bandwidth against the cluster's
  one monotonic clock) that lets the whole multi-"host" cluster run on a
  single CPU/chip for tests and rehearsals. :func:`ppermute_blocks` is the
  real-mesh hop for when prefill and decode live on different slices of
  one ICI ring: a ``lax.ppermute`` over the payload pytree, the same
  primitive ``comm.overlap`` builds its decomposed rings from — decode
  compute the scheduler can slide into the permute window hides the hop,
  and its wire cost is exactly :func:`transfer_wire_bytes`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.serve.kv_cache import KVCacheConfig

Pytree = Any

WIRE_MODES = ("raw", "int8")

# payload leaves per wire format (scales present iff codes ship)
_POOL_KEYS = ("k", "v")
_SCALE_KEYS = ("k_scale", "v_scale")


def validate_wire_mode(wire_mode: str) -> None:
    if wire_mode not in WIRE_MODES:
        raise ValueError(
            f"wire_mode must be one of {WIRE_MODES}, got {wire_mode!r}")


def payload_is_quantized(cfg: KVCacheConfig, wire_mode: str) -> bool:
    """Whether the wire carries int8 codes + fp32 scales. True for an int8
    pool under EITHER mode (the pool representation IS the wire format —
    shipping it raw is already quantized) and for ``wire_mode="int8"`` on
    a float pool."""
    validate_wire_mode(wire_mode)
    return cfg.quantized or wire_mode == "int8"


# ---------------------------------------------------------------------------
# Wire-byte model — the comm.accounting convention: whole transfers priced
# from static shapes, one number the measured payload must agree with.


def transfer_wire_bytes(cfg: KVCacheConfig, n_blocks: int,
                        wire_mode: str = "raw") -> int:
    """Modeled bytes-on-wire to hand off ``n_blocks`` pool blocks (all
    layers, K+V, scales included when the wire is quantized). Matches the
    packed payload's ``nbytes`` exactly: a quantized POOL ships its own
    representation (the ``kv_cache._elem_bytes`` amortization — int8
    codes + fp32 per-vector scales at ``1 + 4/head_dim`` B/element, int4
    nibble pairs + bf16 group scales at ``0.5 + 2/group`` — half the int8
    wire again); ``wire_mode="int8"`` on a float pool is the codec-side
    int8 layout; a raw float wire is the pool dtype's itemsize."""
    from apex_tpu.serve.kv_cache import _elem_bytes

    validate_wire_mode(wire_mode)
    elems = (cfg.num_layers * cfg.num_heads * n_blocks * cfg.block_size
             * cfg.head_dim)
    if cfg.quantized:
        return int(round(2 * elems * _elem_bytes(cfg)))
    if payload_is_quantized(cfg, wire_mode):
        vectors = elems // cfg.head_dim
        return 2 * (elems + 4 * vectors)
    return 2 * elems * int(jnp.dtype(cfg.dtype).itemsize)


# ---------------------------------------------------------------------------
# Pack / unpack — device-side block slicing. Both take a PADDED id vector
# of fixed length so each worker compiles exactly one extract and one
# insert program: extract pads by repeating a live block (junk content the
# insert drops), insert pads with an out-of-range id (mode="drop").


def extract_blocks(cache: Dict[str, jnp.ndarray],
                   ids: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Slice blocks ``ids`` ((nb_pad,) int32) out of every pool leaf:
    ``(L, H, B, bs[, D])`` → ``(L, H, nb_pad, bs[, D])``."""
    return {name: arr[:, :, ids] for name, arr in cache.items()}


def _quantize_payload(payload: Dict[str, jnp.ndarray]
                      ) -> Dict[str, jnp.ndarray]:
    """Float block payload → int8 codes + fp32 scales per (L, H, block,
    token) head_dim vector — the exact ``kv_cache._quant_rows`` codec, so
    an int8 wire on a float pool shares the int8 pool's layout and error
    bounds."""
    from apex_tpu.serve.kv_cache import _quant_rows

    out = {}
    for name in _POOL_KEYS:
        q, s = _quant_rows(payload[name])
        out[name] = q
        out[name + "_scale"] = s
    return out


def _dequantize_payload(payload: Dict[str, jnp.ndarray],
                        dtype) -> Dict[str, jnp.ndarray]:
    from apex_tpu.serve.kv_cache import _dequant_rows

    return {name: _dequant_rows(payload[name], payload[name + "_scale"],
                                dtype)
            for name in _POOL_KEYS}


def pack_blocks(cache: Dict[str, jnp.ndarray], cfg: KVCacheConfig,
                ids: jnp.ndarray, wire_mode: str = "raw"
                ) -> Dict[str, jnp.ndarray]:
    """Extract blocks ``ids`` and encode them for the wire. An int8 pool
    ships its codes+scales verbatim under BOTH modes (no dequant-requant);
    a float pool ships raw arrays or codec-quantized codes+scales."""
    validate_wire_mode(wire_mode)
    payload = extract_blocks(cache, ids)
    if cfg.quantized or wire_mode == "raw":
        return payload
    return _quantize_payload(payload)


def insert_blocks(cache: Dict[str, jnp.ndarray], cfg: KVCacheConfig,
                  payload: Dict[str, jnp.ndarray], dst_ids: jnp.ndarray,
                  wire_mode: str = "raw") -> Dict[str, jnp.ndarray]:
    """Land a packed payload at pool blocks ``dst_ids`` ((nb_pad,) int32;
    out-of-range entries drop — the padding convention). The indexing is
    :func:`~apex_tpu.serve.kv_cache.copy_block`'s ``.at[:, :, dst]`` set,
    one whole block per id across every leaf."""
    validate_wire_mode(wire_mode)
    if not cfg.quantized and wire_mode == "int8":
        payload = _dequantize_payload(payload, cfg.dtype)
    out = dict(cache)
    for name, arr in cache.items():
        out[name] = arr.at[:, :, dst_ids].set(
            payload[name].astype(arr.dtype), mode="drop")
    return out


def payload_nbytes(payload: Dict[str, Any], n_blocks: int) -> int:
    """Measured wire bytes of a (host-side) payload trimmed to its
    ``n_blocks`` valid blocks — the number that must agree with
    :func:`transfer_wire_bytes`."""
    total = 0
    for arr in payload.values():
        a = np.asarray(arr)
        total += a[:, :, :n_blocks].nbytes
    return total


def payload_crc32(payload: Dict[str, Any]) -> int:
    """crc32 over a packed payload's bytes (leaves in sorted-name order)
    — stamped on every :class:`~apex_tpu.serve.cluster.workers.KVHandoff`
    at pack time and re-checked at delivery, so a corrupted transfer is
    DETECTED and re-requested instead of silently diverging the stream
    (the ``resilience.checkpoint`` per-leaf-crc discipline applied to
    the wire)."""
    import zlib

    crc = 0
    for name in sorted(payload):
        a = np.ascontiguousarray(np.asarray(payload[name]))
        crc = zlib.crc32(a.tobytes(), crc)
    return crc


def corrupt_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Bit-rot a COPY of a payload (first leaf, middle bytes XOR-flipped
    — the ``resilience.chaos.corrupt_file`` "flip" mode applied to a
    wire payload). The original is untouched: the sender's retry copy
    must survive the corruption of the bytes on the wire."""
    out = {k: np.array(np.asarray(v), copy=True)
           for k, v in payload.items()}
    name = sorted(out)[0]
    flat = out[name].reshape(-1).view(np.uint8)
    off = flat.size // 2
    n = min(64, flat.size - off)
    flat[off:off + n] ^= 0xFF
    return out


# ---------------------------------------------------------------------------
# Real-mesh hop: the ppermute primitive the decomposed comm.overlap rings
# are built from, applied to a whole block payload. Runs inside a
# shard_map/mesh program whose axis spans the prefill+decode slices; the
# scheduler overlaps decode-side compute with the permute window exactly
# as accounting.overlap_report proves for the collective matmuls.


def ppermute_blocks(payload: Pytree, axis_name: str,
                    perm: Sequence[Tuple[int, int]]) -> Pytree:
    """One ICI hop of the payload pytree: ``lax.ppermute`` every leaf over
    ``perm`` (``[(src, dst), ...]``). Wire cost per hop =
    :func:`transfer_wire_bytes` of the payload's blocks."""
    return jax.tree_util.tree_map(
        lambda x: lax.ppermute(x, axis_name, perm), payload)


# ---------------------------------------------------------------------------
# Host-simulated transport — the in-process link that runs the whole
# multi-"host" cluster on one CPU/chip. Deterministic: delivery time is
# send time + a modeled latency (fixed + bytes/bandwidth), measured on the
# cluster's one monotonic clock.


@dataclasses.dataclass
class Delivery:
    """One in-flight handoff: the opaque item plus its wire accounting.
    ``corrupted`` marks a fault-injected delivery whose payload bytes
    must be treated as rotted at the receiver (the receiver's CRC check
    is what must catch it); ``dropped`` marks a send the link ate."""

    item: Any
    wire_bytes: int
    t_send_ms: float
    t_deliver_ms: float
    corrupted: bool = False
    dropped: bool = False

    @property
    def transfer_ms(self) -> float:
        return self.t_deliver_ms - self.t_send_ms


# deterministic link fault modes (serve.cluster.chaos injects these):
# drop — the send never arrives; stall — delivery delayed stall_ms;
# corrupt — the payload bytes rot on the wire (CRC must catch it)
FAULT_MODES = ("drop", "stall", "corrupt")


class SimTransport:
    """In-process prefill→decode link with modeled latency.

    ``fixed_ms`` is the per-transfer setup cost; ``gib_per_s`` the modeled
    link bandwidth (0 disables the byte term — instant delivery, the
    deterministic test default). Totals (``wire_bytes_total``,
    ``transfer_ms_total``, ``transfers_total``) feed the cluster's
    transfer telemetry.

    **Fault injection** (the chaos harness's link half):
    :meth:`inject_fault` queues deterministic faults consumed by the
    NEXT sends, in order — ``drop`` (the delivery never happens),
    ``stall`` (delivery delayed ``stall_ms``) and ``corrupt`` (delivery
    arrives flagged ``corrupted`` — the receiver's CRC validation, not
    the transport, is what must notice). Fault counters
    (``drops_total`` / ``stalls_total`` / ``corrupts_total``) feed the
    chaos record."""

    def __init__(self, fixed_ms: float = 0.0, gib_per_s: float = 0.0):
        if fixed_ms < 0 or gib_per_s < 0:
            raise ValueError("fixed_ms and gib_per_s must be >= 0")
        self.fixed_ms = float(fixed_ms)
        self.gib_per_s = float(gib_per_s)
        self._inflight: List[Delivery] = []
        self._faults: List[Tuple[str, float]] = []
        self.wire_bytes_total = 0
        self.transfer_ms_total = 0.0
        self.transfers_total = 0
        self.drops_total = 0
        self.stalls_total = 0
        self.corrupts_total = 0

    def inject_fault(self, mode: str, count: int = 1,
                     stall_ms: float = 0.0) -> None:
        """Queue ``count`` link faults for the next sends (FIFO)."""
        if mode not in FAULT_MODES:
            raise ValueError(
                f"fault mode must be one of {FAULT_MODES}, got {mode!r}")
        if count < 1:
            raise ValueError("count must be >= 1")
        if mode == "stall" and stall_ms <= 0:
            raise ValueError("stall fault needs stall_ms > 0")
        self._faults.extend([(mode, float(stall_ms))] * int(count))

    @property
    def pending_faults(self) -> int:
        return len(self._faults)

    def modeled_ms(self, wire_bytes: int) -> float:
        ms = self.fixed_ms
        if self.gib_per_s > 0:
            ms += wire_bytes / (self.gib_per_s * (1 << 30)) * 1e3
        return ms

    def send(self, item: Any, wire_bytes: int, t_ms: float) -> Delivery:
        d = Delivery(item=item, wire_bytes=int(wire_bytes),
                     t_send_ms=float(t_ms),
                     t_deliver_ms=float(t_ms) + self.modeled_ms(wire_bytes))
        if self._faults:
            mode, stall_ms = self._faults.pop(0)
            if mode == "drop":
                # the bytes transited the wire, but no transfer
                # completed: count the bytes and the drop, not a
                # delivery — transfers_total must not overstate link
                # health under the exact chaos plans the gate compares
                d.dropped = True
                self.drops_total += 1
                self.wire_bytes_total += d.wire_bytes
                return d  # eaten: never enters the in-flight set
            if mode == "stall":
                d.t_deliver_ms += stall_ms
                self.stalls_total += 1
            elif mode == "corrupt":
                d.corrupted = True
                self.corrupts_total += 1
        # totals AFTER fault application: a stalled delivery's extra
        # latency belongs in transfer_ms_total (it agrees with the
        # per-delivery transfer_ms the receiver histograms)
        self.wire_bytes_total += d.wire_bytes
        self.transfer_ms_total += d.transfer_ms
        self.transfers_total += 1
        self._inflight.append(d)
        return d

    def poll(self, t_ms: float) -> List[Delivery]:
        """Deliveries whose modeled arrival time has passed, in send
        order."""
        ready = [d for d in self._inflight if d.t_deliver_ms <= t_ms]
        if ready:
            self._inflight = [d for d in self._inflight
                              if d.t_deliver_ms > t_ms]
        return ready

    @property
    def in_flight(self) -> int:
        return len(self._inflight)

"""Elastic cluster membership — who is dispatchable, right now.

Production slices lose hosts mid-request (spot reclaims, maintenance
events, link flaps, wedged runtimes); a serving cluster whose dispatch
set is fixed at construction turns every one of those into an outage.
This module makes the dispatch set a runtime quantity:

* :class:`ClusterMembership` — one record per worker with a three-state
  health ladder (``alive → draining → dead``). *Alive* workers receive
  new work; *draining* workers finish (prefill) or proactively migrate
  (decode) what they hold but receive nothing new; *dead* workers are
  out of the dispatch set and their in-flight requests are migrated by
  the cluster. Every transition stamps the cluster's ONE shared
  :class:`~apex_tpu.monitor.events.EventLog` clock and emits the
  ``worker_join`` / ``worker_leave`` lifecycle events, so membership
  churn lines up with request lifecycles in the same JSONL stream and
  Chrome trace.
* **heartbeat-miss detection** — each worker that makes progress beats
  (:meth:`ClusterMembership.beat`); :meth:`check_heartbeats` declares
  workers whose last beat is older than ``heartbeat_timeout_ms`` dead
  (reason ``"heartbeat"``). Deterministic under a manual clock — the
  chaos tests stall a worker and watch it get declared dead at exactly
  the configured timeout, no wall time involved.
* :class:`AutoscalePolicy` — scale decisions driven by the PR-6 gauges
  the cluster already exports (router queue depth = backlog, decode
  occupancy): sustained backlog at high occupancy asks for a join,
  sustained idleness asks for a drain, both rate-limited by
  ``cooldown_ms`` on the same shared clock. The policy only *decides*;
  :class:`~apex_tpu.serve.cluster.cluster.ServeCluster` acts (spawning
  a :class:`~apex_tpu.serve.cluster.workers.DecodeWorker` or draining
  the least-loaded one).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from apex_tpu.monitor.events import EventLog

__all__ = ["ALIVE", "DRAINING", "DEAD", "AutoscalePolicy",
           "ClusterMembership", "WorkerRecord"]

ALIVE = "alive"
DRAINING = "draining"
DEAD = "dead"
_STATES = (ALIVE, DRAINING, DEAD)


@dataclasses.dataclass
class WorkerRecord:
    """One worker's membership state. ``reason`` records why it left
    (``"preempted"`` / ``"killed"`` / ``"heartbeat"`` / ``"stall"`` /
    ``"scale_down"`` / ``"drained"``).

    ``adapters`` / ``quant`` are the heterogeneous-fleet ADVERTISEMENT:
    each beat refreshes the worker's resident LoRA adapter set and its
    KV quant mode, so the router's adapter-warm placement and any
    fleet-mix policy read membership state instead of poking workers —
    the gossip half of item 5c."""

    name: str
    kind: str                      # "prefill" | "decode"
    state: str = ALIVE
    joined_ms: float = 0.0
    last_beat_ms: float = 0.0
    left_ms: Optional[float] = None
    reason: Optional[str] = None
    adapters: tuple = ()           # resident adapter names, sorted
    quant: str = "none"            # the worker's kv_quant mode
    # tier-4 metering advertisement: the worker's recent cost accrual
    # rate (CostModel units/second) — the routing-signal half of the
    # heartbeat (ROADMAP 5c): a fleet-mix policy can weigh "cheap" vs
    # "expensive" hosts from membership state alone. None until the
    # worker's meter has accrued anything.
    cost_rate: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """When to grow or shrink the decode set. A join is asked for when
    the router backlog exceeds ``scale_up_queue_depth`` AND decode
    occupancy exceeds ``scale_up_occupancy`` (backlog alone could be a
    prefill bottleneck — adding decode hosts would not help); a drain is
    asked for when the queue is empty and occupancy sits below
    ``scale_down_occupancy``. ``cooldown_ms`` rate-limits decisions on
    the shared clock; ``min_decode`` / ``max_decode`` bound the fleet."""

    scale_up_queue_depth: int = 8
    scale_up_occupancy: float = 0.75
    scale_down_occupancy: float = 0.15
    min_decode: int = 1
    max_decode: int = 8
    cooldown_ms: float = 1000.0

    def validate(self) -> None:
        if self.min_decode < 1:
            raise ValueError("min_decode must be >= 1")
        if self.max_decode < self.min_decode:
            raise ValueError("max_decode must be >= min_decode")
        if not (0.0 <= self.scale_down_occupancy
                < self.scale_up_occupancy <= 1.0):
            raise ValueError(
                "need 0 <= scale_down_occupancy < scale_up_occupancy <= 1")
        if self.scale_up_queue_depth < 1:
            raise ValueError("scale_up_queue_depth must be >= 1")
        if self.cooldown_ms < 0:
            raise ValueError("cooldown_ms must be >= 0")


class ClusterMembership:
    """The cluster's health ledger: join/beat/drain/dead transitions,
    heartbeat-miss detection, and the autoscale decision — all on the
    one shared clock, all evented."""

    def __init__(self, heartbeat_timeout_ms: Optional[float] = None,
                 events: Optional[EventLog] = None,
                 autoscale: Optional[AutoscalePolicy] = None):
        if heartbeat_timeout_ms is not None and heartbeat_timeout_ms <= 0:
            raise ValueError("heartbeat_timeout_ms must be > 0 when given")
        if autoscale is not None:
            autoscale.validate()
        self.heartbeat_timeout_ms = heartbeat_timeout_ms
        self.autoscale_policy = autoscale
        self._events = events
        self._workers: Dict[str, WorkerRecord] = {}
        self.joins = 0
        self.leaves = 0
        self.worker_deaths = 0        # dead for a non-voluntary reason
        self.heartbeat_misses = 0
        self.autoscale_ups = 0
        self.autoscale_downs = 0
        self._last_scale_ms: Optional[float] = None

    # -- transitions -------------------------------------------------------
    def join(self, name: str, kind: str, t_ms: float) -> WorkerRecord:
        if name in self._workers and self._workers[name].state != DEAD:
            raise ValueError(f"worker {name!r} already joined")
        rec = WorkerRecord(name=name, kind=kind, joined_ms=float(t_ms),
                           last_beat_ms=float(t_ms))
        self._workers[name] = rec
        self.joins += 1
        if self._events is not None:
            self._events.emit("worker_join", t_ms=t_ms, worker=name,
                              worker_kind=kind)
        return rec

    def beat(self, name: str, t_ms: float,
             adapters: Optional[List[str]] = None,
             quant: Optional[str] = None,
             cost_rate: Optional[float] = None) -> None:
        """Record liveness (and, when given, refresh the worker's
        advertisement: resident adapter set + quant mode + cost rate)."""
        rec = self._workers[name]
        if rec.state != DEAD:
            rec.last_beat_ms = float(t_ms)
            if adapters is not None:
                rec.adapters = tuple(sorted(adapters))
            if quant is not None:
                rec.quant = quant
            if cost_rate is not None:
                rec.cost_rate = float(cost_rate)

    def mark_draining(self, name: str, t_ms: float, reason: str) -> bool:
        """alive → draining (idempotent; False if already leaving)."""
        rec = self._workers[name]
        if rec.state != ALIVE:
            return False
        rec.state = DRAINING
        rec.reason = reason
        return True

    def mark_dead(self, name: str, t_ms: float, reason: str) -> bool:
        """→ dead: out of the dispatch set, ``worker_leave`` emitted.
        ``reason`` ``"drained"``/``"scale_down"``/``"preempted"`` is a
        voluntary exit (the drain protocol ran — nothing was lost);
        anything else counts as a death."""
        rec = self._workers[name]
        if rec.state == DEAD:
            return False
        rec.state = DEAD
        rec.left_ms = float(t_ms)
        rec.reason = reason
        self.leaves += 1
        if reason not in ("drained", "scale_down", "preempted"):
            self.worker_deaths += 1
        if self._events is not None:
            self._events.emit("worker_leave", t_ms=t_ms, worker=name,
                              worker_kind=rec.kind, reason=reason)
        return True

    # -- queries -----------------------------------------------------------
    def state(self, name: str) -> str:
        return self._workers[name].state

    def record(self, name: str) -> WorkerRecord:
        return self._workers[name]

    def is_dispatchable(self, name: str) -> bool:
        """Only ALIVE workers receive new work."""
        return self._workers[name].state == ALIVE

    def names(self, kind: Optional[str] = None,
              state: Optional[str] = None) -> List[str]:
        return [n for n, r in self._workers.items()
                if (kind is None or r.kind == kind)
                and (state is None or r.state == state)]

    # -- failure detection -------------------------------------------------
    def check_heartbeats(self, t_ms: float,
                         beat_floor_ms: Optional[float] = None
                         ) -> List[str]:
        """Declare workers dead whose last beat is older than the
        timeout; returns the newly-dead names (the cluster migrates
        their requests). No-op when detection is off.

        ``beat_floor_ms`` guards against the self-inflicted outage a
        wall clock invites: a single SLOW tick (a fresh worker's first
        compile, one long prefill chunk) would otherwise age EVERY
        worker's beat past the timeout at once and the detector would
        kill the whole healthy fleet. The cluster passes the previous
        tick's start time — a worker that beat during that tick had its
        chance and took it, so only workers that actually MISSED a full
        tick opportunity (chaos-stalled, wedged) are eligible, no
        matter how much wall time one tick burned."""
        if self.heartbeat_timeout_ms is None:
            return []
        newly_dead = []
        for name, rec in self._workers.items():
            if rec.state == DEAD:
                continue
            if (beat_floor_ms is not None
                    and rec.last_beat_ms >= beat_floor_ms):
                continue
            if t_ms - rec.last_beat_ms >= self.heartbeat_timeout_ms:
                self.heartbeat_misses += 1
                self.mark_dead(name, t_ms, "heartbeat")
                newly_dead.append(name)
        return newly_dead

    # -- autoscale ---------------------------------------------------------
    def approve_scale(self, direction: str, t_ms: float) -> bool:
        """Gate one scale action: cooldown + fleet bounds + the
        counters. The THRESHOLD half of autoscaling now lives in the
        alert-rules engine (``scale_up``/``scale_down`` rules evaluated
        over scraped series — see ``ServeCluster``); this method is the
        actuation gate an active alert must still pass, so rate
        limiting and min/max fleet size stay enforced in one place no
        matter who asks."""
        pol = self.autoscale_policy
        if pol is None or direction not in ("up", "down"):
            return False
        if (self._last_scale_ms is not None
                and t_ms - self._last_scale_ms < pol.cooldown_ms):
            return False
        n_alive = len(self.names(kind="decode", state=ALIVE))
        if direction == "up" and n_alive < pol.max_decode:
            self._last_scale_ms = float(t_ms)
            self.autoscale_ups += 1
            return True
        if direction == "down" and n_alive > pol.min_decode:
            self._last_scale_ms = float(t_ms)
            self.autoscale_downs += 1
            return True
        return False

    def autoscale_decision(self, queue_depth: int, occupancy: float,
                           t_ms: float) -> Optional[str]:
        """COMPAT: ``"up"`` / ``"down"`` / None straight off the gauge
        values (threshold + cooldown + bounds in one call). The cluster
        no longer calls this — its thresholds are alert rules and only
        :meth:`approve_scale` runs here — but external callers sizing a
        fleet off raw gauges keep working."""
        pol = self.autoscale_policy
        if pol is None:
            return None
        if (queue_depth >= pol.scale_up_queue_depth
                and occupancy >= pol.scale_up_occupancy
                and self.approve_scale("up", t_ms)):
            return "up"
        if (queue_depth == 0 and occupancy <= pol.scale_down_occupancy
                and self.approve_scale("down", t_ms)):
            return "down"
        return None

    # -- reporting ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        by_state = {s: 0 for s in _STATES}
        for r in self._workers.values():
            by_state[r.state] += 1
        return {
            # timestamp keys deliberately avoid the "_ms" suffix: these
            # are clock POSITIONS, not latencies — monitor.regress would
            # otherwise gate them lower-is-better and flag every fresh
            # run as a regression
            "workers": {
                n: {"kind": r.kind, "state": r.state,
                    "reason": r.reason,
                    "joined_at": round(r.joined_ms, 3),
                    "last_beat_at": round(r.last_beat_ms, 3),
                    "left_at": (round(r.left_ms, 3)
                                if r.left_ms is not None else None),
                    "adapters": list(r.adapters),
                    "quant": r.quant,
                    # deliberately NOT "_cost_rate_ms" or similar — the
                    # rate is load-dependent, so regress must not gate it
                    "cost_rate": (round(r.cost_rate, 6)
                                  if r.cost_rate is not None else None)}
                for n, r in sorted(self._workers.items())},
            "alive": by_state[ALIVE],
            "draining": by_state[DRAINING],
            "dead": by_state[DEAD],
            "joins": self.joins,
            "leaves": self.leaves,
            "worker_deaths": self.worker_deaths,
            "heartbeat_misses": self.heartbeat_misses,
            "autoscale_ups": self.autoscale_ups,
            "autoscale_downs": self.autoscale_downs,
        }
